"""Host-RAM KV tier + wire framing for cross-replica prefix migration.

Rung two and rung three of the KV tiering ladder (rung one — quantized
resident pages — lives in models/llama.py / ops/):

  * :class:`HostKVTier` — a byte-capped LRU of *spilled* prefix-cache
    entries.  When the device pool is pressured the engine demotes the
    prefix cache's LRU victim here (page rows fetched to pinned host
    numpy) instead of dropping it; the next prompt that would have hit
    the victim rehydrates the rows with one async ``device_put``-style
    scatter instead of re-prefilling.  Losing an entry (host-cap
    eviction, supervisor losing the buffer) is always safe: the engine
    falls back to the supervisor's tokens-to-prompt replay machinery,
    i.e. a plain prefix-cache miss.

  * Blob framing — ``pack_prefix_blob`` / ``unpack_prefix_blob`` frame a
    prefix's page rows for the fleet tier's page-fetch endpoint
    (fleet/router.py migration, monitor/server.py ``/api/v1/kv``).  The
    record format deliberately mirrors the WAL (resilience/journal.py):

      blob    := magic(4) record*
      record  := type(u8) length(u32 LE) crc(u32 LE) payload
      crc     := crc32(type_byte + payload)

    META (JSON) carries the geometry contract — model name, layer
    count, fused lane width, block size, kv_quant mode, token ids — and
    ARRAY records carry raw row bytes, one per (layer, k/v/scale) leaf
    in a fixed order.  A receiver whose META doesn't match its own
    geometry refuses the install (``incompatible``) rather than
    installing garbage pages; any CRC/truncation raises
    :class:`BlobError`.

Head-sharded pools need no special casing here: the engine fetches rows
with ``np.asarray(pages.k[li][blocks])`` which gathers the *global*
fused-lane row regardless of how the mesh splits it (page ids are
global — serving/kv_cache.py module docstring), and installs write back
through a sharded-donated scatter that GSPMD re-splits.  Per-shard
byte accounting is ``page_slice_bytes(..., tp, scale_bytes)``.

Stdlib + numpy only; no JAX imports (the supervisor constructs the tier
before any engine exists and keeps it across rebuilds).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import struct
import threading
import zlib
from typing import Iterable

import numpy as np

from k8s_llm_monitor_tpu.devtools.lockcheck import guarded_by, make_lock
from k8s_llm_monitor_tpu.resilience.tenancy import DEFAULT_TENANT

logger = logging.getLogger("serving.kv_tier")

#: Wire magic for migration blobs ("KV eXchange v1").
MAGIC = b"KVX1"
REC_META = 1
REC_ARRAY = 2

_HEADER = struct.Struct("<BII")  # type, payload length, crc32
# Largest legitimate ARRAY payload: a long prefix's rows for one leaf.
_MAX_PAYLOAD = 1 << 30

#: Blob geometry-contract version; bump on any layout change.
BLOB_VERSION = 1


class BlobError(Exception):
    """Migration blob failed framing/CRC/geometry validation."""


def pack_records(records: Iterable[tuple[int, bytes]]) -> bytes:
    """Frame ``(type, payload)`` records with the WAL header + CRC."""
    out = [MAGIC]
    for rtype, payload in records:
        crc = zlib.crc32(bytes((rtype,)) + payload) & 0xFFFFFFFF
        out.append(_HEADER.pack(rtype, len(payload), crc))
        out.append(payload)
    return b"".join(out)


def unpack_records(blob: bytes) -> list[tuple[int, bytes]]:
    """Parse and CRC-check a framed blob.  Unlike the WAL scanner this
    RAISES on any damage — a torn journal tail is expected after a
    crash, but a torn migration blob means the transfer failed and the
    receiver must fall back to re-prefill, not install half a prefix."""
    if blob[:len(MAGIC)] != MAGIC:
        raise BlobError("bad magic (not a KV migration blob)")
    off = len(MAGIC)
    records: list[tuple[int, bytes]] = []
    while off < len(blob):
        if off + _HEADER.size > len(blob):
            raise BlobError(f"truncated header at byte {off}")
        rtype, length, crc = _HEADER.unpack_from(blob, off)
        body_start = off + _HEADER.size
        if length > _MAX_PAYLOAD or body_start + length > len(blob):
            raise BlobError(f"truncated record at byte {off}")
        body = blob[body_start:body_start + length]
        if zlib.crc32(bytes((rtype,)) + body) & 0xFFFFFFFF != crc:
            raise BlobError(f"CRC mismatch at byte {off}")
        records.append((rtype, body))
        off = body_start + length
    return records


def pack_prefix_blob(meta: dict, arrays: Iterable[np.ndarray]) -> bytes:
    """META + one ARRAY record per page-row leaf, in the engine's fixed
    per-layer order (k, v[, k_scale, v_scale])."""
    meta = dict(meta, version=BLOB_VERSION)
    recs: list[tuple[int, bytes]] = [
        (REC_META, json.dumps(meta, separators=(",", ":")).encode())]
    for arr in arrays:
        recs.append((REC_ARRAY, np.ascontiguousarray(arr).tobytes()))
    return pack_records(recs)


def unpack_prefix_blob(blob: bytes) -> tuple[dict, list[bytes]]:
    """Inverse of :func:`pack_prefix_blob`; returns (meta, raw leaf
    bytes).  Leaf dtype/shape reconstruction is the caller's job — it
    owns the geometry contract the META is validated against."""
    records = unpack_records(blob)
    if not records or records[0][0] != REC_META:
        raise BlobError("first record is not META")
    try:
        meta = json.loads(records[0][1])
    except ValueError as e:
        raise BlobError(f"undecodable META: {e}") from e
    if not isinstance(meta, dict):
        raise BlobError("META is not an object")
    if meta.get("version") != BLOB_VERSION:
        raise BlobError(f"unsupported blob version {meta.get('version')!r}")
    arrays = []
    for rtype, body in records[1:]:
        if rtype != REC_ARRAY:
            raise BlobError(f"unexpected record type {rtype}")
        arrays.append(body)
    return meta, arrays


@dataclasses.dataclass
class SpilledPrefix:
    """One demoted prefix-cache entry: host copies of its page rows.

    ``layers[li]`` is ``(k, v)`` or ``(k, v, k_scale, v_scale)`` —
    numpy arrays of shape ``[n_blocks, block_size, lanes]`` (scales:
    ``[n_blocks, block_size, kv_heads]``), materialized (``np.asarray``)
    at spill time so the entry survives engine teardown/rebuild."""

    n_blocks: int
    layers: list[tuple[np.ndarray, ...]]
    nbytes: int = 0
    #: Namespace owner.  The digest key is already tenant-seeded (the
    #: chain seed is ``tenant_seed(tenant)``), so cross-tenant probes
    #: cannot match; the tag exists for fairness accounting + stats.
    tenant: str = DEFAULT_TENANT

    def __post_init__(self) -> None:
        if not self.nbytes:
            self.nbytes = sum(
                a.nbytes for leaf in self.layers for a in leaf)


@guarded_by("_lock", "spills", "restores", "lost", "_bytes",
            "_tenant_bytes")
class HostKVTier:
    """Byte-capped LRU of :class:`SpilledPrefix` entries, keyed by the
    prefix cache's chain digest (so a restore probe is the same digest
    walk a device-tier lookup already does).  Digests are tenant-seeded
    upstream, so the key space is already namespaced; the tier adds
    per-tenant byte accounting and a max-share cap (``max_tenant_share``
    of ``max_bytes``, enforced only while >= 2 tenants are resident) so
    one tenant cannot monopolize host RAM either.

    Thread-safe: spill/restore run on the engine step thread, but stats
    are scraped from exporter threads and the supervisor constructs/
    keeps the tier across engine rebuilds.
    """

    def __init__(self, max_bytes: int = 256 << 20,
                 max_tenant_share: float = 1.0):
        self.max_bytes = max_bytes
        self.max_tenant_share = float(max_tenant_share)
        self._entries: dict[bytes, SpilledPrefix] = {}
        self.spills = 0
        self.restores = 0
        #: Entries dropped without restore (host-cap eviction / clear).
        self.lost = 0
        self._bytes = 0
        self._tenant_bytes: dict[str, int] = {}
        # Created last so __init__ writes above stay lockcheck-exempt.
        self._lock = make_lock("host_kv_tier")

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def bytes_used(self) -> int:
        with self._lock:
            return self._bytes

    def _drop_locked(self, key: bytes, *, lost: bool) -> SpilledPrefix:
        entry = self._entries.pop(key)
        self._bytes -= entry.nbytes
        rem = self._tenant_bytes.get(entry.tenant, 0) - entry.nbytes
        if rem > 0:
            self._tenant_bytes[entry.tenant] = rem
        else:
            self._tenant_bytes.pop(entry.tenant, None)
        if lost:
            self.lost += 1
        return entry

    def _tenant_lru_locked(self, tenant: str,
                           skip: bytes | None = None) -> bytes | None:
        for key, entry in self._entries.items():
            if entry.tenant == tenant and key != skip:
                return key
        return None

    def put(self, digest: bytes, entry: SpilledPrefix, *,
            tenant: str = DEFAULT_TENANT) -> bool:
        """Admit a demoted entry under ``tenant``'s namespace; returns
        False when it can never fit (bigger than the whole cap) — the
        caller then just drops it."""
        entry.tenant = tenant
        if entry.nbytes > self.max_bytes:
            return False
        with self._lock:
            if digest in self._entries:
                self._drop_locked(digest, lost=False)
            while self._bytes + entry.nbytes > self.max_bytes:
                self._drop_locked(next(iter(self._entries)), lost=True)
            self._entries[digest] = entry
            self._bytes += entry.nbytes
            self._tenant_bytes[tenant] = (
                self._tenant_bytes.get(tenant, 0) + entry.nbytes)
            self.spills += 1
            # Fairness cap: a tenant over its byte share (with another
            # tenant resident) pays with its OWN oldest entries.  The
            # just-admitted entry is never the victim, so spill always
            # makes progress.
            if self.max_tenant_share < 1.0:
                cap = self.max_tenant_share * self.max_bytes
                while (len(self._tenant_bytes) >= 2
                       and self._tenant_bytes.get(tenant, 0) > cap):
                    victim = self._tenant_lru_locked(tenant, skip=digest)
                    if victim is None:
                        break
                    self._drop_locked(victim, lost=True)
            return True

    def take(self, digest: bytes) -> SpilledPrefix | None:
        """Remove and return the entry for ``digest`` (restore consumes
        the host copy — the device tier re-registers it on rehydrate,
        so keeping a stale duplicate would only burn host RAM)."""
        with self._lock:
            if digest not in self._entries:
                return None
            entry = self._drop_locked(digest, lost=False)
            self.restores += 1
            return entry

    def peek(self, digest: bytes) -> SpilledPrefix | None:
        """Entry for ``digest`` without consuming it (no LRU touch, no
        counter) — the engine validates geometry before committing device
        blocks to a restore."""
        with self._lock:
            return self._entries.get(digest)

    def contains(self, digest: bytes) -> bool:
        with self._lock:
            return digest in self._entries

    def clear(self) -> None:
        with self._lock:
            self.lost += len(self._entries)
            self._entries.clear()
            self._bytes = 0
            self._tenant_bytes.clear()

    def bytes_by_tenant(self) -> dict[str, int]:
        """Resident host-tier bytes per tenant (fairness accounting)."""
        with self._lock:
            return dict(self._tenant_bytes)

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "max_bytes": self.max_bytes,
                "spills": self.spills,
                "restores": self.restores,
                "lost": self.lost,
                "tenant_bytes": dict(self._tenant_bytes),
            }
