"""Monitor API server entrypoint.

Parity target: ``/root/reference/cmd/server/main.go:23-172`` — config
load, cluster client with graceful dev-mode degradation (:43-51), metrics
manager start (:82-87), route registration + serve, clean shutdown.

Cluster selection:
- ``--cluster fake``   : in-memory demo cluster (runs anywhere, like the
                         reference's nil-client dev mode but with data)
- ``--cluster kube``   : real API server via kubeconfig/in-cluster
                         (stdlib REST client, monitor/kube_rest.py)
- ``--cluster none``   : no cluster at all (pure degraded mode)

Usage:
    python -m k8s_llm_monitor_tpu.cmd.server --config config.yaml
    python -m k8s_llm_monitor_tpu.cmd.server --cluster fake --port 8081
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys
import threading


def _graceful_shutdown(srv, grace_s: float, log: logging.Logger) -> None:
    """SIGTERM handover: stop admitting, drain within the grace window,
    seal the journal, then unblock ``serve_forever`` so the process exits.

    Order matters: readiness flips to 503 first (via the supervisor's
    TERMINATING state / health DRAINING) so the kube endpoint controller
    stops routing new traffic while inflight generations finish — the
    manifest's preStop sleep covers the propagation delay.
    """
    from k8s_llm_monitor_tpu.observability.flight import get_flight_recorder

    # Last-gasp artifact before teardown mutates any in-flight state; a
    # dump failure (read-only fs, disk full) must never block the drain.
    rec = get_flight_recorder()
    rec.note("sigterm", grace_s=grace_s)
    rec.dump("sigterm", extra={"grace_s": grace_s})
    # Announce draining FIRST: the next fleet stats probe sees it and the
    # router stops dispatching here before the engine starts refusing.
    srv.draining = True
    if srv.signals is not None:
        srv.signals.stop()
        log.info("signal scraper stopped")
    watcher = getattr(srv, "diagnosis_watcher", None)
    if watcher is not None:
        watcher.stop()
        log.info("diagnosis watcher stopped")
    if srv.diagnosis is not None:
        srv.diagnosis.stop()
        log.info("diagnosis pipeline stopped")
    sup = srv.engine_supervisor()
    if sup is not None:
        drained = sup.shutdown(grace_s=grace_s)
        log.info("engine supervisor shut down (drained=%s, journal sealed)",
                 drained)
    else:
        svc = srv.engine_service()
        if svc is not None:
            svc.drain(timeout=grace_s)
            svc.stop(timeout=5.0)
            log.info("engine service drained and stopped")
    if srv.fleet_router() is not None:
        srv.analysis.close()  # stop probes, close replica adapters
        log.info("fleet router closed")
    if srv.manager is not None:
        srv.manager.stop()
    srv.request_shutdown()


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description="k8s-llm-monitor TPU server")
    parser.add_argument("--config", default="", help="config YAML path")
    parser.add_argument("--host", default=None)
    parser.add_argument("--port", type=int, default=None)
    parser.add_argument(
        "--cluster",
        choices=("fake", "kube", "none"),
        default="fake",
        help="cluster backend (default: fake demo cluster)",
    )
    parser.add_argument("--kubeconfig", default="", help="kubeconfig path for --cluster kube")
    parser.add_argument(
        "--llm",
        default="",
        help="override llm.provider (tpu | openai | template)",
    )
    parser.add_argument(
        "--role",
        choices=("replica", "router"),
        default="replica",
        help="replica: serve a local engine (default); router: front the "
             "fleet.replicas URLs with policy routing + failover",
    )
    parser.add_argument(
        "--replicas",
        default="",
        help="router role: comma-separated replica base URLs "
             "(overrides fleet.replicas / FLEET_REPLICAS)",
    )
    args = parser.parse_args(argv)

    from k8s_llm_monitor_tpu.monitor.config import load_config
    from k8s_llm_monitor_tpu.monitor.server import build_server

    config = load_config(args.config or None)
    logging.basicConfig(
        level=logging.DEBUG if config.server.debug else logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
    )
    log = logging.getLogger("cmd.server")
    if args.host is not None:
        config.server.host = args.host
    if args.port is not None:
        config.server.port = args.port
    if args.llm:
        config.llm.provider = args.llm
    if args.replicas:
        config.fleet.replicas = [
            u.strip() for u in args.replicas.split(",") if u.strip()]

    if args.role == "router":
        # Router role: no local engine, no cluster client — just the fleet
        # behind the same /api/v1/query + /api/v1/analyze API.
        from k8s_llm_monitor_tpu.fleet.frontend import build_router_server

        srv = build_router_server(config)
        if srv.autoscaler is not None:
            srv.autoscaler.start()
        shutdown_started = threading.Event()

        def _on_router_signal(signum, frame):  # noqa: ARG001 — signal API
            if shutdown_started.is_set():
                raise SystemExit(128 + signum)
            shutdown_started.set()
            log.info("signal %d: router shutting down", signum)

            def _stop() -> None:
                from k8s_llm_monitor_tpu.observability.flight import (
                    get_flight_recorder)

                get_flight_recorder().dump("sigterm",
                                           extra={"role": "router"})
                if srv.autoscaler is not None:
                    srv.autoscaler.stop()
                if srv.signals is not None:
                    srv.signals.stop()
                srv.analysis.close()
                srv.request_shutdown()

            threading.Thread(target=_stop, name="graceful-shutdown",
                             daemon=True).start()

        signal.signal(signal.SIGTERM, _on_router_signal)
        signal.signal(signal.SIGINT, _on_router_signal)
        try:
            srv.serve_forever()
        finally:
            if not shutdown_started.is_set():
                if srv.autoscaler is not None:
                    srv.autoscaler.stop()
                if srv.signals is not None:
                    srv.signals.stop()
                srv.analysis.close()
        return 0

    if config.llm.provider == "tpu" and config.llm.tpu.compile_cache_dir:
        # Persistent XLA compilation cache BEFORE any jit runs: a warm
        # restart reuses compiled prefill/decode programs (~seconds)
        # instead of recompiling the full ladder (~minutes on 8B).
        import jax

        jax.config.update("jax_compilation_cache_dir",
                          config.llm.tpu.compile_cache_dir)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        log.info("XLA compilation cache at %s",
                 config.llm.tpu.compile_cache_dir)

    backend = None
    if args.cluster == "fake":
        from k8s_llm_monitor_tpu.monitor.cluster import FakeCluster, seed_demo_cluster

        backend = seed_demo_cluster(FakeCluster())
        log.info("using in-memory demo cluster")
    elif args.cluster == "kube":
        try:
            from k8s_llm_monitor_tpu.monitor.kube_rest import KubeRestBackend

            backend = KubeRestBackend.from_kubeconfig(
                args.kubeconfig or config.k8s.kubeconfig or None
            )
            backend.server_version()  # fail fast if unreachable
        except Exception as exc:  # noqa: BLE001 — dev-mode degradation
            log.warning("cluster unreachable (%s) - development mode", exc)
            backend = None

    srv = build_server(config, backend=backend)
    if srv.manager is not None:
        srv.manager.start()
        log.info(
            "metrics manager started (interval %ds)", config.metrics.collect_interval
        )

    # Standing watcher→LLM diagnosis loop: the resource watcher feeds the
    # pipeline's EventHandler; the pipeline's worker thread (started with
    # the HTTP server) turns event bursts into constrained root-cause
    # verdicts behind GET /api/v1/diagnoses.
    srv.diagnosis_watcher = None
    if srv.diagnosis is not None and srv.client is not None:
        from k8s_llm_monitor_tpu.monitor.watcher import Watcher

        srv.diagnosis_watcher = Watcher(
            srv.client, srv.diagnosis.handler,
            namespaces=config.k8s.watch_namespaces)
        srv.diagnosis_watcher.start()
        log.info("diagnosis watcher started (burst threshold %d in %.0fs)",
                 config.diagnosis.burst_threshold, config.diagnosis.window_s)

    # SIGTERM (kubelet) / SIGINT: flip readiness to 503, drain inflight
    # generations within the grace window, seal the request journal, exit.
    # The work runs on a helper thread: httpd.shutdown() deadlocks when
    # called from the thread running serve_forever, and signal handlers
    # run exactly there.
    shutdown_started = threading.Event()

    def _on_signal(signum, frame):  # noqa: ARG001 — signal API
        if shutdown_started.is_set():
            log.warning("second signal %d: exiting immediately", signum)
            raise SystemExit(128 + signum)
        shutdown_started.set()
        log.info("signal %d: graceful shutdown (grace %.0fs)",
                 signum, config.lifecycle.drain_grace_s)
        threading.Thread(
            target=_graceful_shutdown,
            args=(srv, config.lifecycle.drain_grace_s, log),
            name="graceful-shutdown",
            daemon=True,
        ).start()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)

    try:
        srv.serve_forever()
    finally:
        if not shutdown_started.is_set():
            if srv.signals is not None:
                srv.signals.stop()
            if srv.diagnosis_watcher is not None:
                srv.diagnosis_watcher.stop()
            if srv.diagnosis is not None:
                srv.diagnosis.stop()
            sup = srv.engine_supervisor()
            if sup is not None:
                sup.shutdown(grace_s=0.0)
        if srv.manager is not None:
            srv.manager.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
