"""TPU serving preflight: will this model/quant/mesh fit and shard on the
hardware you have, before you boot the server?

The TPU-plane sibling of ``cmd.test_k8s`` (which preflights cluster
access the way the reference's ``cmd/test-k8s`` does,
reference cmd/test-k8s/main.go:44-185 — the reference has no inference
plane to preflight).  Everything is computed from ``jax.eval_shape`` —
no weights are materialized, so checking a 70B config takes seconds on a
laptop with no accelerator at all.

Usage::

    python -m k8s_llm_monitor_tpu.cmd.preflight --model llama3-8b \
        --quantize w8a8 --mesh 1,1,8
    python -m k8s_llm_monitor_tpu.cmd.preflight --config config.yaml
    python -m k8s_llm_monitor_tpu.cmd.preflight --model llama3-70b \
        --quantize int8 --mesh 1,1,16 --per-chip-hbm-gib 95

Exit code 0 = every check passed (warnings allowed), 1 = at least one
FAIL.
"""

from __future__ import annotations

import argparse
import sys

GIB = 1 << 30

# Fallback per-chip HBM when the runtime does not report a limit (e.g.
# preflighting a TPU deployment from a CPU host).  Sources: public TPU
# system specs.
_HBM_BY_KIND = {
    "TPU v4": 32 * GIB,
    "TPU v5 lite": 16 * GIB,
    "TPU v5e": 16 * GIB,
    "TPU v5": 95 * GIB,
    "TPU v5p": 95 * GIB,
    "TPU v6 lite": 32 * GIB,
    "TPU v6e": 32 * GIB,
}

# Headroom for activations, the XLA workspace, and dispatch buffers at
# serving batch sizes — an estimate (the engine's own peak depends on the
# prefill bucket ladder), deliberately conservative.
_WORKSPACE_BYTES = int(1.5 * GIB)

_DTYPE_BYTES = {"bfloat16": 2, "float32": 4, "float16": 2,
                "float8_e4m3fn": 1, "int8": 1}


class _Report:
    """Collects verdicts as structured lists (consumed by ``check()``)
    while printing the human report."""

    def __init__(self) -> None:
        self.failed = 0
        self.warned = 0
        self.fail_msgs: list[str] = []
        self.warn_msgs: list[str] = []

    def ok(self, msg: str) -> None:
        print(f"  PASS {msg}")

    def warn(self, msg: str) -> None:
        self.warned += 1
        self.warn_msgs.append(msg)
        print(f"  WARN {msg}")

    def fail(self, msg: str) -> None:
        self.failed += 1
        self.fail_msgs.append(msg)
        print(f"  FAIL {msg}")


def _tree_bytes(shapes, specs, model_axis: int,
                leaf_bytes=None) -> tuple[int, int]:
    """(total_bytes, per_chip_bytes) for an eval_shape tree under TP
    sharding: leaves with a ``model`` axis divide across the mesh's model
    dim, everything else is replicated per chip.  ``leaf_bytes``
    overrides the per-leaf byte rule (used by the estimated-int8 path)."""
    import jax
    from jax.sharding import PartitionSpec

    if leaf_bytes is None:
        leaf_bytes = lambda leaf: leaf.size * leaf.dtype.itemsize  # noqa: E731
    total = per_chip = 0
    for leaf, spec in zip(jax.tree_util.tree_leaves(shapes),
                          jax.tree_util.tree_leaves(
                              specs,
                              is_leaf=lambda s: isinstance(s, PartitionSpec))):
        nbytes = leaf_bytes(leaf)
        total += nbytes
        shard = model_axis if any(ax == "model" for ax in spec) else 1
        per_chip += nbytes // shard
    return total, per_chip


def run_preflight(args: argparse.Namespace,
                  r: _Report | None = None) -> int:
    import jax

    from k8s_llm_monitor_tpu.models import llama
    from k8s_llm_monitor_tpu.models.config import PRESETS
    from k8s_llm_monitor_tpu.parallel.sharding import param_partition_specs

    r = r if r is not None else _Report()

    def finish() -> int:
        # Single verdict trailer — printed on early bail-outs too, so
        # wrappers keying on this line always get one.
        print(f"\npreflight: {'FAIL' if r.failed else 'PASS'} "
              f"({r.failed} failed, {r.warned} warnings)")
        return 1 if r.failed else 0

    # -- 1. runtime -----------------------------------------------------
    print("== 1. runtime ==")
    devices = jax.devices()
    kind = devices[0].device_kind
    plat = devices[0].platform
    r.ok(f"jax {jax.__version__}, {len(devices)} x {kind} ({plat})")

    hbm = None
    if args.per_chip_hbm_gib:
        hbm = int(args.per_chip_hbm_gib * GIB)
    else:
        try:
            stats = devices[0].memory_stats() or {}
            hbm = stats.get("bytes_limit")
        except Exception:  # noqa: BLE001 — CPU/older runtimes
            hbm = None
        if not hbm:
            hbm = next((v for k, v in _HBM_BY_KIND.items()
                        if kind.startswith(k)), None)
    if hbm:
        r.ok(f"per-chip HBM budget {hbm / GIB:.0f} GiB"
             + ("" if args.per_chip_hbm_gib else f" (from {kind!r})"))
    else:
        r.warn(f"unknown HBM for device kind {kind!r} - fit checks "
               "skipped (pass --per-chip-hbm-gib)")

    # -- 2. model geometry ----------------------------------------------
    print("== 2. model ==")
    if args.checkpoint:
        import json
        import os

        cfg_path = os.path.join(args.checkpoint, "config.json")
        try:
            from k8s_llm_monitor_tpu.utils.checkpoint import config_from_hf

            with open(cfg_path, encoding="utf-8") as fh:
                cfg = config_from_hf(json.load(fh))
            r.ok(f"checkpoint config {cfg_path}: {cfg.name}")
        except Exception as exc:  # noqa: BLE001 — report, don't crash
            r.fail(f"cannot read checkpoint config {cfg_path}: {exc}")
            return finish()
    else:
        if args.model not in PRESETS:
            r.fail(f"unknown preset {args.model!r}; have "
                   f"{', '.join(sorted(PRESETS))}")
            return finish()
        cfg = PRESETS[args.model]
    if args.quantize == "w8a8":
        import dataclasses

        cfg = dataclasses.replace(cfg, act_quant=True)
    head_dim = cfg.head_dim or cfg.hidden_size // cfg.num_heads
    if cfg.num_heads % cfg.num_kv_heads == 0:
        r.ok(f"{cfg.num_layers}L hidden={cfg.hidden_size} "
             f"heads={cfg.num_heads}/{cfg.num_kv_heads}kv "
             f"head_dim={head_dim} vocab={cfg.vocab_size}"
             + (f" experts={cfg.num_experts}" if cfg.num_experts else ""))
    else:
        r.fail(f"num_heads {cfg.num_heads} not a multiple of "
               f"num_kv_heads {cfg.num_kv_heads}")

    # -- 3. mesh --------------------------------------------------------
    print("== 3. mesh ==")
    try:
        data, seq, model = (int(x) for x in args.mesh.split(","))
        if data < 1 or seq < 1 or model < 1:
            raise ValueError("mesh dims must be >= 1")
    except Exception:  # noqa: BLE001
        r.fail(f"bad --mesh {args.mesh!r}; expected data,seq,model")
        return finish()
    n_mesh = data * seq * model
    if n_mesh == len(devices):
        r.ok(f"mesh data={data} seq={seq} model={model} "
             f"matches {len(devices)} local device(s)")
    else:
        r.warn(f"mesh needs {n_mesh} device(s), this host sees "
               f"{len(devices)} - fine if deploying elsewhere or "
               "multi-host")
    if model > 1:
        bad = [(nm, dim) for nm, dim in
               [("num_heads", cfg.num_heads),
                ("intermediate_size", cfg.intermediate_size),
                ("vocab_size", cfg.vocab_size)] if dim % model != 0]
        for nm, dim in bad:
            r.fail(f"{nm}={dim} not divisible by model={model}")
        if not bad:
            r.ok(f"q-heads/FFN/vocab all divide model={model}")
        if cfg.num_kv_heads % model == 0:
            r.ok(f"kv_heads={cfg.num_kv_heads} shard {model}-way "
                 "(KV pages split on head boundaries)")
        else:
            r.warn(f"kv_heads={cfg.num_kv_heads} not divisible by "
                   f"model={model} - KV pages replicate per chip "
                   "(parallel/sharding.py kv_pages_partition_specs)")
    if seq > 1:
        # Serve meshes with a seq axis shard prefill token batches; the
        # engine validates bucket divisibility at boot (engine.py).
        r.ok(f"seq={seq}: engine shards prefill chunks (buckets must "
             f"divide by {seq}; checked at boot)")

    # -- 4. weights -----------------------------------------------------
    print("== 4. weights ==")
    quantized = args.quantize in ("int8", "w8a8")
    bf16_shapes = jax.eval_shape(
        lambda: llama.init_params(jax.random.PRNGKey(0), cfg))
    estimated = False
    if quantized:
        try:
            from k8s_llm_monitor_tpu.utils.quantize import (
                init_params_quantized,
            )

            shapes = jax.eval_shape(
                lambda: init_params_quantized(jax.random.PRNGKey(0), cfg))
        except Exception:  # noqa: BLE001 — MoE expert quantizer is
            # host-side (untraceable); estimate from the bf16 tree:
            # every >=2-D leaf stores 1 byte/element as int8 (per-channel
            # f32 scales are <0.1% and ignored).
            shapes = bf16_shapes
            estimated = True
    else:
        shapes = bf16_shapes
    specs = param_partition_specs(shapes)
    total_b, chip_b = _tree_bytes(
        shapes, specs, model,
        leaf_bytes=(lambda leaf: leaf.size * (1 if leaf.ndim >= 2
                                              else leaf.dtype.itemsize))
        if estimated else None)
    r.ok(f"{args.quantize or 'bf16'} weights {total_b / GIB:.2f} GiB total"
         + (f", {chip_b / GIB:.2f} GiB/chip at TP-{model}"
            if model > 1 else "")
         + (" (estimated: int8 bytes from bf16 tree)" if estimated else ""))

    # -- 5. KV cache ----------------------------------------------------
    print("== 5. kv cache ==")
    kv_bytes_per = _DTYPE_BYTES.get(cfg.kv_dtype or cfg.dtype, 2)
    kv_heads_chip = (cfg.num_kv_heads // model
                     if model > 1 and cfg.num_kv_heads % model == 0
                     else cfg.num_kv_heads)
    kv_chip = (args.kv_blocks * args.block_size * cfg.num_layers * 2
               * kv_heads_chip * head_dim * kv_bytes_per)
    cap_tokens = args.kv_blocks * args.block_size
    r.ok(f"{args.kv_blocks} blocks x {args.block_size} = "
         f"{cap_tokens} tokens capacity, {kv_chip / GIB:.2f} GiB/chip "
         f"({cfg.kv_dtype or cfg.dtype} KV)")
    per_seq = args.prompt_len + args.max_tokens
    if per_seq > 0:
        fit = cap_tokens // per_seq
        msg = (f"~{fit} concurrent sequences at prompt {args.prompt_len} "
               f"+ gen {args.max_tokens}")
        (r.ok if fit >= 1 else r.fail)(
            msg if fit >= 1 else msg + " - raise --kv-blocks")

    # -- 6. fit verdict -------------------------------------------------
    print("== 6. fit ==")
    if hbm:
        need = chip_b + kv_chip + _WORKSPACE_BYTES
        line = (f"per-chip: weights {chip_b / GIB:.2f} + kv "
                f"{kv_chip / GIB:.2f} + workspace "
                f"{_WORKSPACE_BYTES / GIB:.1f} = {need / GIB:.2f} GiB "
                f"of {hbm / GIB:.0f} GiB")
        if need <= 0.92 * hbm:
            r.ok(line)
        elif need <= hbm:
            r.warn(line + " - under 8% headroom")
        else:
            r.fail(line + " - does not fit; shrink --kv-blocks, raise "
                   "TP, or quantize")
    else:
        r.warn("no HBM budget known - skipped")

    # -- 7. optional compile smoke --------------------------------------
    if args.compile:
        print("== 7. compile ==")
        import jax.numpy as jnp

        out = jax.jit(lambda a, b: a @ b)(
            jnp.ones((256, 256), jnp.bfloat16),
            jnp.ones((256, 256), jnp.bfloat16))
        out.block_until_ready()
        r.ok(f"jit matmul on {plat} ok")

    return finish()


def _build_args(argv: list[str] | None) -> argparse.Namespace:
    ap = argparse.ArgumentParser(
        description="TPU serving preflight (no weights materialized)")
    ap.add_argument("--config", default="",
                    help="server YAML; fills any flag not given "
                         "explicitly from llm.tpu.* (explicit flags win)")
    ap.add_argument("--model", default=None)
    ap.add_argument("--checkpoint", default=None,
                    help="HF checkpoint dir (overrides --model)")
    ap.add_argument("--quantize", default=None,
                    choices=["", "none", "int8", "w8a8"])
    ap.add_argument("--mesh", default=None,
                    help="data,seq,model (llm.tpu.mesh_shape)")
    ap.add_argument("--kv-blocks", type=int, default=None)
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--prompt-len", type=int, default=192)
    ap.add_argument("--max-tokens", type=int, default=256)
    ap.add_argument("--per-chip-hbm-gib", type=float, default=0.0)
    ap.add_argument("--compile", action="store_true",
                    help="run a tiny jit on the backend")
    args = ap.parse_args(argv)
    if args.config:
        # Only flags the user did NOT pass explicitly (still None) are
        # filled from the YAML — an explicit flag always wins.
        from k8s_llm_monitor_tpu.monitor.config import load_config

        c = load_config(args.config)
        if args.model is None:
            args.model = c.llm.tpu.model or None
        if args.checkpoint is None:
            args.checkpoint = c.llm.tpu.checkpoint or None
        if args.quantize is None:
            args.quantize = getattr(c.llm.tpu, "quantize", None)
        if args.mesh is None:
            args.mesh = c.llm.tpu.mesh_shape or None
        if args.kv_blocks is None:
            args.kv_blocks = c.llm.tpu.kv_blocks or None
    # Hard defaults for anything neither flag nor config set.
    if args.model is None:
        args.model = "llama3-8b"
    args.checkpoint = args.checkpoint or ""
    args.quantize = args.quantize if args.quantize is not None else "w8a8"
    if args.quantize == "none":
        args.quantize = ""
    args.mesh = args.mesh or "1,1,1"
    args.kv_blocks = args.kv_blocks or 512
    return args


def check(argv: list[str] | None = None) -> tuple[int, list[str], list[str]]:
    """Programmatic preflight: (exit_code, fail_msgs, warn_msgs).

    Same argv surface as the CLI; callers (monitor/analysis.py boot)
    consume the structured lists instead of scraping printed output."""
    r = _Report()
    rc = run_preflight(_build_args(argv), r)
    return rc, r.fail_msgs, r.warn_msgs


def main(argv: list[str] | None = None) -> int:
    return run_preflight(_build_args(argv))


if __name__ == "__main__":
    sys.exit(main())
