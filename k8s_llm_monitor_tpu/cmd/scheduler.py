"""Scheduler controller entrypoint.

Parity target: ``/root/reference/cmd/scheduler/main.go:20-67`` — wires the
dynamic CRD client + controller with an ``-interval`` flag (default 15 s).
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys
import threading


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description="UAV-aware scheduling controller")
    parser.add_argument("--config", default="", help="config YAML path")
    parser.add_argument("--interval", type=float, default=15.0)
    parser.add_argument(
        "--cluster", choices=("fake", "kube"), default="kube",
        help="cluster backend",
    )
    parser.add_argument("--kubeconfig", default="")
    parser.add_argument(
        "--once", action="store_true",
        help="run a single reconcile pass and exit (scripting/CI)")
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s %(levelname)s %(name)s %(message)s"
    )
    log = logging.getLogger("cmd.scheduler")

    from k8s_llm_monitor_tpu.monitor.client import Client
    from k8s_llm_monitor_tpu.monitor.config import load_config
    from k8s_llm_monitor_tpu.monitor.scheduler import (
        SchedulerConfig,
        SchedulerController,
    )

    config = load_config(args.config or None)
    if args.cluster == "fake":
        from k8s_llm_monitor_tpu.monitor.cluster import FakeCluster, seed_demo_cluster

        backend = seed_demo_cluster(FakeCluster())
    else:
        from k8s_llm_monitor_tpu.monitor.kube_rest import KubeRestBackend

        backend = KubeRestBackend.from_kubeconfig(
            args.kubeconfig or config.k8s.kubeconfig or None
        )

    client = Client(backend, namespaces=config.k8s.watch_namespaces)
    ctrl = SchedulerController(client, SchedulerConfig(interval=args.interval))
    if args.once:
        n = ctrl.reconcile()
        log.info("one-shot reconcile processed %d request(s)", n)
        return 0
    ctrl.start()
    log.info("scheduler controller running (interval %.0fs)", args.interval)

    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    stop.wait()
    log.info("shutting down scheduler...")
    ctrl.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
