"""Fine-tuning entrypoint: GSPMD train loop with checkpoint save/resume.

Beyond-reference capability (the reference has no model execution at all);
completes the framework's training path (training/train.py) with a driver:
token-file or synthetic data, a ``data × seq × model`` mesh, optional ring
attention over ``seq`` for long sequences, periodic orbax checkpoints, and
resume.

Usage:
    python -m k8s_llm_monitor_tpu.cmd.train --model llama-1b --steps 100 \
        --mesh 2,2,2 --batch 8 --seq-len 1024 --ckpt-dir /tmp/ckpt
    python -m k8s_llm_monitor_tpu.cmd.train --resume /tmp/ckpt/step_50 ...

Data: ``--data tokens.npy`` expects a flat int32 token array (memory-mapped;
batches are random contiguous windows); without it a synthetic corpus keeps
the loop runnable anywhere (smoke tests, mesh bring-up).
"""

from __future__ import annotations

import argparse
import logging
import sys
import time


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description="k8s-llm-monitor TPU trainer")
    parser.add_argument("--model", default="llama-1b", help="preset name")
    parser.add_argument("--steps", type=int, default=100)
    parser.add_argument("--batch", type=int, default=8)
    parser.add_argument("--seq-len", type=int, default=1024)
    parser.add_argument("--mesh", default="", help="data,seq,model (default: all data)")
    parser.add_argument("--lr", type=float, default=3e-4)
    parser.add_argument("--remat", action="store_true")
    parser.add_argument("--ring-attention", action="store_true",
                        help="explicit ring attention over the seq axis")
    parser.add_argument("--pipeline", type=int, default=0,
                        help="pipeline-parallel stages (GPipe over a "
                             "data x pipe mesh; parallel/pipeline.py); "
                             "0/1 = off.  Mutually exclusive with --mesh")
    parser.add_argument("--microbatches", type=int, default=4,
                        help="microbatches per step under --pipeline")
    parser.add_argument("--data", default="", help="flat int32 token .npy")
    parser.add_argument("--seed", type=int, default=0,
                        help="data-stream seed (offset by resumed step)")
    parser.add_argument("--multihost", action="store_true",
                        help="join a multi-host JAX runtime (DCN across "
                             "hosts; see parallel.mesh.init_multihost)")
    parser.add_argument("--ckpt-dir", default="")
    parser.add_argument("--ckpt-every", type=int, default=50)
    parser.add_argument("--resume", default="", help="checkpoint to restore")
    parser.add_argument("--log-every", type=int, default=10)
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(levelname)s %(message)s")
    log = logging.getLogger("cmd.train")

    import numpy as np

    import jax

    from k8s_llm_monitor_tpu.models.config import PRESETS
    from k8s_llm_monitor_tpu.parallel.mesh import MeshConfig, create_mesh
    from k8s_llm_monitor_tpu.training import (
        TrainConfig,
        create_train_state,
        make_train_step,
        shard_train_state,
    )
    from k8s_llm_monitor_tpu.training.train import data_spec
    from k8s_llm_monitor_tpu.utils.checkpoint import (
        restore_checkpoint,
        save_checkpoint,
    )

    cfg = PRESETS[args.model]
    if args.multihost:
        from k8s_llm_monitor_tpu.parallel.mesh import init_multihost

        pid = init_multihost()
        log.info("multihost: process %d/%d, %d local of %d global devices",
                 pid, jax.process_count(), jax.local_device_count(),
                 jax.device_count())
    n_dev = len(jax.devices())
    step0 = 0
    if args.pipeline > 1:
        # GPipe pipeline parallelism: contiguous layer blocks over `pipe`,
        # data parallel over the rest (parallel/pipeline.py).
        if args.mesh:
            log.error("--pipeline and --mesh are mutually exclusive")
            return 1
        if args.ring_attention:
            log.error("--ring-attention is not available under --pipeline "
                      "(stages run dense attention over whole sequences)")
            return 1
        if args.pipeline > n_dev or n_dev % args.pipeline:
            log.error("%d pipeline stages must evenly split the %d devices",
                      args.pipeline, n_dev)
            return 1
        dp = n_dev // args.pipeline
        if args.batch % args.microbatches or \
                (args.batch // args.microbatches) % dp:
            log.error("--batch (%d) must be a multiple of --microbatches "
                      "(%d) x data-parallel degree (%d)",
                      args.batch, args.microbatches, dp)
            return 1
        from k8s_llm_monitor_tpu.models import llama
        from k8s_llm_monitor_tpu.parallel.pipeline import (
            create_pp_mesh,
            make_pipeline_train_step,
            place_pipeline_opt_state,
            place_pipeline_params,
            stack_pipeline_params,
        )
        from k8s_llm_monitor_tpu.training.train import make_optimizer

        mesh = create_pp_mesh(dp, args.pipeline)
        log.info("mesh: data=%d pipe=%d on %d %s device(s); "
                 "%d microbatches (bubble overhead %d/%d ticks)",
                 dp, args.pipeline, n_dev, jax.devices()[0].platform,
                 args.microbatches, args.pipeline - 1,
                 args.microbatches + args.pipeline - 1)
        tc = TrainConfig(learning_rate=args.lr, remat=True)
        optimizer = make_optimizer(tc)
        staged = stack_pipeline_params(
            llama.init_params(jax.random.PRNGKey(0), cfg), args.pipeline)
        opt_state = optimizer.init(staged)
        if args.resume:
            restored = restore_checkpoint(
                args.resume,
                like={"params": staged, "opt_state": opt_state, "step": 0})
            staged = restored["params"]
            opt_state = restored["opt_state"]
            step0 = int(restored["step"])
            log.info("resumed from %s at step %d", args.resume, step0)
        params = place_pipeline_params(staged, mesh)
        opt_state = place_pipeline_opt_state(opt_state, args.pipeline, mesh)
        step_fn = make_pipeline_train_step(mesh, cfg, optimizer,
                                           args.microbatches)
        from jax.sharding import PartitionSpec as _P
        token_spec = _P("data", None)
    else:
        if args.mesh:
            d, s, m = (int(x) for x in args.mesh.split(","))
            mcfg = MeshConfig(data=d, seq=s, model=m)
        else:
            mcfg = MeshConfig(data=n_dev)
        mesh = create_mesh(mcfg, devices=jax.devices()[: mcfg.size])
        log.info("mesh: data=%d seq=%d model=%d on %d %s device(s)",
                 mcfg.data, mcfg.seq, mcfg.model, mcfg.size,
                 jax.devices()[0].platform)

        tc = TrainConfig(learning_rate=args.lr, remat=args.remat,
                         ring_attention=args.ring_attention)
        state = create_train_state(jax.random.PRNGKey(0), cfg, tc)
        if args.resume:
            # Full train state: params + AdamW moments + step, so
            # resumption continues the run instead of restarting the
            # optimizer.
            restored = restore_checkpoint(
                args.resume,
                like={"params": state.params, "opt_state": state.opt_state,
                      "step": 0},
            )
            state.params = restored["params"]
            state.opt_state = restored["opt_state"]
            step0 = int(restored["step"])
            log.info("resumed from %s at step %d", args.resume, step0)
        state = shard_train_state(state, mesh)
        step_fn = make_train_step(cfg, tc, mesh=mesh)
        token_spec = data_spec()
        params, opt_state = state.params, state.opt_state

    if args.data:
        corpus = np.load(args.data, mmap_mode="r")
        if corpus.size < args.seq_len:
            log.error("corpus has %d tokens but --seq-len is %d",
                      corpus.size, args.seq_len)
            return 1
        log.info("corpus: %d tokens from %s", corpus.size, args.data)
    else:
        corpus = None
        log.info("no --data given: synthetic random tokens")

    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, token_spec)
    # Seed the data stream with the restored step: a resumed run continues
    # the stream instead of replaying the batch windows already trained on
    # (advisor r3).
    rng = np.random.default_rng(args.seed + step0)
    B, S = args.batch, args.seq_len

    def next_batch() -> jax.Array:
        if corpus is not None:
            starts = rng.integers(0, corpus.size - S + 1, size=B)
            batch = np.stack([corpus[st:st + S] for st in starts])
        else:
            batch = rng.integers(0, cfg.vocab_size, size=(B, S))
        return jax.device_put(batch.astype(np.int32), sharding)

    t0 = time.monotonic()
    tokens_seen = 0
    last = step0 + args.steps
    for step in range(step0 + 1, last + 1):
        params, opt_state, loss = step_fn(params, opt_state, next_batch())
        tokens_seen += B * S
        if step % args.log_every == 0 or step == last:
            loss = float(loss)
            dt = time.monotonic() - t0
            log.info("step %d/%d loss %.4f | %.0f tok/s",
                     step, last, loss, tokens_seen / max(dt, 1e-9))
        if args.ckpt_dir and (step % args.ckpt_every == 0 or step == last):
            path = f"{args.ckpt_dir}/step_{step}"
            save_checkpoint(path, jax.device_get(
                {"params": params, "opt_state": opt_state, "step": step}))
            log.info("checkpoint saved: %s", path)
    return 0


if __name__ == "__main__":
    sys.exit(main())
