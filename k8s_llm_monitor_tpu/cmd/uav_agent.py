"""UAV agent entrypoint (per-node DaemonSet process).

Parity target: ``/root/reference/cmd/uav-agent/main.go:22-63`` — flags
``-port``/``-master-url``/``-report-interval`` with env fallbacks
``MASTER_URL``/``REPORT_INTERVAL``/``NODE_NAME``/``NODE_IP`` (the
DaemonSet injects node identity via fieldRef, ref
deployments/uav-agent-daemonset.yaml).
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import sys
import threading


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description="per-node UAV telemetry agent")
    parser.add_argument("--port", type=int, default=9090)
    parser.add_argument("--master-url", default=os.environ.get("MASTER_URL", ""))
    parser.add_argument(
        "--report-interval",
        type=float,
        default=float(os.environ.get("REPORT_INTERVAL", "10")),
    )
    parser.add_argument("--node-name", default=os.environ.get("NODE_NAME", ""))
    parser.add_argument("--node-ip", default=os.environ.get("NODE_IP", ""))
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s %(levelname)s %(name)s %(message)s"
    )
    log = logging.getLogger("cmd.uav-agent")
    node_name = args.node_name or os.uname().nodename

    from k8s_llm_monitor_tpu.monitor.agent import UAVAgent

    agent = UAVAgent(
        node_name=node_name,
        node_ip=args.node_ip,
        port=args.port,
        master_url=args.master_url,
        report_interval=args.report_interval,
    )
    agent.start()
    log.info(
        "uav-agent on %s: telemetry :%d, reporting to %s every %.0fs",
        node_name,
        agent.port,
        args.master_url or "<disabled>",
        args.report_interval,
    )

    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    stop.wait()
    log.info("shutting down uav-agent...")
    agent.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
