"""CLI smoke-test harness against a cluster backend.

Parity target: ``/root/reference/cmd/test-k8s/main.go:44-185`` —
connection test, cluster info, pod/service/event listings, a network
analysis between the first two pods, and a 10 s watch with a counting
event handler (``TestEventHandler``, main.go:16-42).
"""

from __future__ import annotations

import argparse
import logging
import sys
import time


class CountingHandler:
    """ref cmd/test-k8s/main.go:16-42."""

    def __init__(self) -> None:
        self.pod_events = 0
        self.service_events = 0
        self.events = 0
        self.crd_events = 0

    def on_pod_update(self, event_type, pod):
        self.pod_events += 1
        print(f"  [watch] pod {event_type}: {pod.namespace}/{pod.name}")

    def on_service_update(self, event_type, service):
        self.service_events += 1
        print(f"  [watch] service {event_type}: {service.namespace}/{service.name}")

    def on_event(self, event):
        self.events += 1
        print(f"  [watch] event: {event.reason} - {event.message}")

    def on_crd_event(self, event):
        self.crd_events += 1
        print(f"  [watch] CRD {event.type}: {event.kind}/{event.name}")


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description="cluster access smoke test")
    parser.add_argument("--config", default="")
    parser.add_argument("--cluster", choices=("fake", "kube"), default="fake")
    parser.add_argument("--kubeconfig", default="")
    parser.add_argument("--watch-seconds", type=float, default=10.0)
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.WARNING)

    from k8s_llm_monitor_tpu.monitor.client import Client
    from k8s_llm_monitor_tpu.monitor.config import load_config
    from k8s_llm_monitor_tpu.monitor.network import NetworkAnalyzer
    from k8s_llm_monitor_tpu.monitor.watcher import Watcher

    config = load_config(args.config or None)
    if args.cluster == "fake":
        from k8s_llm_monitor_tpu.monitor.cluster import FakeCluster, seed_demo_cluster

        backend = seed_demo_cluster(FakeCluster())
    else:
        from k8s_llm_monitor_tpu.monitor.kube_rest import KubeRestBackend

        backend = KubeRestBackend.from_kubeconfig(
            args.kubeconfig or config.k8s.kubeconfig or None
        )
    client = Client(backend, namespaces=config.k8s.watch_namespaces)

    print("=== 1. connection ===")
    version = client.test_connection()
    print(f"  connected: {version}")

    print("=== 2. cluster info ===")
    info = client.get_cluster_info()
    print(f"  {info}")

    print("=== 3. pods ===")
    pods = []
    for ns in client.namespaces():
        for p in client.get_pods(ns):
            pods.append(p)
            print(f"  {p.namespace}/{p.name} [{p.status}] on {p.node_name} ip={p.ip}")

    print("=== 4. services ===")
    for ns in client.namespaces():
        for s in client.get_services(ns):
            ports = ",".join(str(pp.port) for pp in s.ports)
            print(f"  {s.namespace}/{s.name} {s.type} {s.cluster_ip}:{ports}")

    print("=== 5. events ===")
    for ns in client.namespaces():
        for e in client.get_events(ns, limit=10):
            print(f"  [{e.type}] {e.reason}: {e.message}")

    if len(pods) >= 2:
        print("=== 6. network analysis (first two pods) ===")
        a, b = pods[0], pods[1]
        analysis = NetworkAnalyzer(client).analyze_pod_communication(
            f"{a.namespace}/{a.name}", f"{b.namespace}/{b.name}"
        )
        print(f"  status={analysis.status} confidence={analysis.confidence}")
        for issue in analysis.issues:
            print(f"  issue: {issue}")
        for sol in analysis.solutions:
            print(f"  solution: {sol}")

    print(f"=== 7. watching for {args.watch_seconds:.0f}s ===")
    handler = CountingHandler()
    watcher = Watcher(client, handler)
    watcher.start()
    time.sleep(args.watch_seconds)
    watcher.stop()
    print(
        f"  watch summary: pods={handler.pod_events} services="
        f"{handler.service_events} events={handler.events}"
    )
    print("=== all checks passed ===")
    return 0


if __name__ == "__main__":
    sys.exit(main())
