"""Demo walkthroughs, one subcommand per reference demo binary.

Parity target: ``/root/reference/cmd/demos/`` — ``debug-test`` (annotated
8-step stack walkthrough), ``live-monitor`` (continuous change stream +
stats ticker), ``network-demo`` (pod-communication analysis over the first
two pods), ``crd-demo`` (CRD discovery + CR event stream), ``rtt-demo``
(direct RTT probe).

Usage: ``python -m k8s_llm_monitor_tpu.cmd.demo <name> [--seconds N]``
All demos run against the in-memory demo cluster by default so they work
on any laptop (the reference needs k3d for the same experience).
"""

from __future__ import annotations

import argparse
import sys
import threading
import time


def _client(args):
    from k8s_llm_monitor_tpu.monitor.client import Client
    from k8s_llm_monitor_tpu.monitor.cluster import FakeCluster, seed_demo_cluster

    if args.cluster == "kube":
        from k8s_llm_monitor_tpu.monitor.config import load_config
        from k8s_llm_monitor_tpu.monitor.kube_rest import KubeRestBackend

        cfg = load_config(None)
        backend = KubeRestBackend.from_kubeconfig(args.kubeconfig or None)
        return Client(backend, namespaces=cfg.k8s.watch_namespaces + ["kube-system"]), backend
    fake = seed_demo_cluster(FakeCluster())
    return Client(fake, namespaces=["default", "kube-system"]), fake


class _PrintingHandler:
    def on_pod_update(self, et, pod):
        print(f"[pod {et}] {pod.namespace}/{pod.name} status={pod.status}")

    def on_service_update(self, et, svc):
        print(f"[service {et}] {svc.namespace}/{svc.name}")

    def on_event(self, ev):
        print(f"[event] {ev.reason}: {ev.message}")

    def on_crd_event(self, ev):
        print(f"[crd {ev.type}] {ev.kind} {ev.namespace}/{ev.name}")


def demo_debug_test(args) -> None:
    """Step-by-step walkthrough (ref cmd/demos/debug-test)."""
    client, fake = _client(args)
    print("step 1: connect ->", client.test_connection())
    print("step 2: cluster info ->", client.get_cluster_info())
    print("step 3: pods ->", [p.name for p in client.get_pods("default")])
    print("step 4: services ->", [s.name for s in client.get_services("default")])
    print("step 5: events ->", [e.reason for e in client.get_events("default", 5)])
    print("step 6: CRDs ->", [c["metadata"]["name"] for c in client.backend.list_crds()])
    from k8s_llm_monitor_tpu.monitor.watcher import Watcher

    print(f"step 7: watching for {args.seconds:.0f}s...")
    w = Watcher(client, _PrintingHandler())
    w.start()
    if args.cluster == "fake":
        fake.add_pod("debug-demo-pod", node="k3d-demo-agent-0")
    time.sleep(args.seconds)
    w.stop()
    print("step 8: done")


def demo_live_monitor(args) -> None:
    """Continuous change stream + stats ticker (ref cmd/demos/live-monitor)."""
    client, fake = _client(args)
    from k8s_llm_monitor_tpu.monitor.watcher import CRDWatcher, Watcher

    handler = _PrintingHandler()
    w = Watcher(client, handler)
    cw = CRDWatcher(client, handler)
    w.start()
    cw.start()

    stop = threading.Event()

    def stats():
        while not stop.wait(min(30.0, args.seconds / 2 or 5)):
            info = client.get_cluster_info()
            print(f"[stats] nodes={info['nodes']} pods={info['pods']}")

    t = threading.Thread(target=stats, daemon=True)
    t.start()
    if args.cluster == "fake":
        fake.add_pod("live-pod-1", node="k3d-demo-agent-0")
        time.sleep(args.seconds / 3)
        fake.update_pod("default", "live-pod-1", phase="Failed")
        fake.add_event(type_="Warning", reason="Failed", message="demo failure")
    time.sleep(args.seconds)
    stop.set()
    w.stop()
    cw.stop()


def demo_network(args) -> None:
    """Pod-communication analysis over the first two pods
    (ref cmd/demos/network-demo)."""
    client, _ = _client(args)
    from k8s_llm_monitor_tpu.monitor.network import NetworkAnalyzer

    pods = client.get_pods("default")
    if len(pods) < 2:
        print("need at least two pods in default")
        return
    a, b = pods[0], pods[1]
    print(f"analyzing {a.name} <-> {b.name} ...")
    res = NetworkAnalyzer(client).analyze_pod_communication(
        f"default/{a.name}", f"default/{b.name}"
    )
    print(f"status: {res.status} (confidence {res.confidence})")
    for i in res.issues:
        print(f"  issue: {i}")
    for s in res.solutions:
        print(f"  solution: {s}")


def demo_crd(args) -> None:
    """CRD discovery + CR stream (ref cmd/demos/crd-demo:107-141)."""
    client, fake = _client(args)
    from k8s_llm_monitor_tpu.monitor.watcher import CRDWatcher

    cw = CRDWatcher(client, _PrintingHandler())
    cw.start()
    time.sleep(0.2)
    print("established CRDs:")
    for crd in cw.get_crds():
        print(f"  {crd.name} (kind={crd.kind}, scope={crd.scope})")
    if args.cluster == "fake":
        from k8s_llm_monitor_tpu.monitor.models import UAVReport

        client.upsert_uav_metric(
            "",
            UAVReport(node_name="demo-node", uav_id="uav-demo",
                      state={"battery": {"remaining_percent": 88.0}}),
        )
    time.sleep(args.seconds)
    cw.stop()


def demo_rtt(args) -> None:
    """Direct RTT probe (ref cmd/demos/rtt-demo)."""
    client, _ = _client(args)
    from k8s_llm_monitor_tpu.monitor.rtt import RTTTester

    pods = client.get_pods("default")
    if len(pods) < 2:
        print("need at least two pods in default")
        return
    a, b = pods[0], pods[1]
    res = RTTTester(client).test_pod_connectivity(
        f"default/{a.name}", f"default/{b.name}"
    )
    print(f"{a.name} <-> {b.name}:")
    for r in res.rtt_results:
        status = f"{r.rtt_ms:.2f}ms" if r.success else f"FAILED ({r.error_message})"
        print(f"  {r.method}: {status}")
    print(
        f"  avg {res.average_rtt_ms:.2f}ms, success {res.success_rate:.0f}%, "
        f"grade {res.latency_assessment}"
    )


DEMOS = {
    "debug-test": demo_debug_test,
    "live-monitor": demo_live_monitor,
    "network-demo": demo_network,
    "crd-demo": demo_crd,
    "rtt-demo": demo_rtt,
}


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description="framework demos")
    parser.add_argument("demo", choices=sorted(DEMOS))
    parser.add_argument("--seconds", type=float, default=5.0)
    parser.add_argument("--cluster", choices=("fake", "kube"), default="fake")
    parser.add_argument("--kubeconfig", default="")
    args = parser.parse_args(argv)
    DEMOS[args.demo](args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
