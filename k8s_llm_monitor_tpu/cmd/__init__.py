"""Executable entrypoints (``python -m k8s_llm_monitor_tpu.cmd.<name>``).

Parity with the reference's cmd/ tree (``/root/reference/cmd/``):
``server`` (cmd/server), ``uav_agent`` (cmd/uav-agent), ``scheduler``
(cmd/scheduler), ``test_k8s`` (cmd/test-k8s), ``demo`` (the five
cmd/demos/* walkthroughs as subcommands).
"""
