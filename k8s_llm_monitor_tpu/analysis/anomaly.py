"""Embedding anomaly detector over cluster events and logs.

Embeds text (events, log lines, symptom strings) with the BERT-family
encoder (models/encoder.py, BASELINE.md config #3 — BGE-large on TPU) and
flags semantic outliers by cosine distance from the batch centroid.  This
upgrades the reference's thresholds-only anomaly surface (reference
internal/metrics/manager.go:546-564 — fixed 80 %/90 % utilisation rules)
with a content-aware signal: a burst of novel error text stands out even
when every numeric gauge looks healthy.

Batches are padded to power-of-two (B, S) buckets so the jitted encoder
compiles a handful of shapes; the detector is CPU-tolerant (tiny encoder,
tests) and TPU-ready (BGE-large weights via models/encoder.load_hf_encoder).
"""

from __future__ import annotations

import re
import zlib
from typing import Sequence

import numpy as np

import jax
import jax.numpy as jnp

from k8s_llm_monitor_tpu.models import encoder
from k8s_llm_monitor_tpu.models.config import ENCODER_PRESETS, EncoderConfig

_WORD_RE = re.compile(r"[a-z0-9]+")


class HashingTokenizer:
    """Deterministic hashing word tokenizer for checkpoint-less encoders.

    ids: 0 = pad, 1 = CLS, 2 = SEP, then crc32(word) hashed into the rest
    of the vocab.  Stable across processes (unlike builtin ``hash``), which
    keeps embeddings comparable between runs.
    """

    def __init__(self, vocab_size: int):
        self.vocab_size = vocab_size

    def encode(self, text: str, max_len: int) -> list[int]:
        words = _WORD_RE.findall(text.lower())[: max_len - 2]
        body = [3 + zlib.crc32(w.encode()) % (self.vocab_size - 3)
                for w in words]
        return [1] + body + [2]


class EmbeddingAnomalyDetector:
    """Embed texts; score each by cosine distance from the centroid."""

    MAX_SEQ = 256

    def __init__(
        self,
        cfg: EncoderConfig | None = None,
        params=None,
        tokenizer=None,
        *,
        pooling: str = "cls",
        seed: int = 0,
    ) -> None:
        self.cfg = cfg or ENCODER_PRESETS["tiny-encoder"]
        if params is None:
            params = encoder.init_params(jax.random.PRNGKey(seed), self.cfg)
        self.params = params
        self.tokenizer = tokenizer or HashingTokenizer(self.cfg.vocab_size)
        self.pooling = pooling
        ecfg = self.cfg

        def _encode(params, tokens, mask):
            return encoder.encode(params, ecfg, tokens, mask, pooling=pooling)

        self._encode = jax.jit(_encode)

    @classmethod
    def from_checkpoint(cls, path: str, **kw) -> "EmbeddingAnomalyDetector":
        """BGE-large (or any BertModel) checkpoint directory; uses the HF
        tokenizer when available."""
        cfg, params = encoder.load_hf_encoder(path)
        tokenizer = None
        try:
            from transformers import AutoTokenizer

            hf_tok = AutoTokenizer.from_pretrained(path)

            class _HFTok:
                def encode(self, text: str, max_len: int) -> list[int]:
                    return hf_tok.encode(text, truncation=True,
                                         max_length=max_len)

            tokenizer = _HFTok()
        except Exception:  # noqa: BLE001 — hashing fallback
            tokenizer = None
        return cls(cfg, params, tokenizer, **kw)

    # -- embedding ------------------------------------------------------

    @staticmethod
    def _pow2(n: int, cap: int) -> int:
        p = 1
        while p < n:
            p *= 2
        return min(p, cap)

    def embed(self, texts: Sequence[str]) -> np.ndarray:
        """[N, H] float32 L2-normalized embeddings."""
        if not texts:
            return np.zeros((0, self.cfg.hidden_size), np.float32)
        ids = [self.tokenizer.encode(t, self.MAX_SEQ) for t in texts]
        S = self._pow2(max(len(x) for x in ids), self.MAX_SEQ)
        B = self._pow2(len(ids), 1 << 30)
        tokens = np.zeros((B, S), np.int32)
        mask = np.zeros((B, S), np.int32)
        for i, x in enumerate(ids):
            x = x[:S]
            tokens[i, : len(x)] = x
            mask[i, : len(x)] = 1
        # padding rows need >= 1 unmasked token to keep softmax finite
        mask[len(ids):, 0] = 1
        out = self._encode(self.params, jnp.asarray(tokens), jnp.asarray(mask))
        return np.asarray(out)[: len(ids)]

    # -- scoring --------------------------------------------------------

    def score(self, texts: Sequence[str]) -> list[float]:
        """Cosine distance of each text from the batch centroid (0 = at the
        centroid, up to 2 = antipodal)."""
        emb = self.embed(texts)
        if len(emb) == 0:
            return []
        centroid = emb.mean(axis=0)
        norm = np.linalg.norm(centroid)
        if norm < 1e-9:
            return [0.0] * len(emb)
        centroid = centroid / norm
        return [float(1.0 - e @ centroid) for e in emb]

    def flag_outliers(
        self,
        texts: Sequence[str],
        threshold: float | None = None,
    ) -> list[tuple[int, float]]:
        """Indices + scores of semantic outliers.

        Default threshold combines a z-score cut (mean + 2*std) with a
        relative cut (2x the median distance), which makes it scale-free:
        embedding geometries differ wildly between trained and random
        encoders (random BERTs are strongly anisotropic — all scores tiny),
        so no absolute distance floor works for both.  Needs >= 4 texts for
        a meaningful distribution; fewer returns [].
        """
        if len(texts) < 4:
            return []
        scores = self.score(texts)
        if threshold is None:
            arr = np.asarray(scores)
            threshold = max(
                float(arr.mean() + 2.0 * arr.std()),
                2.0 * float(np.median(arr)),
                1e-9,
            )
        return [(i, s) for i, s in enumerate(scores) if s > threshold]
