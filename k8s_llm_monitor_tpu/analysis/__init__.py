"""Embedding-based analysis: the anomaly detector over cluster text streams."""

from k8s_llm_monitor_tpu.analysis.anomaly import EmbeddingAnomalyDetector

__all__ = ["EmbeddingAnomalyDetector"]
