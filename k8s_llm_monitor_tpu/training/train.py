"""GSPMD-sharded training step (next-token cross-entropy + AdamW).

TPU-first design:
  - One pure ``train_step`` jitted once; parallelism comes entirely from
    sharding annotations on the inputs (params TP over ``model``, batch DP
    over ``data``, sequence sharding over ``seq``).  XLA inserts the
    gradient psums and attention collectives — there is no hand-written
    collective here.
  - Optional rematerialisation (``jax.checkpoint``) over the model forward
    trades FLOPs for HBM on long sequences.
  - Optimizer state is built *from the sharded params*, so it inherits the
    same layout and the update is fully local except the psums XLA derives.

The reference has no training path to mirror; the capability target is the
framework north star (SURVEY.md §7), not a reference file.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from k8s_llm_monitor_tpu.models import llama
from k8s_llm_monitor_tpu.models.config import ModelConfig
from k8s_llm_monitor_tpu.parallel.sharding import param_partition_specs

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    learning_rate: float = 3e-4
    weight_decay: float = 0.1
    b1: float = 0.9
    b2: float = 0.95
    grad_clip: float = 1.0
    # Recompute the per-layer forward during backward (saves activation HBM
    # at ~30% extra FLOPs — the standard long-context trade on TPU).
    remat: bool = False
    # Explicit ring attention over the mesh's ``seq`` axis (shard_map +
    # ppermute) instead of GSPMD-derived collectives — O(S/n) activation
    # memory per device for long sequences.  Needs make_train_step(mesh=...).
    ring_attention: bool = False


@dataclasses.dataclass
class TrainState:
    params: Params
    opt_state: optax.OptState
    step: int = 0


def make_optimizer(tc: TrainConfig) -> optax.GradientTransformation:
    return optax.chain(
        optax.clip_by_global_norm(tc.grad_clip),
        optax.adamw(
            learning_rate=tc.learning_rate,
            b1=tc.b1,
            b2=tc.b2,
            weight_decay=tc.weight_decay,
        ),
    )


def create_train_state(
    rng: jax.Array, cfg: ModelConfig, tc: TrainConfig | None = None
) -> TrainState:
    tc = tc or TrainConfig()
    params = llama.init_params(rng, cfg)
    opt = make_optimizer(tc)
    return TrainState(params=params, opt_state=opt.init(params), step=0)


def shard_train_state(state: TrainState, mesh: Mesh) -> TrainState:
    """Device-put params with TP sharding; opt state inherits via re-init
    layout (moments mirror the param pytree, scalars replicate)."""
    pspecs = param_partition_specs(state.params)

    def put(x, s):
        return jax.device_put(x, NamedSharding(mesh, s))

    params = jax.tree.map(put, state.params, pspecs)

    def put_opt(node):
        # Adam moments mirror the param pytree structurally, so shard them
        # with the param specs (shape matching is ambiguous: q and o
        # projections are both [H, H]); counts/scales replicate.
        if isinstance(node, optax.ScaleByAdamState):
            return optax.ScaleByAdamState(
                count=put(node.count, P()),
                mu=jax.tree.map(put, node.mu, pspecs),
                nu=jax.tree.map(put, node.nu, pspecs),
            )
        return put(node, P()) if hasattr(node, "shape") else node

    opt_state = jax.tree.map(
        put_opt,
        state.opt_state,
        is_leaf=lambda x: isinstance(x, optax.ScaleByAdamState),
    )
    return TrainState(params=params, opt_state=opt_state, step=state.step)


def next_token_loss(
    params: Params, cfg: ModelConfig, tokens: jnp.ndarray,
    loss_mask: jnp.ndarray | None = None,
    attn_fn=None,
) -> jnp.ndarray:
    """Mean next-token cross-entropy over ``tokens`` [B, S] int32.

    MoE configs add 0.01 x the router load-balancing aux loss (the Switch
    Transformer coefficient) so training pressure keeps experts utilized.
    """
    # Forward the full sequence and drop the last position's logits (rather
    # than slicing the input) so S keeps its seq-axis divisibility for the
    # ring-attention path; the extra position costs 1/S more compute.
    moe = cfg.num_experts > 0
    out = llama.forward_full(
        params, cfg, tokens, attn_fn=attn_fn, return_aux=moe)
    logits, aux = out if moe else (out, 0.0)
    logits = logits[:, :-1]                            # [B, S-1, V]
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if loss_mask is not None:
        mask = loss_mask[:, 1:].astype(jnp.float32)
        return (-jnp.sum(ll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
                + 0.01 * aux)
    return -jnp.mean(ll) + 0.01 * aux


def make_train_step(
    cfg: ModelConfig, tc: TrainConfig | None = None, mesh: Mesh | None = None
) -> Callable:
    """Build the jitted train step: (params, opt_state, tokens) ->
    (params, opt_state, loss).

    Call with sharded inputs; GSPMD propagates the layout through grads and
    the optimizer update (grad psum over ``data``, TP-local AdamW).  With
    ``tc.ring_attention`` and a mesh whose ``seq`` axis is nontrivial, the
    forward uses explicit ring attention (parallel/ring_attention.py)."""
    tc = tc or TrainConfig()
    opt = make_optimizer(tc)

    attn_fn = None
    if tc.ring_attention and mesh is not None and mesh.shape["seq"] > 1:
        if cfg.has_attn_extras:
            raise ValueError(
                "ring attention does not support Gemma-style attention "
                "extras (softcap / sliding window / custom query scale) — "
                "train these configs without --ring-attention")
        from k8s_llm_monitor_tpu.parallel.ring_attention import (
            make_ring_attention,
        )

        attn_fn = make_ring_attention(mesh)

    def loss_fn(params, tokens):
        return next_token_loss(params, cfg, tokens, attn_fn=attn_fn)

    if tc.remat:
        loss_fn = jax.checkpoint(loss_fn)

    def train_step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(train_step, donate_argnums=(0, 1))


def data_spec() -> P:
    """Token batch sharding: batch over ``data``, sequence over ``seq``."""
    return P("data", "seq")
