"""Training: pjit/GSPMD train step for the decoder LM family.

The reference has no training of any kind (its LLM layer is config keys,
reference internal/config/config.go:141-145); this package exists for the
north-star obligation of a complete TPU framework — fine-tuning the
diagnosis model on cluster-incident transcripts runs through the same
sharded forward as serving.
"""

from k8s_llm_monitor_tpu.training.train import (  # noqa: F401
    TrainConfig,
    TrainState,
    create_train_state,
    make_train_step,
    shard_train_state,
)
