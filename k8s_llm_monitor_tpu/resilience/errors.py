"""Admission-control errors shared by the serving and HTTP layers.

``OverloadedError`` is raised deep in the serving stack (load shedding,
drain, supervisor rebuild) but must be *mapped* by the HTTP layer — 429
for retriable sheds, 503 when this replica is going away — with a
``Retry-After`` header derived from the backoff hint.  It lives here,
stdlib-only, so ``monitor/server.py`` can import it without pulling the
jax-backed serving modules; ``serving/service.py`` re-exports it for
compatibility.
"""

from __future__ import annotations


class OverloadedError(Exception):
    """Admission refused by load shedding, drain, or an engine rebuild.

    Retriable: the caller should back off ``retry_after_s`` and retry
    (the HTTP layer maps this to 429 with a Retry-After header); when
    ``retriable`` is False this replica is going away and the client
    should retry against another replica (503).  Carries the backlog
    evidence so clients and logs see *why* they were shed.
    """

    def __init__(self, reason: str, queue_depth: int = 0,
                 queue_tokens: int = 0, retriable: bool = True,
                 retry_after_s: float = 1.0, slo_class: str = "",
                 request_id: str = "", tenant: str = ""):
        super().__init__(
            f"overloaded: {reason} "
            f"(queue_depth={queue_depth}, queue_tokens={queue_tokens})")
        self.reason = reason
        self.queue_depth = queue_depth
        self.queue_tokens = queue_tokens
        self.retriable = retriable
        self.retry_after_s = retry_after_s
        # Tenant the refusal is charged to ('' when the shedding layer is
        # tenant-unaware): the HTTP layer echoes it in the 429 body so a
        # rate-limited tenant can see the quota is *theirs*, not global.
        self.tenant = tenant
        # SLO class of the shed request ('' when the shed predates class
        # plumbing or the layer doesn't know): clients use it to pick the
        # per-class backoff lane, the HTTP layer echoes it in the 429 body.
        self.slo_class = slo_class
        # Request id assigned before the shed decision, echoed in the
        # 429/503 body so the refusal is joinable with traces and the
        # journal ('' when the shedding layer has no id to give).
        self.request_id = request_id
