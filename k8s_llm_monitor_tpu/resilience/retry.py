"""Jittered exponential backoff with a retry budget + a circuit breaker.

One retry discipline for every remote dependency: ``KubeRestBackend``
requests retry through :class:`Backoff`, the watcher reconnect loops reuse
the same curve (replacing their fixed 5 s sleeps), and a shared
:class:`CircuitBreaker` stops a 5xx storm from turning every poll thread
into a retry hammer against a struggling apiserver.

Determinism: both classes take an injectable ``clock`` / ``rng`` so chaos
tests replay identically and never sleep real wall-clock time.
"""

from __future__ import annotations

import random
import time

from k8s_llm_monitor_tpu.devtools.lockcheck import guarded_by, make_lock


class Backoff:
    """Jittered exponential delay schedule with a bounded attempt budget.

    ``delays()`` yields ``base * mult^i`` capped at ``cap``, each scaled by
    a uniform jitter in [1-jitter, 1+jitter] — full determinism comes from
    the injected ``rng``.  ``attempts`` counts the *total* tries (first try
    + retries), so ``attempts=3`` means at most 2 delays.
    """

    def __init__(self, base_s: float = 0.2, cap_s: float = 30.0,
                 mult: float = 2.0, jitter: float = 0.2,
                 attempts: int = 4, rng: random.Random | None = None):
        if attempts < 1:
            raise ValueError("attempts must be >= 1")
        self.base_s = base_s
        self.cap_s = cap_s
        self.mult = mult
        self.jitter = jitter
        self.attempts = attempts
        self._rng = rng or random.Random()

    def delay(self, retry_index: int) -> float:
        """Delay before retry ``retry_index`` (0-based)."""
        raw = min(self.base_s * (self.mult ** retry_index), self.cap_s)
        if self.jitter > 0:
            raw *= 1.0 + self._rng.uniform(-self.jitter, self.jitter)
        return max(raw, 0.0)

    def delays(self):
        """The (attempts - 1) inter-try delays, in order."""
        for i in range(self.attempts - 1):
            yield self.delay(i)


class CircuitOpen(Exception):
    """Raised when a call is refused because the breaker is open."""

    def __init__(self, remaining_s: float):
        super().__init__(
            f"circuit open ({remaining_s:.1f}s until half-open probe)")
        self.remaining_s = remaining_s


@guarded_by("_lock", "_consecutive", "_opened_at", "_probing",
            "trips", "rejections")
class CircuitBreaker:
    """Consecutive-failure breaker with a half-open probe.

    closed  → normal operation; ``failure_threshold`` consecutive failures
              trip it open.
    open    → calls raise :class:`CircuitOpen` for ``cooldown_s``.
    half-open → after cooldown ONE probe call is let through; success
              closes the breaker, failure re-opens it for another cooldown.

    Thread-safe: poll threads, watch threads and HTTP handlers share one
    breaker per backend.
    """

    def __init__(self, failure_threshold: int = 5, cooldown_s: float = 10.0,
                 clock=time.monotonic):
        self.failure_threshold = failure_threshold
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._consecutive = 0
        self._opened_at: float | None = None
        self._probing = False
        self.trips = 0           # times the breaker opened
        self.rejections = 0      # calls refused while open
        # Created last: lockcheck's guarded_by treats writes before the
        # lock exists as construction, not races.
        self._lock = make_lock("resilience.breaker")

    @property
    def state(self) -> str:
        with self._lock:
            return self._state_locked()

    def _state_locked(self) -> str:
        if self._opened_at is None:
            return "closed"
        if self._clock() - self._opened_at >= self.cooldown_s:
            return "half-open"
        return "open"

    def before_call(self) -> None:
        """Gate a call: raises :class:`CircuitOpen` when refusing. In the
        half-open state exactly one caller wins the probe slot; the rest
        are refused until the probe resolves."""
        with self._lock:
            st = self._state_locked()
            if st == "closed":
                return
            if st == "half-open" and not self._probing:
                self._probing = True
                return
            self.rejections += 1
            remaining = 0.0
            if self._opened_at is not None:
                remaining = max(
                    0.0, self.cooldown_s - (self._clock() - self._opened_at))
            raise CircuitOpen(remaining)

    def record_success(self) -> None:
        with self._lock:
            self._consecutive = 0
            self._opened_at = None
            self._probing = False

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive += 1
            if self._probing:
                # Failed probe: re-open for a fresh cooldown.
                self._probing = False
                self._opened_at = self._clock()
                self.trips += 1
            elif (self._opened_at is None
                    and self._consecutive >= self.failure_threshold):
                self._opened_at = self._clock()
                self.trips += 1
