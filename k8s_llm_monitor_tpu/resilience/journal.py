"""Append-only request journal (WAL) for crash-safe serving.

Every admitted generation request is journaled *before* it reaches the
engine; emitted tokens are checkpointed as they stream off the device and
a tombstone marks completion.  After a crash — step-loop death, pod
eviction, SIGKILL mid-write — the recovery scanner reconstructs exactly
which requests were accepted but never finished and how many tokens each
already delivered, so the supervisor (serving/supervisor.py) can re-admit
them with the already-streamed tokens trimmed off.  The invariant this
file carries: **no accepted request is ever silently lost.**

On-disk format (one directory, numbered segments ``wal-<n>.log``):

    record  := type(u8) length(u32 LE) crc(u32 LE) payload
    payload := compact JSON (utf-8), length bytes
    crc     := crc32(type_byte + payload)

Record types: ADMIT (id, prompt token ids, sampling, deadline, arrival
wall-clock), PROGRESS (id, newly emitted token ids), COMPLETE (tombstone),
SEAL (clean close marker).  The scanner tolerates a torn or truncated
tail — a short header, an absurd length, a CRC mismatch or undecodable
payload ends that segment's scan without raising and without applying the
corrupt record.

Rotation + compaction: the active segment rolls over at
``segment_max_bytes``; any sealed-off segment referenced by no live
(incomplete) request holds only tombstoned history and is deleted.

Fsync policy (``fsync=``): ``always`` fsyncs every record (safest,
slowest), ``interval`` fsyncs at most every ``fsync_interval_s`` (default;
bounded loss window), ``never`` only flushes to the OS (CI speed — set via
``K8SLLM_JOURNAL_FSYNC=never``).

Stdlib-only and clock-injectable, like the rest of this package.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import re
import struct
import time
import zlib
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Iterable

from k8s_llm_monitor_tpu.devtools.lockcheck import make_lock

logger = logging.getLogger("resilience.journal")

# Record types.
ADMIT = 1
PROGRESS = 2
COMPLETE = 3
SEAL = 4

_HEADER = struct.Struct("<BII")  # type, payload length, crc32
# A length beyond this is treated as tail corruption, not a real record
# (the largest legitimate payload is a full prompt's token ids).
_MAX_PAYLOAD = 1 << 26

_SEGMENT_RE = re.compile(r"^wal-(\d{8})\.log$")

FSYNC_POLICIES = ("always", "interval", "never")


@dataclass
class JournaledRequest:
    """One request's reconstructed state after a journal scan."""

    request_id: str
    prompt_ids: list[int] = field(default_factory=list)
    sampling: dict[str, Any] = field(default_factory=dict)
    deadline_s: float = 0.0
    arrival_unix: float = 0.0
    emitted: list[int] = field(default_factory=list)
    completed: bool = False
    # SLO class (resilience/slo.py); pre-class WALs default to "standard".
    slo_class: str = "standard"
    # Tenant namespace (resilience/tenancy.py); pre-tenancy WALs default
    # to "public" — replay restores per-tenant quota reservations from
    # this field, so a crash cannot launder one tenant's quota into
    # another's.
    tenant: str = "public"


def _pack(rtype: int, payload: dict[str, Any]) -> bytes:
    body = json.dumps(payload, separators=(",", ":")).encode()
    crc = zlib.crc32(bytes((rtype,)) + body) & 0xFFFFFFFF
    return _HEADER.pack(rtype, len(body), crc) + body


def _iter_records(data: bytes, path: str) -> Iterable[tuple[int, dict]]:
    """Yield (type, payload) records; stop silently at a torn tail."""
    off = 0
    while off < len(data):
        if off + _HEADER.size > len(data):
            logger.warning("journal %s: truncated header at byte %d "
                           "(torn tail, %d byte(s) dropped)",
                           path, off, len(data) - off)
            return
        rtype, length, crc = _HEADER.unpack_from(data, off)
        body_start = off + _HEADER.size
        if length > _MAX_PAYLOAD or body_start + length > len(data):
            logger.warning("journal %s: truncated record at byte %d "
                           "(torn tail)", path, off)
            return
        body = data[body_start:body_start + length]
        if zlib.crc32(bytes((rtype,)) + body) & 0xFFFFFFFF != crc:
            logger.warning("journal %s: CRC mismatch at byte %d — dropping "
                           "the rest of the segment", path, off)
            return
        try:
            payload = json.loads(body)
        except ValueError:
            logger.warning("journal %s: undecodable payload at byte %d — "
                           "dropping the rest of the segment", path, off)
            return
        if not isinstance(payload, dict):
            logger.warning("journal %s: non-object payload at byte %d — "
                           "dropping the rest of the segment", path, off)
            return
        yield rtype, payload
        off = body_start + length


def _segment_paths(directory: Path) -> list[tuple[int, Path]]:
    out = []
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return []
    for name in names:
        m = _SEGMENT_RE.match(name)
        if m:
            out.append((int(m.group(1)), directory / name))
    out.sort()
    return out


def scan_journal(directory: str | Path) -> tuple[dict[str, JournaledRequest], bool]:
    """Recover request state from every segment in ``directory``.

    Returns ``(requests, sealed)`` where ``requests`` maps request id to
    its reconstructed state (check ``.completed``) and ``sealed`` is True
    when the journal ends with a clean-close SEAL marker.  Never raises on
    torn/corrupt data: scanning a segment stops at the first bad record
    (everything before it is applied; nothing after it can be trusted).
    """
    directory = Path(directory)
    requests: dict[str, JournaledRequest] = {}
    sealed = False
    for _, path in _segment_paths(directory):
        try:
            data = path.read_bytes()
        except OSError as exc:
            logger.warning("journal %s: unreadable (%s) — skipped", path, exc)
            continue
        for rtype, payload in _iter_records(data, str(path)):
            sealed = rtype == SEAL  # only a SEAL as the *last* record counts
            if rtype == SEAL:
                continue
            rid = payload.get("id")
            if not isinstance(rid, str) or not rid:
                continue
            if rtype == ADMIT:
                req = requests.setdefault(rid, JournaledRequest(rid))
                req.prompt_ids = [int(t) for t in payload.get("prompt", [])]
                req.sampling = dict(payload.get("sampling") or {})
                req.deadline_s = float(payload.get("deadline_s", 0.0))
                req.arrival_unix = float(payload.get("arrival", 0.0))
                req.slo_class = str(payload.get("slo_class", "standard"))
                req.tenant = str(payload.get("tenant", "public"))
            elif rtype == PROGRESS:
                req = requests.get(rid)
                if req is None:
                    continue  # admit lost to earlier corruption/compaction
                req.emitted.extend(int(t) for t in payload.get("tokens", []))
            elif rtype == COMPLETE:
                req = requests.get(rid)
                if req is not None:
                    req.completed = True
    return requests, sealed


class RequestJournal:
    """Segmented append-only WAL with CRC records and live-ref compaction.

    Construction scans any prior segments in ``directory`` (exposed as
    ``recovered`` / ``recovered_sealed`` for the supervisor's warm-start
    replay) and then opens a *fresh* segment — a possibly-torn tail is
    never appended to.  Incomplete recovered requests keep their old
    segments pinned until this journal tombstones them.
    """

    def __init__(self, directory: str | Path, *,
                 segment_max_bytes: int = 4 << 20,
                 fsync: str | None = None,
                 fsync_interval_s: float = 0.05,
                 clock=time.monotonic):
        if fsync is None:
            fsync = os.environ.get("K8SLLM_JOURNAL_FSYNC", "") or "interval"
        if fsync not in FSYNC_POLICIES:
            raise ValueError(
                f"fsync policy {fsync!r} not in {FSYNC_POLICIES}")
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.segment_max_bytes = max(int(segment_max_bytes), 1024)
        self.fsync = fsync
        self.fsync_interval_s = fsync_interval_s
        self._clock = clock
        self._last_fsync = clock()
        self._closed = False

        # Monotonic totals (exporter / tests).
        self.records_written = 0
        self.bytes_written = 0
        self.admits = 0
        self.completes = 0
        self.compacted_segments = 0

        segments = _segment_paths(self.directory)
        self._seg_sizes: dict[int, int] = {
            idx: path.stat().st_size for idx, path in segments
            if path.exists()
        }
        self.recovered, self.recovered_sealed = scan_journal(self.directory)
        # Incomplete recovered requests pin every pre-existing segment
        # (their records may be anywhere in prior history).
        self._live_refs: dict[str, set[int]] = {}
        for rid, req in self.recovered.items():
            if not req.completed:
                self._live_refs[rid] = {idx for idx, _ in segments}

        self._seg_index = (segments[-1][0] + 1) if segments else 0
        self._fh = open(self._seg_path(self._seg_index), "ab")
        self._seg_sizes[self._seg_index] = 0
        self._lock = make_lock("resilience.journal")
        self._compact_locked()

    # -- paths / sizes ---------------------------------------------------

    def _seg_path(self, index: int) -> Path:
        return self.directory / f"wal-{index:08d}.log"

    @property
    def size_bytes(self) -> int:
        """Bytes on disk across live (non-compacted) segments."""
        with self._lock:
            return sum(self._seg_sizes.values())

    @property
    def incomplete_recovered(self) -> list[JournaledRequest]:
        return [r for r in self.recovered.values() if not r.completed]

    # -- write path ------------------------------------------------------

    def _append_locked(self, rtype: int, payload: dict[str, Any],
                       force_sync: bool = False) -> None:
        if self._closed:
            return
        rec = _pack(rtype, payload)
        self._fh.write(rec)
        self._fh.flush()
        self.records_written += 1
        self.bytes_written += len(rec)
        self._seg_sizes[self._seg_index] = (
            self._seg_sizes.get(self._seg_index, 0) + len(rec))
        if self.fsync == "always" or force_sync:
            os.fsync(self._fh.fileno())
            self._last_fsync = self._clock()
        elif self.fsync == "interval":
            now = self._clock()
            if now - self._last_fsync >= self.fsync_interval_s:
                os.fsync(self._fh.fileno())
                self._last_fsync = now
        if self._seg_sizes[self._seg_index] >= self.segment_max_bytes:
            self._rotate_locked()

    def _rotate_locked(self) -> None:
        os.fsync(self._fh.fileno())
        self._fh.close()
        self._seg_index += 1
        self._fh = open(self._seg_path(self._seg_index), "ab")
        self._seg_sizes[self._seg_index] = 0
        self._compact_locked()

    def _compact_locked(self) -> None:
        """Drop non-active segments referenced by no live request — they
        hold only completed history."""
        pinned: set[int] = set()
        for refs in self._live_refs.values():
            pinned |= refs
        for idx in list(self._seg_sizes):
            if idx == self._seg_index or idx in pinned:
                continue
            try:
                self._seg_path(idx).unlink(missing_ok=True)
            except OSError as exc:
                logger.warning("journal compaction: cannot remove segment "
                               "%d (%s)", idx, exc)
                continue
            del self._seg_sizes[idx]
            self.compacted_segments += 1

    # -- public logging API ----------------------------------------------

    def log_admit(self, request_id: str, prompt_ids: list[int],
                  sampling: Any, deadline_s: float = 0.0,
                  arrival_unix: float | None = None,
                  slo_class: str = "standard",
                  tenant: str = "public") -> None:
        """Journal an accepted request BEFORE it reaches the engine
        (write-ahead).  ``sampling`` may be a SamplingParams dataclass or a
        plain dict.  ``tenant`` rides the ADMIT record so a warm-start
        replay restores per-tenant quota reservations exactly."""
        if dataclasses.is_dataclass(sampling):
            sampling = dataclasses.asdict(sampling)
        payload = {
            "id": request_id,
            "prompt": [int(t) for t in prompt_ids],
            "sampling": sampling or {},
            "deadline_s": float(deadline_s),
            "arrival": time.time() if arrival_unix is None else arrival_unix,
            "slo_class": slo_class,
            "tenant": tenant,
        }
        with self._lock:
            self._live_refs.setdefault(request_id, set()).add(self._seg_index)
            self._append_locked(ADMIT, payload)
            self.admits += 1

    def log_progress(self, request_id: str, token_ids: list[int]) -> None:
        if not token_ids:
            return
        with self._lock:
            if request_id in self._live_refs:
                self._live_refs[request_id].add(self._seg_index)
            self._append_locked(PROGRESS, {
                "id": request_id,
                "tokens": [int(t) for t in token_ids],
            })

    def log_complete(self, request_id: str) -> None:
        with self._lock:
            self._append_locked(COMPLETE, {"id": request_id})
            self.completes += 1
            self._live_refs.pop(request_id, None)
            self._compact_locked()

    def seal(self) -> None:
        """Clean-close marker + final fsync.  Incomplete requests (drain
        timeout stragglers) remain replayable by the next process."""
        with self._lock:
            if self._closed:
                return
            self._append_locked(SEAL, {"id": ""}, force_sync=True)
            self._closed = True
            self._fh.close()

    def close(self) -> None:
        """Flush and close without a SEAL (crash-like close; everything
        incomplete stays replayable)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            try:
                self._fh.flush()
                os.fsync(self._fh.fileno())
            except OSError:
                pass
            self._fh.close()
