"""Live health state machine: HEALTHY → DEGRADED → DRAINING / UNHEALTHY.

Kubernetes probes (monitor/server.py ``/health`` and ``/readyz``) need
*truth*, not a hard-coded literal: a monitor whose engine sheds half its
admissions or trips the dispatch watchdog should stop receiving traffic
before it wedges.  The :class:`HealthMonitor` aggregates event streams from
the serving layer (watchdog trips, dispatch failures, sheds, admissions)
and computes the state on read:

  UNHEALTHY  — the step loop died, or ``unhealthy_failures`` consecutive
               dispatch failures (the engine is failing every dispatch);
  DRAINING   — drain mode armed (shutdown in progress): finish inflight,
               admit nothing — readiness is down, liveness still up;
  DEGRADED   — a watchdog trip or dispatch failure inside ``window_s``, or
               the recent shed rate crossed ``degraded_shed_rate``;
  HEALTHY    — none of the above for a full window.

Events carry timestamps from an injectable ``clock`` so chaos tests drive
transitions deterministically without sleeping.
"""

from __future__ import annotations

import collections
import time

from k8s_llm_monitor_tpu.devtools.lockcheck import guarded_by, make_lock

HEALTHY = "healthy"
DEGRADED = "degraded"
DRAINING = "draining"
UNHEALTHY = "unhealthy"

# States a Kubernetes readiness probe should accept traffic in.
READY_STATES = (HEALTHY, DEGRADED)


@guarded_by("_lock", "_draining", "_dead_reason", "_consecutive_failures",
            "watchdog_trips", "dispatch_failures", "sheds", "admits")
class HealthMonitor:
    """Aggregates resilience events into the probe-facing health state."""

    def __init__(self, window_s: float = 30.0, degraded_shed_rate: float = 0.1,
                 unhealthy_failures: int = 8, clock=time.monotonic):
        self.window_s = window_s
        self.degraded_shed_rate = degraded_shed_rate
        self.unhealthy_failures = unhealthy_failures
        self._clock = clock
        self._draining = False
        self._dead_reason: str | None = None
        self._consecutive_failures = 0
        # Recent event timestamps, pruned to the window on read.
        self._trips: collections.deque[float] = collections.deque()
        self._failures: collections.deque[float] = collections.deque()
        self._sheds: collections.deque[float] = collections.deque()
        self._admits: collections.deque[float] = collections.deque()
        # Monotonic totals (exporter counters).
        self.watchdog_trips = 0
        self.dispatch_failures = 0
        self.sheds = 0
        self.admits = 0
        # Created last: lockcheck's guarded_by treats writes before the
        # lock exists as construction, not races.
        self._lock = make_lock("resilience.health")

    # -- event intake ---------------------------------------------------

    def record_watchdog_trip(self) -> None:
        with self._lock:
            self._trips.append(self._clock())
            self.watchdog_trips += 1

    def record_dispatch_failure(self) -> None:
        with self._lock:
            self._failures.append(self._clock())
            self.dispatch_failures += 1
            self._consecutive_failures += 1

    def record_dispatch_ok(self) -> None:
        with self._lock:
            self._consecutive_failures = 0

    def record_shed(self) -> None:
        with self._lock:
            self._sheds.append(self._clock())
            self.sheds += 1

    def record_admit(self) -> None:
        with self._lock:
            self._admits.append(self._clock())
            self.admits += 1

    def set_draining(self, draining: bool = True) -> None:
        with self._lock:
            self._draining = draining

    def set_dead(self, reason: str) -> None:
        """The step loop died; the state pins UNHEALTHY until restart."""
        with self._lock:
            self._dead_reason = reason

    def clear_dead(self) -> None:
        """The supervisor rebuilt the engine: un-pin UNHEALTHY so the
        replica can re-enter rotation (recent-window evidence still holds
        the state at DEGRADED until a clean window passes)."""
        with self._lock:
            self._dead_reason = None
            self._consecutive_failures = 0

    # -- state ----------------------------------------------------------

    def _prune(self, now: float) -> None:
        horizon = now - self.window_s
        for dq in (self._trips, self._failures, self._sheds, self._admits):
            while dq and dq[0] < horizon:
                dq.popleft()

    def state(self) -> str:
        return self.snapshot()["state"]

    def snapshot(self) -> dict:
        """State + the evidence behind it (the /health response body)."""
        with self._lock:
            now = self._clock()
            self._prune(now)
            recent_sheds = len(self._sheds)
            recent_admits = len(self._admits)
            offered = recent_sheds + recent_admits
            shed_rate = recent_sheds / offered if offered else 0.0
            reason = ""
            if self._dead_reason is not None:
                state = UNHEALTHY
                reason = self._dead_reason
            elif self._consecutive_failures >= self.unhealthy_failures:
                state = UNHEALTHY
                reason = (f"{self._consecutive_failures} consecutive "
                          f"dispatch failures")
            elif self._draining:
                state = DRAINING
                reason = "drain in progress"
            elif self._trips:
                state = DEGRADED
                reason = (f"{len(self._trips)} watchdog trip(s) in the last "
                          f"{self.window_s:.0f}s")
            elif self._failures:
                state = DEGRADED
                reason = (f"{len(self._failures)} dispatch failure(s) in "
                          f"the last {self.window_s:.0f}s")
            elif offered and shed_rate >= self.degraded_shed_rate:
                state = DEGRADED
                reason = (f"shedding {shed_rate:.0%} of admissions in the "
                          f"last {self.window_s:.0f}s")
            else:
                state = HEALTHY
            return {
                "state": state,
                "reason": reason,
                "ready": state in READY_STATES,
                "window_s": self.window_s,
                "recent": {
                    "watchdog_trips": len(self._trips),
                    "dispatch_failures": len(self._failures),
                    "sheds": recent_sheds,
                    "admits": recent_admits,
                    "shed_rate": round(shed_rate, 4),
                },
                "totals": {
                    "watchdog_trips": self.watchdog_trips,
                    "dispatch_failures": self.dispatch_failures,
                    "sheds": self.sheds,
                    "admits": self.admits,
                },
                "consecutive_dispatch_failures": self._consecutive_failures,
            }
