"""Resilience layer: fault injection, retry/backoff, circuit breaking, and
the live health state machine.

The serving stack (serving/engine.py, serving/service.py) and the monitor
plane (monitor/kube_rest.py, monitor/watcher.py, monitor/server.py) share
this package so that every failure mode has ONE definition, one injection
point, and one observable surface:

  * ``faults``  — process-global :class:`FaultInjector` with named failure
    points, configured by ``K8SLLM_FAULTS`` or programmatically (tests);
  * ``retry``   — jittered exponential :class:`Backoff` with a retry budget
    and the :class:`CircuitBreaker` used by the kube REST backend;
  * ``health``  — :class:`HealthMonitor`, the HEALTHY → DEGRADED →
    DRAINING/UNHEALTHY state machine behind ``/health`` and ``/readyz``;
  * ``journal`` — :class:`RequestJournal`, the append-only request WAL
    behind the crash-safe lifecycle (serving/supervisor.py replays it);
  * ``errors``  — :class:`OverloadedError`, the admission-refusal error
    the HTTP layer maps to 429/503 + Retry-After;
  * ``tenancy`` — :func:`normalize_tenant` / :func:`tenant_seed` and the
    per-tenant :class:`TenantGovernor` (request-rate + token-quota
    admission, reservation-settled so fleet hedges/failovers can't
    double-charge).

Everything here is stdlib-only and CPU-deterministic (seeded RNGs,
injectable clocks) so chaos tests reproduce bit-identically in CI.
"""

from k8s_llm_monitor_tpu.resilience.errors import OverloadedError
from k8s_llm_monitor_tpu.resilience.faults import (
    FAULT_POINTS,
    FaultError,
    FaultInjector,
    get_injector,
)
from k8s_llm_monitor_tpu.resilience.journal import (
    JournaledRequest,
    RequestJournal,
    scan_journal,
)
from k8s_llm_monitor_tpu.resilience.health import (
    DEGRADED,
    DRAINING,
    HEALTHY,
    UNHEALTHY,
    HealthMonitor,
)
from k8s_llm_monitor_tpu.resilience.retry import (
    Backoff,
    CircuitBreaker,
    CircuitOpen,
)
from k8s_llm_monitor_tpu.resilience.tenancy import (
    DEFAULT_TENANT,
    TenantGovernor,
    TokenBucket,
    normalize_tenant,
    tenant_seed,
)

__all__ = [
    "FAULT_POINTS",
    "FaultError",
    "FaultInjector",
    "get_injector",
    "OverloadedError",
    "JournaledRequest",
    "RequestJournal",
    "scan_journal",
    "Backoff",
    "CircuitBreaker",
    "CircuitOpen",
    "HealthMonitor",
    "HEALTHY",
    "DEGRADED",
    "DRAINING",
    "UNHEALTHY",
    "DEFAULT_TENANT",
    "TenantGovernor",
    "TokenBucket",
    "normalize_tenant",
    "tenant_seed",
]
