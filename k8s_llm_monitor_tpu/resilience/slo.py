"""SLO classes + the brownout ladder (docs/resilience.md).

Overload robustness is *class-ordered*, not first-come-first-shed: every
request carries an SLO class — ``interactive`` (a human is waiting),
``standard`` (API callers with retry budgets), ``batch`` (the standing
diagnosis pipeline, bulk analyses) — and the three pressure valves consult
the class before acting:

  * admission shedding sheds the lowest class first and never sheds a
    class while strictly-lower-priority work is still queued
    (``LLMEngine.should_shed``);
  * lane eviction preempts the lowest-class *running* lane when slots or
    KV pages run out (``LLMEngine._eviction_victim``);
  * the :class:`BrownoutController` ladder turns ``HealthMonitor`` state
    into staged degradation — hedging/speculation off and ``batch``
    max_tokens clamped at DEGRADED, diagnosis-pipeline triggers paused at
    DRAINING — with hysteretic (dwell-gated, one-step) recovery so a
    flapping health signal cannot oscillate the fleet.

Classes are host-side scheduling metadata only: no class value ever enters
a jitted program, so the plumbing is recompile-free by construction
(graftcheck's trace guards prove it).
"""

from __future__ import annotations

import time

from k8s_llm_monitor_tpu.devtools.lockcheck import guarded_by, make_lock

# Priority order, highest first.  Rank is the shed/evict key: lower rank
# is protected, higher rank pays first.
SLO_CLASSES: tuple[str, ...] = ("interactive", "standard", "batch")
SLO_RANK: dict[str, int] = {c: i for i, c in enumerate(SLO_CLASSES)}
DEFAULT_CLASS = "standard"

# Brownout ladder levels (BrownoutController.level): monotone severity.
BROWNOUT_NORMAL = 0     # full service
BROWNOUT_DEGRADED = 1   # hedging + spec decode off, batch max_tokens clamped
BROWNOUT_DRAINING = 2   # + diagnosis-pipeline triggers paused
BROWNOUT_NAMES: tuple[str, ...] = ("normal", "degraded", "draining")


def normalize_slo_class(value, default: str = DEFAULT_CLASS) -> str:
    """Coerce an SLO class: empty/None → ``default``, unknown → ValueError.

    Callers at trust boundaries (HTTP handlers) catch the ValueError and
    map it to a 400; internal callers pass validated values through.
    """
    if value is None or value == "":
        return default
    cls = str(value).strip().lower()
    if cls not in SLO_RANK:
        raise ValueError(
            f"unknown slo_class {value!r}; expected one of {SLO_CLASSES}")
    return cls


def _level_for_state(state: str) -> int:
    """Raw health state → the ladder level it calls for."""
    if state in ("draining", "unhealthy"):
        return BROWNOUT_DRAINING
    if state == "degraded":
        return BROWNOUT_DEGRADED
    return BROWNOUT_NORMAL


@guarded_by("_lock", "_level", "_better_since", "escalations", "recoveries")
class BrownoutController:
    """Hysteretic degradation ladder over a health-state source.

    ``state_fn`` is read on every :meth:`level` call (``HealthMonitor``
    already computes state-on-read, so polling it is the idiom).
    Escalation is immediate — the moment health worsens, service degrades.
    De-escalation is deliberate: the raw signal must call for a *better*
    level continuously for ``recover_dwell_s`` before the ladder steps
    down, and it steps down one rung at a time — a DRAINING episode
    passes back through DEGRADED before full service resumes.  A single
    flap inside the dwell resets the timer, so an oscillating health
    signal pins the ladder at its worst recent level instead of toggling
    hedging/speculation on and off under load.
    """

    def __init__(self, state_fn, recover_dwell_s: float = 10.0,
                 clock=time.monotonic):
        self._state_fn = state_fn
        self.recover_dwell_s = recover_dwell_s
        self._clock = clock
        self._level = BROWNOUT_NORMAL
        # When the raw signal first became continuously better than the
        # held level; None while it is at or above the held level.
        self._better_since: float | None = None
        # Monotonic totals (exporter counters).
        self.escalations = 0
        self.recoveries = 0
        # Created last: lockcheck's guarded_by treats writes before the
        # lock exists as construction, not races.
        self._lock = make_lock("resilience.brownout")

    def level(self) -> int:
        """Current ladder level (0=normal, 1=degraded, 2=draining)."""
        raw = _level_for_state(self._state_fn())
        with self._lock:
            now = self._clock()
            if raw >= self._level:
                # At or above the held level: hold (or escalate) and reset
                # the recovery dwell.
                if raw > self._level:
                    self._level = raw
                    self.escalations += 1
                self._better_since = None
                return self._level
            if self._better_since is None:
                self._better_since = now
            elif now - self._better_since >= self.recover_dwell_s:
                self._level -= 1  # one rung per dwell, never straight home
                self.recoveries += 1
                self._better_since = None if raw >= self._level else now
            return self._level

    def name(self) -> str:
        return BROWNOUT_NAMES[self.level()]

    def snapshot(self) -> dict:
        lvl = self.level()
        with self._lock:
            return {
                "level": lvl,
                "name": BROWNOUT_NAMES[lvl],
                "escalations": self.escalations,
                "recoveries": self.recoveries,
                "recover_dwell_s": self.recover_dwell_s,
            }
