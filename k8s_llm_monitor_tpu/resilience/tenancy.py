"""Tenant identity, admission quotas, and KV namespacing (docs/resilience.md).

Multi-tenant hardening has three legs, all host-side (no tenant value ever
enters a jitted program, so the plumbing is recompile-free by construction):

  * **Identity** — every request carries a tenant id, normalized once at
    the trust boundary (:func:`normalize_tenant`, the ``slo_class`` idiom)
    and threaded through ``GenerationRequest``, the WAL journal, and the
    fleet router unchanged.
  * **Admission** — :class:`TenantGovernor` holds a per-tenant request-rate
    :class:`TokenBucket` plus a generated-token quota bucket.  Quota runs
    *before* SLO-class shedding and refuses with a tenant-tagged 429, so an
    over-quota tenant's traffic never enters the queue and can never cause
    a within-quota tenant to shed.  Token quota is *reserved* at admission
    (``max_tokens``), converted to consumption as tokens are delivered, and
    the unused remainder refunded at settlement — hedge losers and failover
    replays therefore cannot double-charge: only the single logical
    admission reserves, and only delivered tokens stay charged.
  * **Namespacing** — :func:`tenant_seed` folds the tenant id into the
    prefix-cache chain-digest seed and the ``KVX1`` blob header, making a
    cross-tenant prefix hit structurally impossible (two tenants hashing
    identical token prefixes produce disjoint digest chains).  graftcheck's
    ``tenant-namespace`` rule gates every cache/tier call site statically.

Runtime toggles (registered in ``monitor/config.py`` ``ENV_KEYS``):
``K8SLLM_TENANT_ENFORCE`` force-enables quota enforcement even when the
config leaves tenancy accounting-only, and ``K8SLLM_TENANT_DEFAULT``
overrides the tenant assigned to unlabeled requests.
"""

from __future__ import annotations

import hashlib
import os
import re
import time
from dataclasses import dataclass, field

from k8s_llm_monitor_tpu.devtools.lockcheck import guarded_by, make_lock
from k8s_llm_monitor_tpu.resilience.errors import OverloadedError

# The tenant every unlabeled request belongs to.  Single-tenant deployments
# never see another value; the accounting still runs so enabling quotas
# later needs no migration.
DEFAULT_TENANT = "public"

# DNS-label-ish: lowercase alphanumeric start, then [a-z0-9_.-], 64 chars
# max.  Tight on purpose — tenant ids become metric label values, journal
# payload fields, and digest-seed inputs.
_TENANT_RE = re.compile(r"^[a-z0-9][a-z0-9_.-]{0,63}$")

# Domain-separation tag for the digest seed: distinct from every other
# sha256 use in the tree, so a tenant id can never collide with a token
# block's contribution to a chain digest.
_SEED_TAG = b"k8sllm.tenant.v1\x00"


def default_tenant() -> str:
    """The tenant for unlabeled requests; ``K8SLLM_TENANT_DEFAULT``
    overrides the built-in ``"public"`` (read per call: tests flip it)."""
    raw = os.environ.get("K8SLLM_TENANT_DEFAULT", "")
    return normalize_tenant(raw, default=DEFAULT_TENANT) if raw else DEFAULT_TENANT


def normalize_tenant(value, default: str | None = None) -> str:
    """Coerce a tenant id: empty/None → the default tenant, malformed →
    ValueError.

    Callers at trust boundaries (HTTP handlers) catch the ValueError and
    map it to a 400; internal callers pass validated values through.
    """
    if value is None or value == "":
        return default if default is not None else default_tenant()
    tenant = str(value).strip().lower()
    if not _TENANT_RE.match(tenant):
        raise ValueError(
            f"invalid tenant {value!r}; expected lowercase alphanumeric "
            "start, then [a-z0-9_.-], at most 64 chars")
    return tenant


def tenant_seed(tenant: str) -> bytes:
    """The 32-byte digest-chain seed namespacing all KV keys for a tenant.

    ``PrefixCache`` seeds its chain digests with this instead of ``b""``,
    and ``HostKVTier`` keys inherit the same digests — so two tenants
    hashing identical token prefixes produce disjoint chains and a
    cross-tenant prefix hit is impossible by construction, not by check.
    """
    return hashlib.sha256(_SEED_TAG + tenant.encode("utf-8")).digest()


@guarded_by("_lock", "_level", "_stamp", "takes", "refusals")
class TokenBucket:
    """A monotone token bucket with an injectable clock.

    ``rate <= 0`` disables the bucket (every take succeeds) so config
    defaults can leave a dimension unlimited.  ``force_take`` may drive
    the level negative — that models quota *debt* (a supervisor-rebuild
    replay re-reserving work the tenant already holds): refills pay the
    debt down before new admissions succeed again.
    """

    def __init__(self, rate: float, burst: float,
                 clock=time.monotonic, name: str = "bucket"):
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock
        self._level = float(burst)
        self._stamp = float(clock())
        self.takes = 0
        self.refusals = 0
        # Created last: lockcheck's guarded_by treats writes before the
        # lock exists as construction, not races.
        self._lock = make_lock(f"resilience.tenancy.{name}")

    def _refill_locked(self) -> None:
        now = self._clock()
        dt = max(0.0, now - self._stamp)
        self._stamp = now
        if self.rate > 0:
            self._level = min(self.burst, self._level + dt * self.rate)

    def try_take(self, n: float = 1.0) -> float:
        """0.0 on success; else a positive retry-after hint (seconds until
        ``n`` tokens will have refilled)."""
        if self.rate <= 0:
            return 0.0
        with self._lock:
            self._refill_locked()
            if self._level >= n:
                self._level -= n
                self.takes += 1
                return 0.0
            self.refusals += 1
            return max(0.001, (n - self._level) / self.rate)

    def force_take(self, n: float) -> None:
        """Take without refusal (replay/restore); may go negative."""
        if self.rate <= 0 or n <= 0:
            return
        with self._lock:
            self._refill_locked()
            self._level -= n

    def give(self, n: float) -> None:
        """Refund unused reservation, clamped at the burst ceiling."""
        if self.rate <= 0 or n <= 0:
            return
        with self._lock:
            self._refill_locked()
            self._level = min(self.burst, self._level + n)

    def available(self) -> float:
        """Current level (negative while in debt); +inf when disabled."""
        if self.rate <= 0:
            return float("inf")
        with self._lock:
            self._refill_locked()
            return self._level


@dataclass
class _Reservation:
    """One admitted logical request's outstanding token reservation."""

    tenant: str
    reserved: float       # tokens taken from the quota bucket at admit
    delivered: int = 0    # tokens actually streamed to the caller so far


@dataclass
class _TenantState:
    """Per-tenant buckets + monotonic accounting totals."""

    requests: TokenBucket
    tokens: TokenBucket
    admitted: int = 0          # admissions granted
    quota_refusals: int = 0    # 429s from this governor
    sheds: int = 0             # SLO-class sheds charged to this tenant
    charged_tokens: int = 0    # delivered tokens, settled
    admitted_bytes: int = 0    # prompt bytes accepted (accounting only)
    extra: dict = field(default_factory=dict)


@guarded_by("_lock", "_tenants", "_reservations")
class TenantGovernor:
    """Per-tenant admission: request-rate limiting + token-quota accounting.

    The reservation protocol makes "charged tokens == delivered tokens"
    hold exactly across hedges, failovers, and supervisor rebuilds:

      * :meth:`admit` — take 1 from the tenant's request bucket and reserve
        ``max_tokens`` from its token bucket, or raise a tenant-tagged
        retriable :class:`OverloadedError` (HTTP 429).  Exactly one admit
        per *logical* request: replica-level hedge/failover dispatches
        behind a fleet router never call it.
      * :meth:`note_delivered` — count tokens as they stream to the caller
        (winner stream only; hedge losers are cancelled unobserved).
      * :meth:`settle` — refund the unused reservation, fold delivered into
        the tenant's charged total, drop the reservation.  Idempotent.
      * :meth:`restore` — supervisor warm start: re-create a reservation
        from the WAL without refusal (``force_take`` may drive the bucket
        into debt, which refills pay down).

    ``enforce=False`` keeps the full accounting but never refuses — the
    safe default for single-tenant deployments; ``K8SLLM_TENANT_ENFORCE=1``
    flips enforcement on at runtime without a config change.
    """

    def __init__(self, *, requests_per_s: float = 0.0,
                 request_burst: float = 0.0,
                 tokens_per_s: float = 0.0, token_burst: float = 0.0,
                 enforce: bool = True, max_tenants: int = 1024,
                 clock=time.monotonic):
        self.requests_per_s = float(requests_per_s)
        self.request_burst = float(request_burst or max(1.0, requests_per_s))
        self.tokens_per_s = float(tokens_per_s)
        self.token_burst = float(token_burst or max(1.0, tokens_per_s))
        self.enforce = bool(enforce)
        self.max_tenants = int(max_tenants)
        self._clock = clock
        self._tenants: dict[str, _TenantState] = {}
        self._reservations: dict[str, _Reservation] = {}
        # Created last: lockcheck's guarded_by treats writes before the
        # lock exists as construction, not races.
        self._lock = make_lock("resilience.tenancy.governor")

    # -- internals ---------------------------------------------------------

    def _enforcing(self) -> bool:
        if os.environ.get("K8SLLM_TENANT_ENFORCE", "") not in ("", "0"):
            return True
        return self.enforce

    def _state_locked(self, tenant: str) -> _TenantState:
        st = self._tenants.get(tenant)
        if st is not None:
            # dict insertion order doubles as the idle-LRU: re-insert.
            self._tenants.pop(tenant)
            self._tenants[tenant] = st
            return st
        # Cap the map: evict the longest-idle tenant with nothing in
        # flight (abandoning only its bucket levels and totals — the
        # exporter's top-K cut has long since stopped showing it).
        if len(self._tenants) >= self.max_tenants:
            busy = {r.tenant for r in self._reservations.values()}
            for victim in list(self._tenants):
                if victim not in busy:
                    del self._tenants[victim]
                    break
        st = _TenantState(
            requests=TokenBucket(self.requests_per_s, self.request_burst,
                                 clock=self._clock, name="req"),
            tokens=TokenBucket(self.tokens_per_s, self.token_burst,
                               clock=self._clock, name="tok"),
        )
        self._tenants[tenant] = st
        return st

    # -- the reservation protocol ------------------------------------------

    def admit(self, tenant: str, request_id: str, *, max_tokens: int,
              prompt_bytes: int = 0, slo_class: str = "") -> None:
        """Charge one request + reserve ``max_tokens``; raise a retriable
        tenant-tagged :class:`OverloadedError` when over quota."""
        with self._lock:
            st = self._state_locked(tenant)
            enforcing = self._enforcing()
            wait_r = st.requests.try_take(1.0)
            if wait_r > 0.0 and enforcing:
                st.quota_refusals += 1
                st.sheds += 1
                raise OverloadedError(
                    f"tenant {tenant!r} over request-rate quota",
                    retriable=True, retry_after_s=wait_r,
                    slo_class=slo_class, request_id=request_id,
                    tenant=tenant)
            reserve = float(max(0, max_tokens))
            wait_t = st.tokens.try_take(reserve)
            if wait_t > 0.0 and enforcing:
                # Give the request token back: this admission never
                # happened, and the next (smaller) request may fit.
                st.requests.give(1.0)
                st.quota_refusals += 1
                st.sheds += 1
                raise OverloadedError(
                    f"tenant {tenant!r} over token quota",
                    retriable=True, retry_after_s=wait_t,
                    slo_class=slo_class, request_id=request_id,
                    tenant=tenant)
            if wait_t > 0.0:
                # Accounting-only mode refused nothing; still reserve so
                # settlement math stays uniform (debt is fine here).
                st.tokens.force_take(reserve)
            st.admitted += 1
            st.admitted_bytes += max(0, int(prompt_bytes))
            self._reservations[request_id] = _Reservation(
                tenant=tenant, reserved=reserve)

    def note_delivered(self, request_id: str, n: int) -> None:
        """Count ``n`` tokens streamed to the caller (exactly once each)."""
        if n <= 0:
            return
        with self._lock:
            res = self._reservations.get(request_id)
            if res is not None:
                res.delivered += n

    def settle(self, request_id: str) -> int:
        """Refund the unused reservation and finalize charges; idempotent.
        Returns the tokens charged (0 for an unknown/already-settled id)."""
        with self._lock:
            res = self._reservations.pop(request_id, None)
            if res is None:
                return 0
            st = self._state_locked(res.tenant)
            st.tokens.give(max(0.0, res.reserved - res.delivered))
            st.charged_tokens += res.delivered
            return res.delivered

    def restore(self, request_id: str, tenant: str, *, max_tokens: int,
                delivered: int = 0) -> None:
        """Warm-start re-reservation from the WAL (never refuses).

        The remaining budget is force-taken — possibly into debt — so a
        rebuilt engine's replayed work stays charged to its tenant and the
        tenant cannot launder quota through a crash."""
        with self._lock:
            if request_id in self._reservations:
                return
            st = self._state_locked(tenant)
            remaining = float(max(0, max_tokens - delivered))
            st.tokens.force_take(remaining)
            st.requests.force_take(1.0)
            st.admitted += 1
            self._reservations[request_id] = _Reservation(
                tenant=tenant, reserved=remaining + delivered,
                delivered=delivered)

    # -- accounting taps ---------------------------------------------------

    def note_shed(self, tenant: str) -> None:
        """An SLO-class shed downstream of admission, charged to its
        tenant (folds into ``tenant_shed_total`` with quota refusals)."""
        with self._lock:
            self._state_locked(tenant).sheds += 1

    def reservation_tenant(self, request_id: str) -> str | None:
        with self._lock:
            res = self._reservations.get(request_id)
            return res.tenant if res is not None else None

    def charged_tokens(self, tenant: str) -> int:
        """Settled (delivered) tokens for a tenant — the bench's exactness
        probe: after all streams settle this equals tokens received."""
        with self._lock:
            st = self._tenants.get(tenant)
            return st.charged_tokens if st is not None else 0

    def quota_remaining(self, tenant: str) -> float:
        with self._lock:
            st = self._tenants.get(tenant)
        return st.tokens.available() if st is not None else float("inf")

    def snapshot(self) -> dict:
        """Per-tenant accounting block for ``/api/v1/stats`` + exporter."""
        with self._lock:
            tenants = dict(self._tenants)
            inflight: dict[str, int] = {}
            for res in self._reservations.values():
                inflight[res.tenant] = inflight.get(res.tenant, 0) + 1
        out: dict = {}
        for tenant, st in tenants.items():
            remaining = st.tokens.available()
            out[tenant] = {
                "admitted": st.admitted,
                "quota_refusals": st.quota_refusals,
                "sheds": st.sheds,
                "charged_tokens": st.charged_tokens,
                "admitted_bytes": st.admitted_bytes,
                "inflight": inflight.get(tenant, 0),
                "quota_remaining": (
                    -1.0 if remaining == float("inf")
                    else round(remaining, 3)),
            }
        return out
