"""Process-global fault injector with named failure points.

Real v5e-8 failures (a wedged dispatch, a KV pool exhausted by a burst, an
apiserver 5xx storm) cannot be provoked on demand, so every layer plants a
*named hook* here and chaos tests (tests/test_resilience.py) arm the hook
instead of waiting for hardware to misbehave.  Production builds pay one
dict lookup + one ``is-armed`` check per hook when nothing is armed.

Configuration:

  * env — ``K8SLLM_FAULTS=decode_dispatch:0.05,kube_http_5xx:0.3`` arms
    points at the given firing probability for the whole process;
  * programmatic — ``get_injector().arm("decode_dispatch", rate=1.0,
    times=3)`` (tests; ``times`` bounds total firings, ``after`` skips the
    first N evaluations so a fault can land mid-stream).

Determinism: the injector draws from its own seeded ``random.Random`` so a
chaos run replays identically; re-seed with ``reset(seed=...)``.
"""

from __future__ import annotations

import os
import random
from dataclasses import dataclass

from k8s_llm_monitor_tpu.devtools.lockcheck import make_lock

# The registry of failure points layers may hook.  Hooks for unknown names
# raise immediately — a typo'd point name must fail the test that armed it,
# not silently never fire.
FAULT_POINTS: frozenset[str] = frozenset({
    # serving/engine.py — dispatch paths
    "decode_dispatch",      # fused/spec decode program call raises
    "prefill_dispatch",     # batched prefill / chunk-round program call raises
    "decode_stuck",         # decode result never becomes ready (watchdog food)
    "slow_host_callback",   # reconcile-side host work sleeps delay_s
    "lane_eviction",        # class-ordered preemption raises mid-eviction
    # serving/kv_cache.py — allocator
    "alloc_exhaustion",     # alloc/extend raise OutOfBlocks despite free pages
    # serving/service.py — step loop
    "step_loop_crash",      # step loop raises mid-iteration (supervisor food)
    # monitor/kube_rest.py — apiserver client
    "kube_http_5xx",        # _request sees a synthetic 503
    "kube_http_timeout",    # _request sees a synthetic socket timeout
    "kube_http_reset",      # _request sees a synthetic connection reset
})


class FaultError(RuntimeError):
    """Raised by an armed failure point (engine dispatch hooks)."""

    def __init__(self, point: str):
        super().__init__(f"injected fault: {point}")
        self.point = point


@dataclass
class _Point:
    rate: float = 0.0        # firing probability per evaluation
    times: int = -1          # firings remaining; -1 = unbounded
    after: int = 0           # evaluations to skip before arming takes effect
    delay_s: float = 0.0     # for slow_* points: how long to stall
    evaluations: int = 0
    fired: int = 0


class FaultInjector:
    """Named-failure-point registry.  Thread-safe; cheap when disarmed."""

    def __init__(self, seed: int = 0):
        self._lock = make_lock("faults.injector")
        self._rng = random.Random(seed)
        self._points: dict[str, _Point] = {}
        self._load_env()

    # -- configuration --------------------------------------------------

    def _load_env(self) -> None:
        spec = os.environ.get("K8SLLM_FAULTS", "")
        if not spec:
            return
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            name, _, rate = part.partition(":")
            try:
                self.arm(name.strip(), rate=float(rate) if rate else 1.0)
            except ValueError:
                # A malformed env spec must be loud: silently ignoring it
                # would make a chaos drill a no-op.
                raise ValueError(
                    f"K8SLLM_FAULTS: bad entry {part!r} "
                    f"(want point:rate)") from None

    def arm(self, point: str, rate: float = 1.0, times: int = -1,
            after: int = 0, delay_s: float = 0.0) -> None:
        """Arm ``point`` to fire with probability ``rate`` per evaluation,
        at most ``times`` total firings (-1 = unbounded), skipping the
        first ``after`` evaluations."""
        if point not in FAULT_POINTS:
            raise ValueError(f"unknown fault point {point!r} "
                             f"(known: {sorted(FAULT_POINTS)})")
        with self._lock:
            self._points[point] = _Point(
                rate=rate, times=times, after=after, delay_s=delay_s)

    def disarm(self, point: str) -> None:
        with self._lock:
            self._points.pop(point, None)

    def reset(self, seed: int = 0) -> None:
        """Disarm everything and re-seed (test isolation)."""
        with self._lock:
            self._points.clear()
            self._rng = random.Random(seed)

    # -- evaluation (the planted hooks call these) ----------------------

    def should_fire(self, point: str) -> bool:
        """One evaluation of ``point``: True when the fault fires now."""
        with self._lock:
            p = self._points.get(point)
            if p is None:
                return False
            p.evaluations += 1
            if p.evaluations <= p.after:
                return False
            if p.times == 0:
                return False
            if p.rate < 1.0 and self._rng.random() >= p.rate:
                return False
            p.fired += 1
            if p.times > 0:
                p.times -= 1
            return True

    def maybe_raise(self, point: str) -> None:
        """Raise :class:`FaultError` when ``point`` fires (dispatch hooks)."""
        if self.should_fire(point):
            raise FaultError(point)

    def delay_s(self, point: str) -> float:
        """Armed stall duration for slow_* points (0.0 = fire-and-forget)."""
        with self._lock:
            p = self._points.get(point)
            return p.delay_s if p is not None else 0.0

    def fired(self, point: str) -> int:
        with self._lock:
            p = self._points.get(point)
            return p.fired if p is not None else 0

    @property
    def armed(self) -> dict[str, float]:
        with self._lock:
            return {k: v.rate for k, v in self._points.items()}


_injector: FaultInjector | None = None
_injector_lock = make_lock("faults.global_init")


def get_injector() -> FaultInjector:
    """The process-global injector (env-configured on first use)."""
    global _injector
    if _injector is None:
        with _injector_lock:
            if _injector is None:
                _injector = FaultInjector()
    return _injector
