"""graftcheck: JAX-aware static analysis + trace-time correctness gates.

Three passes, one CLI (``python -m k8s_llm_monitor_tpu.devtools.graftcheck``):

  * :mod:`~k8s_llm_monitor_tpu.devtools.astlint` — custom AST rules over the
    package (host reads inside jit bodies, blocking calls under locks, bare
    excepts, mutable defaults, fault-point registry);
  * :mod:`~k8s_llm_monitor_tpu.devtools.traceguard` — jit-traces the engine's
    hot entry points and asserts compile-count stability, no host-callback
    ops in the jaxprs, and donated-buffer rebinding;
  * :mod:`~k8s_llm_monitor_tpu.devtools.lockcheck` — an instrumented-lock
    mode (``K8SLLM_LOCKCHECK=1``) recording acquisition order, lock-order
    cycles, long holds, and unguarded shared-state writes.

See docs/devtools.md.  This ``__init__`` is import-free on purpose:
``lockcheck`` is imported by low-level modules (resilience/faults.py) and
must never drag jax or the lint machinery in with it.
"""
