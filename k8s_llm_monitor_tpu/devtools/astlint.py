"""JAX-aware AST lint: the rules ``compileall`` and pytest cannot see.

The bug classes that dominate risk in this codebase are not syntax errors:
a ``time.time()`` inside a jit body bakes one timestamp into the compiled
program forever; a ``requests`` call under the metrics-manager lock stalls
every ``/metrics`` scrape behind a slow pod; a typo'd fault-point name
turns a chaos drill into a silent no-op.  Each rule here targets one such
class.

Rule framework: one class per rule (subclass :class:`Rule`, set ``name``
and implement ``check``); :data:`ALL_RULES` is the registry.  Suppression:

    x = risky()  # graftcheck: disable=lock-blocking-call -- reason

suppresses the named rule(s) on that line (comma-separated; ``all``
matches every rule), and a line anywhere in the file

    # graftcheck: disable-file=jit-host-read -- reason

suppresses a rule for the whole file.  A reason after ``--`` is
conventionally required by review, not enforced.

Used by the graftcheck CLI (human + JSON output) and unit-tested per rule
in tests/test_graftcheck.py.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
from pathlib import Path
from typing import Iterable, Iterator


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str
    line: int
    col: int
    rule: str
    message: str

    def human(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class Rule:
    """One lint rule.  ``check`` yields findings for a parsed module."""

    name = "abstract"
    description = ""

    def check(self, tree: ast.Module, path: str) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, path: str, node: ast.AST, message: str) -> Finding:
        return Finding(path=path, line=getattr(node, "lineno", 1),
                       col=getattr(node, "col_offset", 0),
                       rule=self.name, message=message)


# ---------------------------------------------------------------------------
# shared AST helpers
# ---------------------------------------------------------------------------

def dotted_name(node: ast.AST) -> str:
    """``a.b.c`` for Name/Attribute chains; "" for anything else."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_jit_expr(node: ast.AST) -> bool:
    """True for expressions denoting jax.jit: ``jit``, ``jax.jit``,
    ``functools.partial(jax.jit, ...)``, or a call of any of those (a
    decorator like ``jax.jit(static_argnames=...)``)."""
    dn = dotted_name(node)
    if dn == "jit" or dn.endswith(".jit"):
        return True
    if isinstance(node, ast.Call):
        fdn = dotted_name(node.func)
        if fdn.endswith("partial") and node.args \
                and _is_jit_expr(node.args[0]):
            return True
        return _is_jit_expr(node.func)
    return False


def jit_bodies(tree: ast.Module) -> list[ast.AST]:
    """Function/lambda nodes whose bodies are jit-traced:

    * defs decorated with ``@jax.jit`` / ``@functools.partial(jax.jit,...)``;
    * defs later wrapped — any ``jax.jit(fn_name, ...)`` call in the file
      marks every same-named def (file-local over-approximation; good
      enough for a lint);
    * lambdas passed directly to ``jax.jit(...)``.
    """
    bodies: list[ast.AST] = []
    wrapped_names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_is_jit_expr(d) for d in node.decorator_list):
                bodies.append(node)
        elif isinstance(node, ast.Call) and _is_jit_expr(node.func):
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    wrapped_names.add(arg.id)
                elif isinstance(arg, ast.Lambda):
                    bodies.append(arg)
    if wrapped_names:
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name in wrapped_names \
                    and node not in bodies:
                bodies.append(node)
    return bodies


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------

class JitHostReadRule(Rule):
    """No host-state reads inside jit-traced bodies.

    ``time.time()`` / ``os.environ[...]`` / ``random.seed`` executed during
    tracing bake one Python-time value into the compiled program: every
    later invocation silently reuses it (or, worse, a changed value
    triggers a retrace).  Host state belongs outside the jit boundary,
    passed in as an argument.
    """

    name = "jit-host-read"
    description = "host read (clock/env/RNG seed) inside a jit-traced body"

    _CALLS = {
        "time.time", "time.monotonic", "time.perf_counter", "time.time_ns",
        "time.monotonic_ns", "datetime.now", "datetime.utcnow",
        "datetime.datetime.now", "datetime.datetime.utcnow",
        "os.getenv", "os.environb",
        "random.seed", "random.random", "random.randint", "random.uniform",
        "random.choice", "random.randrange", "random.getrandbits",
        "np.random.seed", "numpy.random.seed",
    }

    def check(self, tree: ast.Module, path: str) -> Iterator[Finding]:
        for body in jit_bodies(tree):
            for node in ast.walk(body):
                if isinstance(node, ast.Call):
                    dn = dotted_name(node.func)
                    if dn in self._CALLS or dn.endswith(".seed"):
                        yield self.finding(
                            path, node,
                            f"'{dn}()' inside a jit-traced body bakes host "
                            f"state into the compiled program; pass the "
                            f"value in as an argument")
                elif isinstance(node, (ast.Attribute, ast.Name)):
                    if dotted_name(node) == "os.environ":
                        yield self.finding(
                            path, node,
                            "'os.environ' read inside a jit-traced body; "
                            "resolve env config before the jit boundary")


class LockBlockingCallRule(Rule):
    """No blocking calls while a lock is held.

    A sleep, HTTP request, socket connect, subprocess, or device->host
    sync under a lock turns every other thread contending for that lock
    into a convoy — on the serving plane that is the difference between a
    slow scrape and a wedged step loop.  Move the blocking work outside
    the critical section (snapshot under the lock, act after release).

    Heuristics: a ``with`` context whose expression's terminal name
    contains ``lock``, ``mutex``, or ``cond`` is treated as a lock;
    nested function bodies are skipped (closures usually run later,
    outside the lock).
    """

    name = "lock-blocking-call"
    description = "blocking call (sleep/HTTP/socket/device sync) under a lock"

    _CALLS = {
        "time.sleep", "sleep",
        "socket.create_connection", "socket.getaddrinfo",
        "urllib.request.urlopen", "urlopen",
        "subprocess.run", "subprocess.Popen", "subprocess.check_output",
        "subprocess.check_call", "subprocess.call",
        "jax.device_get",
    }
    _REQUESTS_VERBS = {"get", "post", "put", "delete", "head", "patch",
                       "request"}
    _METHOD_SUFFIXES = ("block_until_ready",)

    @staticmethod
    def _is_lock_ctx(expr: ast.AST) -> bool:
        dn = dotted_name(expr)
        leaf = dn.rsplit(".", 1)[-1].lower()
        return any(k in leaf for k in ("lock", "mutex", "cond"))

    def _is_blocking(self, call: ast.Call) -> str:
        dn = dotted_name(call.func)
        if dn in self._CALLS:
            return dn
        parts = dn.split(".")
        if len(parts) >= 2 and parts[-2] == "requests" \
                and parts[-1] in self._REQUESTS_VERBS:
            return dn
        if parts[-1] in self._METHOD_SUFFIXES:
            return dn
        if parts[-1] == "join" and len(parts) >= 2 \
                and "thread" in parts[-2].lower():
            return dn
        if parts[-1] == "asarray" and len(parts) >= 2 \
                and parts[-2] in ("np", "numpy", "jnp"):
            # Device->host sync when the operand is a device array; under
            # a lock that risk is never worth it.
            return dn
        return ""

    def check(self, tree: ast.Module, path: str) -> Iterator[Finding]:
        for node in ast.walk(tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            if not any(self._is_lock_ctx(item.context_expr)
                       for item in node.items):
                continue
            stack: list[ast.AST] = list(node.body)
            while stack:
                sub = stack.pop()
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.Lambda)):
                    continue  # runs later, usually outside the lock
                if isinstance(sub, ast.Call):
                    dn = self._is_blocking(sub)
                    if dn:
                        yield self.finding(
                            path, sub,
                            f"blocking call '{dn}' while holding a lock; "
                            f"move it outside the critical section")
                stack.extend(ast.iter_child_nodes(sub))


class BareExceptRule(Rule):
    """No bare ``except:`` and no swallowed ``BaseException``.

    Both catch ``KeyboardInterrupt``/``SystemExit`` and — in this codebase
    — ``FaultError`` injections, turning a chaos drill into a silent pass.
    Catch ``Exception`` (or narrower), or re-raise.
    """

    name = "bare-except"
    description = "bare except / swallowed BaseException"

    @staticmethod
    def _reraises(handler: ast.ExceptHandler) -> bool:
        for node in ast.walk(handler):
            if isinstance(node, ast.Raise):
                return True
        return False

    def check(self, tree: ast.Module, path: str) -> Iterator[Finding]:
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                if not self._reraises(node):
                    yield self.finding(
                        path, node,
                        "bare 'except:' swallows BaseException (incl. "
                        "KeyboardInterrupt and injected faults); catch "
                        "Exception or re-raise")
            elif dotted_name(node.type).endswith("BaseException"):
                if not self._reraises(node):
                    yield self.finding(
                        path, node,
                        "'except BaseException' without re-raise; catch "
                        "Exception or re-raise")


class MutableDefaultRule(Rule):
    """No mutable default arguments (shared across calls)."""

    name = "mutable-default"
    description = "mutable default argument"

    _MUTABLE_CALLS = {"list", "dict", "set", "defaultdict", "deque",
                      "Counter", "OrderedDict"}

    def _is_mutable(self, node: ast.AST) -> bool:
        if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                             ast.DictComp, ast.SetComp)):
            return True
        if isinstance(node, ast.Call):
            dn = dotted_name(node.func)
            return dn.rsplit(".", 1)[-1] in self._MUTABLE_CALLS
        return False

    def check(self, tree: ast.Module, path: str) -> Iterator[Finding]:
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)):
                continue
            args = node.args
            for default in list(args.defaults) + [
                    d for d in args.kw_defaults if d is not None]:
                if self._is_mutable(default):
                    yield self.finding(
                        path, default,
                        "mutable default argument is shared across calls; "
                        "default to None (or a dataclasses.field factory)")


class FaultPointRule(Rule):
    """Every fault-point name must exist in the central registry.

    ``get_injector().arm("decode_dispach")`` (typo) raises at arm time,
    but hooks like ``self._faults.maybe_raise("decode_dispach")`` planted
    in rarely-exercised paths would just never fire.  This rule checks
    every string literal passed to the injector API against
    ``resilience.faults.FAULT_POINTS``.
    """

    name = "fault-point"
    description = "fault-point name not in resilience.faults.FAULT_POINTS"

    _ALWAYS = {"maybe_raise", "should_fire", "delay_s"}
    _HINTED = {"arm", "disarm", "fired"}
    _RECEIVER_HINTS = ("fault", "injector", "inj")

    def __init__(self, points: frozenset[str] | None = None):
        if points is None:
            from k8s_llm_monitor_tpu.resilience.faults import FAULT_POINTS
            points = FAULT_POINTS
        self._points = points

    def check(self, tree: ast.Module, path: str) -> Iterator[Finding]:
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            attr = node.func.attr
            if attr not in self._ALWAYS and attr not in self._HINTED:
                continue
            if attr in self._HINTED:
                recv = dotted_name(node.func.value).lower()
                if isinstance(node.func.value, ast.Call):
                    recv = dotted_name(node.func.value.func).lower()
                if not any(h in recv for h in self._RECEIVER_HINTS):
                    continue
            if not node.args:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                if arg.value not in self._points:
                    yield self.finding(
                        path, arg,
                        f"fault point {arg.value!r} is not declared in "
                        f"resilience.faults.FAULT_POINTS — a typo here "
                        f"makes the hook silently never fire")


class RawLockRule(Rule):
    """Locks must come from ``devtools.lockcheck.make_lock``.

    A raw ``threading.Lock()`` bypasses the lockcheck instrumentation:
    ``K8SLLM_LOCKCHECK=1`` chaos runs can't see its hold times or
    ``@guarded_by`` violations, so a subsystem built on raw locks gets no
    race coverage at all.  ``lockcheck.py`` itself (the factory) is the
    one legitimate construction site.
    """

    name = "raw-lock"
    description = "raw threading.Lock() outside devtools.lockcheck"

    _LOCK_CALLS = {"threading.Lock", "threading.RLock"}

    @staticmethod
    def _threading_imports(tree: ast.Module) -> set[str]:
        """Local names bound to threading.Lock/RLock via from-imports."""
        names: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.module == "threading":
                for alias in node.names:
                    if alias.name in ("Lock", "RLock"):
                        names.add(alias.asname or alias.name)
        return names

    def check(self, tree: ast.Module, path: str) -> Iterator[Finding]:
        if path.replace("\\", "/").endswith("devtools/lockcheck.py"):
            return
        bare = self._threading_imports(tree)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            dn = dotted_name(node.func)
            # ``bare`` holds only names bound from threading.Lock/RLock
            # (including asnames), so membership alone marks a lock call.
            if dn in self._LOCK_CALLS or dn in bare:
                yield self.finding(
                    path, node,
                    f"raw {dn}() bypasses lockcheck instrumentation; use "
                    f"devtools.lockcheck.make_lock(name) so "
                    f"K8SLLM_LOCKCHECK=1 runs can audit it")


class UnconstrainedParseRule(Rule):
    """Model output must be parsed through ``diagnosis.grammar``.

    A ``json.loads`` on LLM-generated text is a latent 500: free-running
    decode produces almost-JSON often enough to pass review and rarely
    enough to page at 3am.  The sanctioned path is FSM-constrained decode
    plus ``diagnosis.grammar.parse_verdict`` (which validates against the
    same DFA before parsing) — ``diagnosis/grammar.py`` is therefore the
    one file allowed to call ``json.loads`` on model text.

    Heuristics: a ``json.loads`` call is treated as parsing model output
    when it sits inside a class that looks like an LLM provider adapter
    (name ends with ``Backend`` *and* defines ``generate`` — which keeps
    ``KubeRestBackend`` out), or when its argument's name carries a
    model-output marker (``answer``, ``verdict``, ``completion``,
    ``generated`` …).
    Request-body parsing (``_read_json`` in the HTTP server) matches
    neither and stays unflagged.  Protocol-level parses inside a Backend
    (e.g. an OpenAI-compat HTTP envelope) suppress with
    ``# graftcheck: disable=unconstrained-model-parse -- reason``.
    """

    name = "unconstrained-model-parse"
    description = "json.loads of model output outside diagnosis/grammar.py"

    _MARKERS = ("answer", "verdict", "completion", "generated",
                "generation", "model_output", "llm_text")

    @staticmethod
    def _loads_names(tree: ast.Module) -> set[str]:
        """Local names bound to json.loads via from-imports."""
        names: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.module == "json":
                for alias in node.names:
                    if alias.name == "loads":
                        names.add(alias.asname or alias.name)
        return names

    def _is_loads(self, call: ast.Call, bare: set[str]) -> bool:
        dn = dotted_name(call.func)
        return dn == "json.loads" or dn in bare

    def _arg_marker(self, call: ast.Call) -> str:
        if not call.args:
            return ""
        # Strip decode()/strip() chains: json.loads(raw_answer.strip()).
        arg = call.args[0]
        while isinstance(arg, ast.Call):
            arg = arg.func
        label = dotted_name(arg).lower()
        for marker in self._MARKERS:
            if marker in label:
                return marker
        return ""

    def check(self, tree: ast.Module, path: str) -> Iterator[Finding]:
        if path.replace("\\", "/").endswith("diagnosis/grammar.py"):
            return  # the sanctioned parser
        bare = self._loads_names(tree)
        in_backend: set[int] = set()
        for cls in ast.walk(tree):
            if not (isinstance(cls, ast.ClassDef)
                    and cls.name.endswith("Backend")):
                continue
            if not any(isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
                       and m.name == "generate" for m in cls.body):
                continue  # e.g. KubeRestBackend: no LLM here
            for sub in ast.walk(cls):
                in_backend.add(id(sub))
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and self._is_loads(node, bare)):
                continue
            marker = self._arg_marker(node)
            if id(node) in in_backend:
                yield self.finding(
                    path, node,
                    "json.loads inside an LLM backend class parses model "
                    "output unconstrained; use FSM-constrained decode + "
                    "diagnosis.grammar.parse_verdict, or suppress for "
                    "protocol-envelope parses")
            elif marker:
                yield self.finding(
                    path, node,
                    f"json.loads of '{marker}'-named value looks like "
                    f"free-running model output; route it through "
                    f"diagnosis.grammar.parse_verdict so malformed JSON "
                    f"cannot reach callers")


class TenantNamespaceRule(Rule):
    """Prefix-KV key and blob paths must carry the tenant namespace.

    The multi-tenant privacy invariant is structural: ``PrefixCache``
    digests are seeded per tenant and ``KVX1`` blobs carry a tenant tag,
    so a cross-tenant hit is impossible — *if* every call site passes the
    tenant through.  A lookup/register/spill/migration call that omits it
    silently lands in the default namespace, which either leaks one
    tenant's prefix into another's accounting or (worse) bypasses the
    per-tenant eviction cap.  This rule makes the omission a lint error
    instead of a code-review hope.

    Heuristics: ``lookup`` / ``register`` / ``digest_chain`` on a
    receiver that looks like a prefix cache (leaf name ``pc`` or
    containing ``prefix``/``cache``), ``put`` on a tier-like receiver,
    and any ``export_prefix`` / ``fetch_prefix`` / ``install_prefix``
    call must pass ``tenant=`` (``install_prefix`` accepts
    ``expected_tenant=``).  A ``**kwargs`` splat counts as satisfied
    (not analyzable).  The defining modules — ``serving/kv_cache.py``,
    ``serving/kv_tier.py``, ``resilience/tenancy.py`` — are exempt.
    """

    name = "tenant-namespace"
    description = "prefix-KV key/blob path without tenant namespacing"

    _PC_METHODS = {"lookup", "register", "digest_chain"}
    _TIER_METHODS = {"put"}
    _BLOB_METHODS = {"export_prefix": ("tenant",),
                     "fetch_prefix": ("tenant",),
                     "install_prefix": ("tenant", "expected_tenant")}
    _EXEMPT = ("serving/kv_cache.py", "serving/kv_tier.py",
               "resilience/tenancy.py")

    @staticmethod
    def _leaf(expr: ast.AST) -> str:
        return dotted_name(expr).rsplit(".", 1)[-1].lower()

    @classmethod
    def _is_pc_recv(cls, expr: ast.AST) -> bool:
        leaf = cls._leaf(expr)
        return leaf == "pc" or "prefix" in leaf or "cache" in leaf

    @classmethod
    def _is_tier_recv(cls, expr: ast.AST) -> bool:
        return "tier" in cls._leaf(expr)

    @staticmethod
    def _has_kw(call: ast.Call, accepted: tuple[str, ...]) -> bool:
        for kw in call.keywords:
            if kw.arg is None:  # **kwargs splat: assume it's in there
                return True
            if kw.arg in accepted:
                return True
        return False

    def check(self, tree: ast.Module, path: str) -> Iterator[Finding]:
        norm = path.replace("\\", "/")
        if any(norm.endswith(e) for e in self._EXEMPT):
            return  # the namespacing implementations themselves
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            attr = node.func.attr
            if attr in self._BLOB_METHODS:
                accepted = self._BLOB_METHODS[attr]
                if not self._has_kw(node, accepted):
                    yield self.finding(
                        path, node,
                        f"'{attr}()' without {' / '.join(accepted)}= moves "
                        f"a KV blob outside the tenant namespace; pass the "
                        f"request's tenant through")
            elif attr in self._PC_METHODS \
                    and self._is_pc_recv(node.func.value):
                if not self._has_kw(node, ("tenant",)):
                    yield self.finding(
                        path, node,
                        f"prefix-cache '{attr}()' without tenant= lands in "
                        f"the default namespace — cross-tenant prefix "
                        f"leak; pass tenant= through from the request")
            elif attr in self._TIER_METHODS \
                    and self._is_tier_recv(node.func.value):
                if not self._has_kw(node, ("tenant",)):
                    yield self.finding(
                        path, node,
                        "host-tier 'put()' without tenant= skips per-"
                        "tenant byte accounting and the max-share cap; "
                        "tag the spill with the owning tenant")


class RawKubeWriteRule(Rule):
    """Cluster mutations must flow through the sanctioned executors.

    The remediation executor earns its safety claims structurally:
    every write is dry-run-validated first, breaker-guarded, rate
    limited, idempotency-keyed, and (for destructive verbs) approval
    gated.  A mutation issued from anywhere else skips all of that —
    one stray ``delete_pod()`` in a handler and the audit trail, the
    replay protection, and the approval gate are fiction.  This rule
    flags the two ways a write can escape:

    * a call to one of the mutation verbs — ``scale_statefulset``,
      ``rollout_restart``, ``cordon_node``, ``delete_pod`` — on any
      receiver;
    * a ``_request(...)`` call passing ``method=`` POST/PATCH/DELETE
      (the raw kube REST write path).

    Exempt: ``remediation/executor.py`` (the executor itself),
    ``fleet/autoscaler.py`` (``KubeScaleExecutor``, the pre-existing
    sanctioned scale path), and the backends that *implement* the
    verbs (``monitor/kube_rest.py``, ``monitor/cluster.py``).  Test
    files are skipped — they drive fakes, not clusters.
    """

    name = "raw-kube-write"
    description = "kube mutation outside the sanctioned executors"

    _VERBS = {"scale_statefulset", "rollout_restart", "cordon_node",
              "delete_pod"}
    _WRITE_METHODS = {"POST", "PATCH", "DELETE"}
    _EXEMPT = ("remediation/executor.py", "fleet/autoscaler.py",
               "monitor/kube_rest.py", "monitor/cluster.py")

    def check(self, tree: ast.Module, path: str) -> Iterator[Finding]:
        norm = path.replace("\\", "/")
        if any(norm.endswith(e) for e in self._EXEMPT):
            return  # the sanctioned executors / verb implementations
        base = norm.rsplit("/", 1)[-1]
        if base.startswith("test_") or "/tests/" in norm:
            return  # tests drive FakeCluster directly by design
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            attr = node.func.attr
            if attr in self._VERBS:
                yield self.finding(
                    path, node,
                    f"'{attr}()' mutates the cluster outside "
                    f"remediation.executor / KubeScaleExecutor — this "
                    f"skips dry-run validation, breakers, rate limits "
                    f"and the approval gate; route it through "
                    f"RemediationEngine")
            elif attr == "_request":
                for kw in node.keywords:
                    if kw.arg == "method" \
                            and isinstance(kw.value, ast.Constant) \
                            and str(kw.value.value).upper() \
                            in self._WRITE_METHODS:
                        yield self.finding(
                            path, node,
                            f"raw kube {kw.value.value} via _request() "
                            f"bypasses every remediation guard; add a "
                            f"verb to KubeRestBackend and call it from "
                            f"the executor instead")
                        break


def default_rules() -> list[Rule]:
    return [JitHostReadRule(), LockBlockingCallRule(), BareExceptRule(),
            MutableDefaultRule(), FaultPointRule(), RawLockRule(),
            UnconstrainedParseRule(), TenantNamespaceRule(),
            RawKubeWriteRule()]


ALL_RULE_NAMES = tuple(r.name for r in default_rules())


# ---------------------------------------------------------------------------
# suppression + driver
# ---------------------------------------------------------------------------

_SUPPRESS_RE = re.compile(
    r"#\s*graftcheck:\s*(disable|disable-file)\s*=\s*([\w,\-]+)")


def _suppressions(src: str) -> tuple[dict[int, set[str]], set[str]]:
    """(per-line rule sets, whole-file rule set) from magic comments."""
    per_line: dict[int, set[str]] = {}
    per_file: set[str] = set()
    for lineno, line in enumerate(src.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        rules = {r.strip() for r in m.group(2).split(",") if r.strip()}
        if m.group(1) == "disable-file":
            per_file |= rules
        else:
            per_line.setdefault(lineno, set()).update(rules)
    return per_line, per_file


def lint_source(src: str, path: str = "<string>",
                rules: Iterable[Rule] | None = None) -> list[Finding]:
    """Lint one source blob; returns unsuppressed findings sorted by
    position.  Syntax errors come back as a single ``parse-error``
    finding (compileall-grade breakage still surfaces through the lint)."""
    try:
        tree = ast.parse(src)
    except SyntaxError as exc:
        return [Finding(path=path, line=exc.lineno or 1,
                        col=exc.offset or 0, rule="parse-error",
                        message=str(exc.msg))]
    per_line, per_file = _suppressions(src)
    out: list[Finding] = []
    for rule in (rules if rules is not None else default_rules()):
        for f in rule.check(tree, path):
            if f.rule in per_file or "all" in per_file:
                continue
            line_rules = per_line.get(f.line, set())
            if f.rule in line_rules or "all" in line_rules:
                continue
            out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def iter_py_files(root: Path) -> Iterator[Path]:
    if root.is_file():
        if root.suffix == ".py":
            yield root
        return
    for p in sorted(root.rglob("*.py")):
        if "__pycache__" in p.parts:
            continue
        yield p


def lint_paths(paths: Iterable[Path],
               rules: Iterable[Rule] | None = None) -> list[Finding]:
    rules = list(rules) if rules is not None else default_rules()
    findings: list[Finding] = []
    for root in paths:
        for p in iter_py_files(Path(root)):
            findings.extend(
                lint_source(p.read_text(encoding="utf-8"), str(p), rules))
    return findings


def render(findings: list[Finding], as_json: bool = False) -> str:
    if as_json:
        return json.dumps({
            "findings": [f.as_dict() for f in findings],
            "count": len(findings),
            "ok": not findings,
        }, indent=2)
    if not findings:
        return "graftcheck astlint: clean"
    lines = [f.human() for f in findings]
    lines.append(f"graftcheck astlint: {len(findings)} finding(s)")
    return "\n".join(lines)
