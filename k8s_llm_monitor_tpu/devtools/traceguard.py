"""Trace-time correctness gates for the serving engine's hot entry points.

Three guarantees, asserted by *running* the jit machinery on a tiny model
(CPU-friendly shapes, both the fused-interpret and gather decode paths):

  1. **Compile-count stability** — after a warm-up pass, re-invoking the
     engine with same-bucket shapes triggers ZERO new compilations.  A
     silent recompile on the decode hot path costs seconds per occurrence
     in production; this guard turns it into a red gate.  Counted two
     ways: the sum of ``_cache_size()`` over every jitted engine program
     (deterministic, the gating signal) and ``jax.monitoring`` backend
     compile events (supporting evidence in the report).
  2. **No host callbacks in the traced programs** — the jaxprs of the
     decode/prefill/sampling programs must contain no ``pure_callback`` /
     ``io_callback`` / ``debug_callback`` ops: any of those forces a
     device->host round-trip inside what the engine treats as an async
     device call, defeating dispatch-ahead.
  3. **Donated buffers are rebound** — the engine donates KV pages and the
     token buffer into every dispatch; after a step the engine must hold
     the *new* arrays, never a stale alias of a donated input (on TPU that
     alias is a deleted buffer; on CPU it silently reads garbage-to-be).

The report is machine-readable (dict / JSON) and consumed by
tests/test_graftcheck.py and the graftcheck CLI (``--trace``).

Everything imports lazily so the CLI can pin ``JAX_PLATFORMS=cpu`` before
jax initializes.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable

FORBIDDEN_PRIMITIVES = frozenset({
    "pure_callback", "io_callback", "debug_callback",
})

#: Decode paths the guard exercises by default.  "fused" runs the Pallas
#: kernel in interpreter mode off-TPU — same trace, same jaxpr, no TPU
#: needed; "gather" is the XLA fallback (and the numerics oracle); "mesh"
#: builds the engine under a GSPMD mesh spanning every local device (the
#: forced-host 8-device CPU mesh in CI) so the SHARDED fused-decode and
#: chunk-prefill programs are gated too — same zero-recompile and
#: donation-rebinding assertions, now over collective-aware programs;
#: "quant" builds the engine with kv_dtype="int8" so the quantize-on-append
#: prefill/decode programs and the widened donation set (page pool PLUS the
#: per-page scale leaves) are held to the same zero-recompile gate;
#: "overlap" is the mesh engine with the hand-staged reduce-scatter/
#: all-gather decode schedule forced on (parallel/overlap.py) — the mesh
#: path itself pins tp_overlap="off" so the GSPMD reference program stays
#: gated alongside the overlap one; "flash_prefill" forces the flash
#: paged-prefill kernel (prefill_path="flash", interpreter on CPU) so the
#: prefill/chunk/verify programs run the tiled online-softmax kernel and
#: are held to the same zero-recompile / donation-rebinding / no-callback
#: gates as the dense programs; "grammar_swap" is the gather engine with a
#: mid-run ``set_grammar`` swap to a *different* same-shape FSM between
#: the warm and repeat passes — the remediation planner swaps per-request
#: plan grammars at runtime, and this path proves the swap is a pure
#: runtime-argument change (zero recompiles) rather than a retrace.
DEFAULT_PATHS = ("gather", "fused", "mesh", "quant", "overlap",
                 "flash_prefill", "grammar_swap")


def force_cpu() -> None:
    """Pin jax to CPU before any backend initializes (the environment's
    sitecustomize may otherwise route to a tunneled TPU — see
    tests/conftest.py for the same dance).  Also forces the 8-device host
    platform so the "mesh" path has a real axis to shard over; no-op if
    jax already initialized (the mesh path then uses whatever device
    count exists)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")


def _tiny_cfg(fused: bool, mesh_tp: int = 0):
    """Model configs mirroring tests/test_fused_decode.py: one fails the
    Mosaic 128-lane gate (gather-only), one passes it (KVH*D = 2*64).
    ``mesh_tp`` > 0 selects the TP-shardable config: 8 heads / 8 KV heads
    so every power-of-two device count up to 8 gets head-aligned KV page
    shards (parallel/sharding.py:SpecLayout.kv_pages)."""
    from k8s_llm_monitor_tpu.models.config import ModelConfig

    if mesh_tp:
        return ModelConfig(name="tg-mesh", vocab_size=256, hidden_size=64,
                           intermediate_size=128, num_layers=2, num_heads=8,
                           num_kv_heads=8, dtype="float32",
                           rope_theta=10_000.0)
    if fused:
        return ModelConfig(name="tg-fused", vocab_size=128, hidden_size=256,
                           intermediate_size=256, num_layers=1, num_heads=4,
                           num_kv_heads=2, dtype="float32",
                           rope_theta=10_000.0)
    return ModelConfig(name="tg", vocab_size=256, hidden_size=32,
                       intermediate_size=64, num_layers=2, num_heads=4,
                       num_kv_heads=2, dtype="float32", rope_theta=10_000.0)


def _toy_fsm(variant: int = 0):
    """A hand-built 2-state cycling grammar over a 16-token vocab: states
    1 and 2 allow tokens 3..10 and alternate forever (max_len unbounded, so
    constrained drives terminate by budget — eos_id -1 matches the guard
    engines).  Big enough to exercise every constrained program; far
    smaller than the 259-vocab verdict grammar, which would not fit the
    tiny guard models.

    ``variant=1`` allows a shifted token window (5..12) in the SAME table
    shape — the grammar_swap path installs it mid-run to prove that
    swapping FSM *content* (the remediation planner does this per
    snapshot) never retraces, only rebinding the runtime table argument."""
    import numpy as np

    from k8s_llm_monitor_tpu.diagnosis.grammar import TokenFSM

    lo, hi = (5, 13) if variant else (3, 11)
    trans = np.full((3, 16), -1, dtype=np.int32)
    trans[0, :] = 0
    trans[1, lo:hi] = 2
    trans[2, lo:hi] = 1
    return TokenFSM.from_table(trans, start=1,
                               accept=np.array([False, True, True]),
                               eos_id=-1)


def build_engine(decode_path: str = "gather", seed: int = 0):
    """A tiny engine wired for deterministic compile accounting: prefix
    cache off (a second same-prefix prompt would switch admission to the
    chunked program — a *legitimate* new compile the guard must not count),
    speculation off, two buckets.  A toy grammar is installed so the
    constrained decode/prefill programs join the gated set.

    ``decode_path="mesh"`` builds the SHARDED engine: a GSPMD mesh over
    every local device (TP on ``model``), weights and KV pages device-put
    with the SpecLayout-derived NamedShardings, attention on the XLA
    gather oracle (GSPMD partitions it from the annotations) — the same
    programs the v5e-8 serving config runs, minus real ICI.

    ``decode_path="quant"`` builds the int8-KV engine (kv_dtype="int8"):
    the engine's own impl selection routes decode through the gather/
    dequant reference off-TPU, and the donation set gains the per-page
    scale leaves — the guard asserts those rebind too."""
    import jax

    from k8s_llm_monitor_tpu.models import llama
    from k8s_llm_monitor_tpu.ops.attention import select_decode_impl
    from k8s_llm_monitor_tpu.serving.engine import EngineConfig, InferenceEngine

    mesh = None
    kv_dtype = "auto"
    tp_overlap = "off"
    prefill_path = "auto"
    if decode_path == "flash_prefill":
        # Flash paged prefill forced on (interpreter on CPU) while decode
        # stays on the gather oracle: every prefill/chunk program in the
        # gated set now traces flash_prefill_attention, and the donated
        # page pool rebinds through the kernel's pallas_call instead of
        # the scatter+gather XLA graph.
        cfg = _tiny_cfg(fused=False)
        impl = select_decode_impl(cfg=cfg, mode="gather")
        prefill_path = "flash"
    elif decode_path in ("mesh", "overlap"):
        from k8s_llm_monitor_tpu.parallel.mesh import MeshConfig, create_mesh

        tp = len(jax.devices())
        mesh = create_mesh(MeshConfig(model=tp))
        cfg = _tiny_cfg(fused=False, mesh_tp=tp)
        impl = select_decode_impl(cfg=cfg, mesh=mesh, mode="gather")
        # "mesh" pins the GSPMD-auto program (the correctness reference);
        # "overlap" requires the staged schedule — build fails loudly if
        # the tiny config ever stops clearing overlap_supported().
        if decode_path == "overlap":
            tp_overlap = "on" if tp > 1 else "off"
    elif decode_path == "quant":
        # attn_impl=None: the engine's select_decode_impl call sees the
        # quantized pool and picks the dequantizing path itself — the same
        # branch a production int8 config takes.
        cfg = _tiny_cfg(fused=False)
        impl = None
        kv_dtype = "int8"
    elif decode_path == "grammar_swap":
        # Same engine as "gather"; check_path swaps same-shape grammars
        # between (and inside) the passes.  The FSM table is a runtime
        # argument keyed only by shape, so the swap must not retrace.
        cfg = _tiny_cfg(fused=False)
        impl = select_decode_impl(cfg=cfg, mode="gather")
    else:
        cfg = _tiny_cfg(fused=decode_path == "fused")
        impl = select_decode_impl(cfg=cfg, mode=decode_path)
    params = llama.init_params(jax.random.PRNGKey(seed), cfg)
    ec = EngineConfig(
        max_slots=4, num_blocks=64, block_size=8, max_blocks_per_seq=8,
        prefill_buckets=(16, 32), max_prefills_per_step=2,
        max_admission_rounds=2, decode_steps_per_iter=4, max_inflight=2,
        spec_k=0, prefix_cache_entries=0, sample_topk_cap=8,
        kv_dtype=kv_dtype, tp_overlap=tp_overlap, prefill_path=prefill_path,
    )
    engine = InferenceEngine(cfg, params, engine_cfg=ec, eos_id=-1,
                             attn_impl=impl, mesh=mesh)
    engine.set_grammar(_toy_fsm())
    return engine


# ---------------------------------------------------------------------------
# compile accounting
# ---------------------------------------------------------------------------

def _engine_programs(engine) -> list[Any]:
    progs = [engine._prefill_sample, engine._prefill_greedy,
             engine._prefill_chunk_sample, engine._prefill_chunk_greedy,
             engine._prefill_sample_fsm, engine._prefill_chunk_sample_fsm,
             engine._place_tokens, engine._place_fsm]
    if engine._hist_place is not None:
        progs.append(engine._hist_place)
    progs.extend(engine._decode_cache.values())
    return progs


def program_cache_size(engine) -> int:
    """Total compiled-variant count across every jitted engine program.
    The delta across a workload is the number of new compilations it
    triggered — deterministic, unlike wall-clock or log scraping."""
    total = 0
    for prog in _engine_programs(engine):
        size = getattr(prog, "_cache_size", None)
        if callable(size):
            total += size()
    return total


class CompileEvents:
    """Context manager counting backend-compile events via jax.monitoring
    (supporting evidence beside the cache-size delta; the persistent
    compilation cache can serve hits that still emit cache events, so
    this is reported but not gated on)."""

    _COMPILE_MARKERS = ("compile", "backend_compile")

    def __init__(self):
        self.events: list[str] = []

    def _listener(self, event: str, **kwargs) -> None:
        if any(m in event for m in self._COMPILE_MARKERS):
            self.events.append(event)

    def __enter__(self) -> "CompileEvents":
        import jax.monitoring

        jax.monitoring.register_event_listener(self._listener)
        return self

    def __exit__(self, *exc) -> None:
        try:
            from jax._src import monitoring as _m

            _m._unregister_event_listener_by_callback(self._listener)
        except Exception:
            # jax-internal unregister moved; dropping every listener is
            # acceptable in the CLI/test contexts this runs in.
            import jax.monitoring

            jax.monitoring.clear_event_listeners()

    @property
    def count(self) -> int:
        return len(self.events)


def count_new_compiles(engine, fn: Callable[[], Any]) -> tuple[int, int]:
    """Run ``fn`` and return (new compiled variants, monitoring events).
    The first number is the gate; the second is evidence."""
    before = program_cache_size(engine)
    with CompileEvents() as ev:
        fn()
    return program_cache_size(engine) - before, ev.count


# ---------------------------------------------------------------------------
# jaxpr scanning
# ---------------------------------------------------------------------------

def _iter_eqns(jaxpr):
    """Every equation in ``jaxpr``, descending into sub-jaxprs carried in
    eqn params (pjit bodies, scan bodies, cond branches...)."""
    closed = getattr(jaxpr, "jaxpr", None)
    if closed is not None:           # ClosedJaxpr -> Jaxpr
        jaxpr = closed
    for eqn in jaxpr.eqns:
        yield eqn
        for val in eqn.params.values():
            for sub in (val if isinstance(val, (list, tuple)) else (val,)):
                if hasattr(sub, "eqns") or hasattr(sub, "jaxpr"):
                    yield from _iter_eqns(sub)


def forbidden_ops(jaxpr) -> list[str]:
    return sorted({eqn.primitive.name for eqn in _iter_eqns(jaxpr)
                   if eqn.primitive.name in FORBIDDEN_PRIMITIVES})


def scan_engine_programs(engine) -> dict[str, list[str]]:
    """make_jaxpr every hot entry point (decode greedy + sampled, prefill
    greedy + sampled) with engine-shaped arguments and report any
    forbidden host-callback primitives, keyed by program name."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    ec = engine.ecfg
    B = ec.max_slots
    bucket = ec.prefill_buckets[0]
    W = ec.max_blocks_per_seq
    pages = engine.pages
    params = engine.params
    out: dict[str, list[str]] = {}

    dec_tables = jnp.asarray(np.tile(
        np.arange(1, W + 1, dtype=np.int32)[None, :], (B, 1)))
    tok = jnp.zeros((B,), jnp.int32)
    ctx = jnp.ones((B,), jnp.int32)
    remaining = jnp.full((B,), 8, jnp.int32)
    eos = jnp.asarray(-1, jnp.int32)
    K = ec.decode_steps_per_iter

    greedy = engine._decode_program(K, sampled=False)
    out["decode_greedy"] = forbidden_ops(jax.make_jaxpr(greedy)(
        params, tok, ctx, remaining, pages, dec_tables, eos))

    sampled = engine._decode_program(K, sampled=True,
                                     bounded=ec.sample_topk_cap > 0)
    temp = jnp.full((B,), 0.7, jnp.float32)
    topk = jnp.full((B,), 4, jnp.int32)
    topp = jnp.full((B,), 0.9, jnp.float32)
    rng = jax.random.PRNGKey(0)
    out["decode_sampled"] = forbidden_ops(jax.make_jaxpr(sampled)(
        params, tok, ctx, remaining, pages, dec_tables, temp, topk, topp,
        rng, eos))

    if engine._fsm_trans is not None:
        constrained = engine._decode_program(
            K, sampled=True, bounded=ec.sample_topk_cap > 0,
            constrained=True)
        fstate = jnp.ones((B,), jnp.int32)
        out["decode_constrained"] = forbidden_ops(jax.make_jaxpr(constrained)(
            params, tok, fstate, ctx, remaining, pages, dec_tables,
            engine._fsm_trans, temp, topk, topp, rng, eos))

    P = 1
    ptoks = jnp.zeros((P, bucket), jnp.int32)
    plens = jnp.full((P,), bucket, jnp.int32)
    ptbl = jnp.asarray(np.arange(1, W + 1, dtype=np.int32)[None, :])
    out["prefill_greedy"] = forbidden_ops(jax.make_jaxpr(
        engine._prefill_greedy)(params, ptoks, plens, pages, ptbl))
    out["prefill_sampled"] = forbidden_ops(jax.make_jaxpr(
        engine._prefill_sample)(
            params, ptoks, plens, pages, ptbl,
            jnp.full((P,), 0.7, jnp.float32), jnp.full((P,), 4, jnp.int32),
            jnp.full((P,), 0.9, jnp.float32), rng))
    if engine._fsm_trans is not None:
        out["prefill_constrained"] = forbidden_ops(jax.make_jaxpr(
            engine._prefill_sample_fsm)(
                params, ptoks, plens, pages, ptbl,
                jnp.ones((P,), jnp.int32), engine._fsm_trans,
                jnp.full((P,), 0.7, jnp.float32),
                jnp.full((P,), 4, jnp.int32),
                jnp.full((P,), 0.9, jnp.float32), rng))
    return out


# ---------------------------------------------------------------------------
# the guard
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class PathReport:
    decode_path: str
    warm_compiles: int
    warm_events: int
    repeat_compiles: int
    repeat_events: int
    forbidden: dict[str, list[str]]
    donated_pages_rebound: bool
    donated_tokens_rebound: bool
    donated_fsm_rebound: bool = True
    donated_scales_rebound: bool = True
    kv_quant: str = ""
    prefill_path: str = "dense"

    @property
    def ok(self) -> bool:
        return (self.repeat_compiles == 0
                and not any(self.forbidden.values())
                and self.donated_pages_rebound
                and self.donated_tokens_rebound
                and self.donated_fsm_rebound
                and self.donated_scales_rebound)

    def as_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["ok"] = self.ok
        return d


def _drive(engine, prompt_len: int, greedy: bool, tag: int,
           constrained: bool = False) -> None:
    """One generation in the first prefill bucket: 4 tokens, distinct
    prompt content per ``tag`` (same shapes, different values — content
    must never matter to the compile count)."""
    from k8s_llm_monitor_tpu.serving.engine import SamplingParams

    prompt = [(tag * 7 + i) % 100 + 1 for i in range(prompt_len)]
    sampling = (SamplingParams(max_tokens=4, constrained=constrained)
                if greedy
                else SamplingParams(max_tokens=4, temperature=0.7, top_k=4,
                                    constrained=constrained))
    res = engine.generate([prompt], sampling)[0]
    assert res.finish_reason in ("eos", "length"), res


def check_path(decode_path: str) -> PathReport:
    engine = build_engine(decode_path)
    swap = decode_path == "grammar_swap"

    # prompt_len 40 > the top bucket (32): forces the chunk-round admission
    # path, so the chunk-prefill programs (plain + FSM) are compiled in the
    # warm pass and gated for zero recompiles in the repeat pass — on the
    # mesh path these are the SHARDED chunk programs.
    def warm():
        _drive(engine, prompt_len=12, greedy=True, tag=1)
        _drive(engine, prompt_len=12, greedy=False, tag=2)
        _drive(engine, prompt_len=12, greedy=False, tag=5, constrained=True)
        _drive(engine, prompt_len=40, greedy=True, tag=7)
        _drive(engine, prompt_len=40, greedy=False, tag=8, constrained=True)

    def repeat():
        # The grammar_swap path installs a different same-shape FSM before
        # each constrained drive (and swaps back once mid-pass): the swap
        # rebinds the runtime table argument, so the compile-count gate
        # below must still read zero.
        if swap:
            engine.set_grammar(_toy_fsm(variant=1))
        _drive(engine, prompt_len=12, greedy=True, tag=3)
        _drive(engine, prompt_len=12, greedy=False, tag=4)
        _drive(engine, prompt_len=12, greedy=False, tag=6, constrained=True)
        if swap:
            engine.set_grammar(_toy_fsm(variant=0))
        _drive(engine, prompt_len=40, greedy=True, tag=9)
        _drive(engine, prompt_len=40, greedy=False, tag=10, constrained=True)

    warm_c, warm_e = count_new_compiles(engine, warm)
    pages_before = engine.pages
    toks_before = engine._tok_state
    fsm_before = engine._fsm_state
    repeat_c, repeat_e = count_new_compiles(engine, repeat)
    # A quantized pool widens the donation set: the per-page scale leaves
    # ride along with k/v into every dispatch and must rebind the same way
    # (a stale scale alias silently dequantizes new pages with old scales).
    scales_rebound = True
    if engine.kv_quant:
        scales_rebound = (
            engine.pages.k_scale[0] is not pages_before.k_scale[0]
            and engine.pages.v_scale[0] is not pages_before.v_scale[0])
    report = PathReport(
        decode_path=decode_path,
        warm_compiles=warm_c, warm_events=warm_e,
        repeat_compiles=repeat_c, repeat_events=repeat_e,
        forbidden=scan_engine_programs(engine),
        # The engine donates pages and the token/FSM-state buffers into
        # every constrained dispatch; after the repeat pass it must hold
        # fresh outputs, not an alias of something it donated away.
        donated_pages_rebound=engine.pages is not pages_before,
        donated_tokens_rebound=engine._tok_state is not toks_before,
        donated_fsm_rebound=engine._fsm_state is not fsm_before,
        donated_scales_rebound=scales_rebound,
        kv_quant=engine.kv_quant,
        prefill_path=engine.prefill_path,
    )
    return report


def run_traceguard(paths=DEFAULT_PATHS) -> dict:
    """The full trace-time gate; returns the machine-readable report the
    CLI prints and tests consume."""
    reports = {p: check_path(p) for p in paths}
    return {
        "paths": {p: r.as_dict() for p, r in reports.items()},
        "ok": all(r.ok for r in reports.values()),
    }
