"""Lock-discipline / race detector (the ``K8SLLM_LOCKCHECK=1`` mode).

The engine loop, watchdog, request threads, metrics-manager loop, and
watcher reconnect threads share state behind a dozen locks; pytest cannot
see a lock-order inversion or an unlocked write — it only sees the rare
deadlock or corruption those bugs eventually cause.  This module is the
Python stand-in for the Go race detector the reference repo relied on
(PAPER.md §L4):

  * every lock in the serving/monitor/resilience planes is created through
    :func:`make_lock`, which returns a plain ``threading.Lock``/``RLock``
    in production (zero overhead) and an :class:`InstrumentedLock` when
    ``K8SLLM_LOCKCHECK=1``;
  * instrumented locks record, per acquisition, the set of locks the
    acquiring thread already holds — building a global lock-order graph
    whose cycles are *potential deadlocks* even if no run ever deadlocked;
  * holds longer than ``K8SLLM_LOCKCHECK_HOLD_MS`` (default 200) are
    flagged — a slow call under the engine-service handles lock stalls
    every request thread;
  * classes decorated with :func:`guarded_by` assert that writes to their
    registered shared fields happen with the owning lock held.

``report()`` aggregates everything; the chaos suite runs under this mode
and tests/conftest.py fails the session on a dirty report.

Import discipline: stdlib only.  resilience/faults.py imports this module
at interpreter startup; it must never pull in jax, numpy, or the lint.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field

ENV_FLAG = "K8SLLM_LOCKCHECK"
ENV_HOLD_MS = "K8SLLM_LOCKCHECK_HOLD_MS"
_FALSE = ("", "0", "false", "no", "off")


def enabled() -> bool:
    """True when the instrumented-lock mode is armed (checked at lock
    *creation* time — set the env var before constructing the objects
    under test)."""
    return os.environ.get(ENV_FLAG, "").lower() not in _FALSE


def hold_warn_ms() -> float:
    try:
        return float(os.environ.get(ENV_HOLD_MS, "200"))
    except ValueError:
        return 200.0


# Per-thread stack of InstrumentedLock names currently held, outermost
# first.  RLock re-entries do not push a second frame.
_held = threading.local()


def _held_stack() -> list[str]:
    stack = getattr(_held, "stack", None)
    if stack is None:
        stack = []
        _held.stack = stack
    return stack


@dataclass
class LongHold:
    lock: str
    held_ms: float
    thread: str


@dataclass
class UnguardedWrite:
    cls: str
    attr: str
    lock: str
    thread: str


@dataclass
class Registry:
    """Global evidence store for one lockcheck run.

    ``edges`` is the lock-order graph: ``(a, b)`` means some thread
    acquired ``b`` while holding ``a``.  A cycle in this graph is a
    potential deadlock regardless of whether any run has interleaved badly
    enough to hit it.
    """

    edges: dict[tuple[str, str], int] = field(default_factory=dict)
    locks: set[str] = field(default_factory=set)
    long_holds: list[LongHold] = field(default_factory=list)
    unguarded_writes: list[UnguardedWrite] = field(default_factory=list)
    acquisitions: dict[str, int] = field(default_factory=dict)
    max_hold_ms: dict[str, float] = field(default_factory=dict)
    _mu: threading.Lock = field(default_factory=threading.Lock)

    # -- recording (called by InstrumentedLock / guarded_by) ------------

    def note_acquire(self, name: str, held: list[str]) -> None:
        with self._mu:
            self.locks.add(name)
            self.acquisitions[name] = self.acquisitions.get(name, 0) + 1
            for h in held:
                if h != name:
                    self.edges[(h, name)] = self.edges.get((h, name), 0) + 1

    def note_release(self, name: str, held_ms: float) -> None:
        with self._mu:
            if held_ms > self.max_hold_ms.get(name, 0.0):
                self.max_hold_ms[name] = held_ms
            if held_ms > hold_warn_ms():
                self.long_holds.append(LongHold(
                    lock=name, held_ms=round(held_ms, 3),
                    thread=threading.current_thread().name))

    def note_unguarded(self, cls: str, attr: str, lock: str) -> None:
        with self._mu:
            self.unguarded_writes.append(UnguardedWrite(
                cls=cls, attr=attr, lock=lock,
                thread=threading.current_thread().name))

    # -- analysis -------------------------------------------------------

    def cycles(self) -> list[list[str]]:
        """Elementary cycles in the lock-order graph (DFS; the graph has
        tens of nodes at most, so no Johnson's needed)."""
        with self._mu:
            adj: dict[str, set[str]] = {}
            for a, b in self.edges:
                adj.setdefault(a, set()).add(b)
        out: list[list[str]] = []
        seen_cycles: set[tuple[str, ...]] = set()

        def dfs(start: str, node: str, path: list[str],
                on_path: set[str]) -> None:
            for nxt in sorted(adj.get(node, ())):
                if nxt == start:
                    # Canonicalize by rotating the smallest name first so
                    # the same cycle found from two starts dedups.
                    cyc = path[:]
                    k = cyc.index(min(cyc))
                    canon = tuple(cyc[k:] + cyc[:k])
                    if canon not in seen_cycles:
                        seen_cycles.add(canon)
                        out.append(list(canon))
                elif nxt not in on_path and nxt > start:
                    # Only explore nodes > start: each cycle is found from
                    # its smallest member exactly once.
                    on_path.add(nxt)
                    dfs(start, nxt, path + [nxt], on_path)
                    on_path.discard(nxt)

        for start in sorted(adj):
            dfs(start, start, [start], {start})
        return out

    def report(self) -> dict:
        cycles = self.cycles()
        with self._mu:
            return {
                "enabled": enabled(),
                "locks": sorted(self.locks),
                "acquisitions": dict(sorted(self.acquisitions.items())),
                "order_edges": sorted(
                    f"{a} -> {b}" for (a, b) in self.edges),
                "cycles": cycles,
                "long_holds": [vars(h) for h in self.long_holds],
                "max_hold_ms": {k: round(v, 3) for k, v in
                                sorted(self.max_hold_ms.items())},
                "unguarded_writes": [vars(w) for w in self.unguarded_writes],
                "ok": not cycles and not self.unguarded_writes,
            }

    def assert_clean(self) -> None:
        rep = self.report()
        problems = []
        if rep["cycles"]:
            problems.append(f"lock-order cycles: {rep['cycles']}")
        if rep["unguarded_writes"]:
            problems.append(
                f"unguarded shared-state writes: {rep['unguarded_writes']}")
        if problems:
            raise AssertionError("lockcheck: " + "; ".join(problems))

    def reset(self) -> None:
        with self._mu:
            self.edges.clear()
            self.locks.clear()
            self.long_holds.clear()
            self.unguarded_writes.clear()
            self.acquisitions.clear()
            self.max_hold_ms.clear()


_registry = Registry()


def registry() -> Registry:
    return _registry


class InstrumentedLock:
    """Drop-in for ``threading.Lock``/``RLock`` that feeds the registry.

    Tracks the owning thread (so :func:`guarded_by` can ask ``held_by_me``
    even for non-reentrant locks) and the re-entry depth (so an RLock
    re-entry records neither a new order edge nor a nested hold span).
    """

    def __init__(self, name: str, reentrant: bool = False,
                 reg: Registry | None = None):
        self.name = name
        self._reentrant = reentrant
        self._inner = threading.RLock() if reentrant else threading.Lock()
        self._reg = reg or _registry
        self._owner: int | None = None
        self._depth = 0
        self._t0 = 0.0

    @property
    def held_by_me(self) -> bool:
        return self._owner == threading.get_ident()

    def locked(self) -> bool:
        return self._owner is not None

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        me = threading.get_ident()
        if self._reentrant and self._owner == me:
            self._inner.acquire()
            self._depth += 1
            return True
        self._reg.note_acquire(self.name, _held_stack())
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._owner = me
            self._depth = 1
            self._t0 = time.monotonic()
            _held_stack().append(self.name)
        return ok

    def release(self) -> None:
        if self._owner != threading.get_ident():
            raise RuntimeError(
                f"lock {self.name!r} released by non-owner thread")
        self._depth -= 1
        if self._depth == 0:
            held_ms = (time.monotonic() - self._t0) * 1e3
            self._owner = None
            stack = _held_stack()
            if self.name in stack:
                stack.remove(self.name)
            self._reg.note_release(self.name, held_ms)
        self._inner.release()

    def __enter__(self) -> "InstrumentedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


def make_lock(name: str, reentrant: bool = False):
    """The one lock factory for the serving/monitor/resilience planes.

    Production (env flag unset): a plain ``threading.Lock`` / ``RLock`` —
    identical cost to constructing one directly.  ``K8SLLM_LOCKCHECK=1``:
    an :class:`InstrumentedLock` wired into the global registry."""
    if enabled():
        return InstrumentedLock(name, reentrant=reentrant)
    return threading.RLock() if reentrant else threading.Lock()


def guarded_by(lock_attr: str, *fields: str):
    """Class decorator registering shared fields owned by ``lock_attr``.

    With lockcheck enabled, every ``self.<field> = ...`` outside the
    owning lock is recorded as an unguarded write (writes before the lock
    exists — i.e. during ``__init__`` — are exempt, as is any setup done
    while the lock is a plain non-instrumented lock).  Disabled: returns
    the class untouched, so production pays nothing.
    """

    def deco(cls):
        if not enabled():
            return cls
        watched = frozenset(fields)
        orig_setattr = cls.__setattr__

        def checked_setattr(self, name, value):
            if name in watched:
                lock = getattr(self, lock_attr, None)
                if (isinstance(lock, InstrumentedLock)
                        and not lock.held_by_me):
                    _registry.note_unguarded(
                        cls.__name__, name, lock.name)
            orig_setattr(self, name, value)

        cls.__setattr__ = checked_setattr
        return cls

    return deco
