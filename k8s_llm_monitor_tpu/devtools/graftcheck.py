"""graftcheck CLI: the single entry point for all five analysis passes.

    python -m k8s_llm_monitor_tpu.devtools.graftcheck [paths...]
        AST lint over the given paths (default: the package itself).
        Exit 0 = clean, 1 = findings.

    python -m k8s_llm_monitor_tpu.devtools.graftcheck --dataflow
        Additionally run the whole-program dataflow pass (call graph +
        taint): blocking-in-hot-path, recompile-hazard,
        lock-order-static.  Analyzes the package as one program, so it
        ignores positional ``paths``.

    python -m k8s_llm_monitor_tpu.devtools.graftcheck --contracts
        Additionally run the contract-drift checkers (routes, metrics,
        env keys) against README.md, docs/ and the Makefile.

    python -m k8s_llm_monitor_tpu.devtools.graftcheck --trace
        Additionally run the trace-time guards (compile-count stability,
        forbidden host-callback ops, donation rebinding) on CPU.  Slower
        (it jit-compiles a tiny engine), so `make lint` runs the static
        passes; the trace pass is enforced by tests/test_graftcheck.py
        in tier-1 and available here for ad-hoc use.

    --json emits one machine-readable document for CI annotation.
    --list-rules documents every rule and its name (the token used in
    `# graftcheck: disable=...` suppressions).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path


def _package_root() -> Path:
    return Path(__file__).resolve().parents[1]


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="graftcheck",
        description="JAX-aware static analysis, contract-drift checks + "
                    "trace-time gates (docs/devtools.md)")
    parser.add_argument("paths", nargs="*", type=Path,
                        help="files/dirs to lint (default: the package)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable output")
    parser.add_argument("--dataflow", action="store_true",
                        help="also run the interprocedural dataflow rules "
                             "(call graph over the whole package)")
    parser.add_argument("--contracts", action="store_true",
                        help="also run the contract-drift checkers "
                             "(routes/metrics/env vs README, docs/, "
                             "Makefile)")
    parser.add_argument("--trace", action="store_true",
                        help="also run the trace-time guards (jit-compiles "
                             "a tiny engine on CPU; slower)")
    parser.add_argument("--trace-paths",
                        default="gather,fused,mesh,quant,flash_prefill",
                        help="comma-separated engine paths for --trace "
                             "(default: gather,fused,mesh,quant,"
                             "flash_prefill)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print every rule and exit")
    args = parser.parse_args(argv)

    # Pin CPU before anything imports jax: the lint itself imports the
    # package (for FAULT_POINTS) and --trace builds an engine; neither
    # must grab a real TPU out from under a serving process.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from k8s_llm_monitor_tpu.devtools import astlint

    if args.list_rules:
        from k8s_llm_monitor_tpu.devtools import contracts, dataflow

        for rule in astlint.default_rules():
            print(f"{rule.name}: {rule.description}")
        print("blocking-in-hot-path: blocking call reachable from a "
              "serving hot entry (--dataflow)")
        print("recompile-hazard: host read / device sync / mutable "
              "capture in jit-traced flow (--dataflow)")
        print("lock-order-static: static lock acquisition-order cycle "
              "(--dataflow)")
        print("route-contract: routes registered vs documented, both "
              "directions (--contracts)")
        print("metrics-contract: exporter families vs docs inventory vs "
              "bench keys (--contracts)")
        print("env-contract: env reads vs ENV_KEYS registry vs docs "
              "(--contracts)")
        assert set(dataflow.DATAFLOW_RULE_NAMES) <= {
            "blocking-in-hot-path", "recompile-hazard",
            "lock-order-static"}
        assert set(contracts.CONTRACT_RULE_NAMES) <= {
            "route-contract", "metrics-contract", "env-contract"}
        return 0

    paths = args.paths or [_package_root()]
    findings = astlint.lint_paths(paths)

    dataflow_findings = None
    if args.dataflow:
        from k8s_llm_monitor_tpu.devtools import dataflow

        dataflow_findings = dataflow.analyze_paths([_package_root()])

    contract_findings = None
    if args.contracts:
        from k8s_llm_monitor_tpu.devtools import contracts

        contract_findings = contracts.run_contracts(
            _package_root().parent)

    trace_report = None
    if args.trace:
        from k8s_llm_monitor_tpu.devtools import traceguard

        traceguard.force_cpu()
        trace_report = traceguard.run_traceguard(
            tuple(p.strip() for p in args.trace_paths.split(",")
                  if p.strip()))

    ok = (not findings
          and not dataflow_findings
          and not contract_findings
          and (trace_report is None or trace_report["ok"]))
    if args.as_json:
        doc = {
            "astlint": {
                "findings": [f.as_dict() for f in findings],
                "count": len(findings),
            },
            "dataflow": None if dataflow_findings is None else {
                "findings": [f.as_dict() for f in dataflow_findings],
                "count": len(dataflow_findings),
            },
            "contracts": None if contract_findings is None else {
                "findings": [f.as_dict() for f in contract_findings],
                "count": len(contract_findings),
            },
            "traceguard": trace_report,
            "ok": ok,
        }
        print(json.dumps(doc, indent=2))
    else:
        print(astlint.render(findings))
        if dataflow_findings is not None:
            from k8s_llm_monitor_tpu.devtools import dataflow

            print(dataflow.render(dataflow_findings))
        if contract_findings is not None:
            from k8s_llm_monitor_tpu.devtools import contracts

            print(contracts.render(contract_findings))
        if trace_report is not None:
            for path, rep in trace_report["paths"].items():
                status = "ok" if rep["ok"] else "FAIL"
                print(f"graftcheck traceguard[{path}]: {status} "
                      f"(warm compiles={rep['warm_compiles']}, "
                      f"repeat compiles={rep['repeat_compiles']}, "
                      f"forbidden ops="
                      f"{sum(map(len, rep['forbidden'].values()))}, "
                      f"donation rebound="
                      f"{rep['donated_pages_rebound'] and rep['donated_tokens_rebound'] and rep['donated_scales_rebound']})")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
