"""Whole-program dataflow lint: call graph + interprocedural rules.

The per-node rules in :mod:`astlint` cannot see that ``time.sleep`` is
*reachable from* the decode hot loop three calls away, or that a helper
called from inside a jit closure does a host read, or that two modules
acquire the same pair of locks in opposite orders.  This module builds a
package-wide call graph over the AST (no imports executed), runs a small
reaching-defs/taint walk inside each function, and powers three
interprocedural rules on top:

``blocking-in-hot-path``
    time.sleep / socket / urllib / file IO / subprocess reachable via the
    call graph from the serving hot entry points (engine ``step()``,
    prefill/decode dispatch, router dispatch/pump).  Structural
    exclusions, in order of precedence:

    * the enclosing function is *watchdog-guarded* — its body references
      the dispatch watchdog (``watchdog_trips`` / ``dispatch_timeout_s``):
      blocking there is the bounded wait the watchdog exists to supervise;
    * the blocking call carries an explicit ``timeout=`` keyword (bounded
      by construction — the router's hedged HTTP fan-out lives here);
    * a ``time.sleep`` whose duration taints back to the fault injector
      (``*.delay_s(...)``) — chaos hooks are dormant in production;
    * the call site is in a *sanctioned* (module, reason) pair listed in
      :data:`SANCTIONED_BLOCKING` — e.g. the WAL append in
      ``resilience/journal.py``, where the blocking write *is* the
      durability contract.

``recompile-hazard``
    Host reads (``time.time``/``os.environ``/``.item()`` /
    ``jax.device_get`` / ``block_until_ready`` / ``np.asarray``) inside
    functions that *flow into* jit-traced closures via the call graph —
    the static complement of traceguard's dynamic proof, covering all
    code rather than the five traced paths.  Direct host reads inside the
    jit root itself are astlint's ``jit-host-read``; this rule reports
    the interprocedural cases (callees) plus two hazards astlint cannot
    see anywhere: device->host syncs (``.item()`` et al.) and mutable
    closure captures handed to ``jax.jit`` (an unhashable or per-call-
    varying capture retriggers tracing every call).

``lock-order-static``
    Cross-module lock-acquisition orderings that form a cycle — the
    static twin of lockcheck's runtime DFS.  Lock identity comes from
    ``make_lock("name")`` assignment sites, so ``self._lock`` in two
    different classes never unifies; edges come from lexically nested
    ``with`` blocks *and* from calls made while a lock is held, resolved
    through the call graph with a transitive may-acquire fixpoint.

Suppression uses the established ``# graftcheck: disable=RULE`` comment
on the line of the *anchoring site* (the sleep, the host read, the inner
acquisition).  Unit-tested on fixture packages in
tests/test_dataflow.py; run via ``graftcheck --dataflow``.
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path
from typing import Iterable, Iterator

from .astlint import (Finding, _suppressions, dotted_name, iter_py_files,
                      jit_bodies)

DATAFLOW_RULE_NAMES = ("blocking-in-hot-path", "recompile-hazard",
                       "lock-order-static")

PACKAGE = "k8s_llm_monitor_tpu"

#: Hot-path roots: (path suffix, qualified function name).  Everything
#: transitively reachable from these is "hot" for blocking-in-hot-path.
HOT_ENTRIES: tuple[tuple[str, str], ...] = (
    ("serving/engine.py", "InferenceEngine.step"),
    ("serving/engine.py", "InferenceEngine._dispatch_prefill_chunks"),
    ("serving/engine.py", "InferenceEngine._dispatch_decode"),
    ("serving/service.py", "EngineService._run"),
    ("fleet/router.py", "FleetRouter._dispatch_tokens"),
    ("fleet/router.py", "FleetRouter._dispatch_text"),
    ("fleet/router.py", "FleetRouter._pump"),
)

#: (path suffix, reason) pairs where blocking calls are the contract.
#: Kept deliberately short; every entry must say *why* in one clause.
SANCTIONED_BLOCKING: tuple[tuple[str, str], ...] = (
    ("resilience/journal.py",
     "WAL durability: the fsync'd append IS the contract"),
    ("observability/flight.py",
     "crash-edge flight dump: runs once, on the way down"),
)

#: Method names too generic to resolve by name alone — linking every
#: ``x.get(...)`` to every ``def get`` in the package would drown the
#: graph in false edges.
_FALLBACK_STOPLIST = frozenset({
    "get", "put", "pop", "items", "keys", "values", "append", "extend",
    "add", "update", "clear", "copy", "remove", "discard", "sort",
    "index", "count", "read", "write", "close", "flush", "seek", "tell",
    "encode", "decode", "split", "strip", "join", "format", "lower",
    "upper", "startswith", "endswith", "group", "match", "search", "sub",
    "findall", "acquire", "release", "notify", "notify_all", "wait",
    "set", "is_set", "isoformat", "timestamp", "result", "done", "name",
    "cancel", "send", "recv", "keys", "exists", "mkdir", "touch",
})
_FALLBACK_MAX_CANDIDATES = 6

_WATCHDOG_MARKERS = ("watchdog_trips", "watchdog", "dispatch_timeout_s")
_FAULT_RECEIVER_HINTS = ("fault", "injector", "inj")


# ---------------------------------------------------------------------------
# package index
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class FuncInfo:
    qname: str                 # "<dotted module>::Class.method" / "::func"
    module: str                # dotted module name
    cls: str | None
    name: str                  # bare function name
    qual: str                  # "Class.method" or "func" (or "outer.<locals>.f")
    node: ast.FunctionDef | ast.AsyncFunctionDef
    path: str

    @property
    def display(self) -> str:
        return f"{self.module.rsplit('.', 1)[-1]}.{self.qual}"


@dataclasses.dataclass
class ModuleInfo:
    module: str                          # dotted name
    path: str
    tree: ast.Module
    src: str
    functions: dict[str, FuncInfo]       # qual -> FuncInfo
    imports: dict[str, str]              # local alias -> dotted target
    classes: dict[str, ast.ClassDef]
    bases: dict[str, list[str]]          # class -> base local names


class PackageIndex:
    """All modules + functions of the scanned tree, with import maps."""

    def __init__(self) -> None:
        self.modules: dict[str, ModuleInfo] = {}
        self.funcs: dict[str, FuncInfo] = {}          # qname -> info
        self.methods: dict[str, list[FuncInfo]] = {}  # method name -> infos
        self.node_to_func: dict[int, FuncInfo] = {}   # id(ast node) -> info

    # -- construction -------------------------------------------------

    @staticmethod
    def _dotted_module(path: Path) -> str:
        parts = list(path.with_suffix("").parts)
        if PACKAGE in parts:
            parts = parts[parts.index(PACKAGE):]
        else:
            parts = parts[-1:]
        if parts and parts[-1] == "__init__":
            parts = parts[:-1] or parts
        return ".".join(parts)

    def add_module(self, path: Path, src: str) -> None:
        try:
            tree = ast.parse(src)
        except SyntaxError:
            return  # astlint reports parse errors; skip here
        module = self._dotted_module(path)
        imports: dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    imports[alias.asname or alias.name.split(".")[0]] = \
                        alias.name if alias.asname else alias.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:  # relative: resolve against this module
                    pkg = module.split(".")
                    pkg = pkg[:len(pkg) - node.level]
                    base = ".".join(pkg + ([base] if base else []))
                for alias in node.names:
                    imports[alias.asname or alias.name] = f"{base}.{alias.name}"
        classes = {n.name: n for n in tree.body
                   if isinstance(n, ast.ClassDef)}
        bases = {cname: [dotted_name(b).rsplit(".", 1)[-1]
                         for b in cnode.bases if dotted_name(b)]
                 for cname, cnode in classes.items()}
        info = ModuleInfo(module=module, path=str(path), tree=tree, src=src,
                          functions={}, imports=imports, classes=classes,
                          bases=bases)
        self.modules[module] = info
        self._register_functions(info)

    def _register_functions(self, mi: ModuleInfo) -> None:
        def register(node, cls: str | None, prefix: str) -> None:
            qual = f"{prefix}{node.name}"
            fi = FuncInfo(qname=f"{mi.module}::{qual}", module=mi.module,
                          cls=cls, name=node.name, qual=qual,
                          node=node, path=mi.path)
            mi.functions[qual] = fi
            self.funcs[fi.qname] = fi
            self.node_to_func[id(node)] = fi
            if cls is not None:
                self.methods.setdefault(node.name, []).append(fi)
            for sub in node.body:
                walk(sub, cls, f"{qual}.<locals>.")

        def walk(node, cls: str | None, prefix: str) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                register(node, cls, prefix)
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    walk(sub, node.name, f"{prefix}{node.name}.")
            elif isinstance(node, (ast.If, ast.Try, ast.With, ast.For,
                                   ast.While)):
                for sub in ast.iter_child_nodes(node):
                    walk(sub, cls, prefix)

        for top in mi.tree.body:
            walk(top, None, "")

    # -- method resolution helpers ------------------------------------

    def _class_method(self, mi: ModuleInfo, cls: str,
                      meth: str) -> FuncInfo | None:
        """Look up a method on a class, following base-class names through
        the index (by bare name — a lint-grade MRO)."""
        seen: set[str] = set()
        queue = [(mi, cls)]
        while queue:
            m, c = queue.pop(0)
            if (m.module, c) in seen:
                continue
            seen.add((m.module, c))
            fi = m.functions.get(f"{c}.{meth}")
            if fi is not None:
                return fi
            for base in m.bases.get(c, []):
                for m2 in self.modules.values():
                    if base in m2.classes:
                        queue.append((m2, base))
        return None

    def resolve_call(self, call: ast.Call, fi: FuncInfo) -> list[FuncInfo]:
        """Best-effort static resolution of a call site to FuncInfos."""
        dn = dotted_name(call.func)
        if not dn:
            return []
        mi = self.modules[fi.module]
        parts = dn.split(".")
        # self.method(...) — own class first, then name fallback.
        if parts[0] == "self" and fi.cls and len(parts) == 2:
            hit = self._class_method(mi, fi.cls, parts[1])
            if hit is not None:
                return [hit]
            return self._by_method_name(parts[1])
        if parts[0] in ("self", "cls") and len(parts) > 2:
            return self._by_method_name(parts[-1])
        if len(parts) == 1:
            name = parts[0]
            # sibling nested def in the same enclosing function
            if "<locals>" in fi.qual:
                outer = fi.qual.rsplit(".<locals>.", 1)[0]
                sib = mi.functions.get(f"{outer}.<locals>.{name}")
                if sib is not None:
                    return [sib]
            # nested def of this function
            nested = mi.functions.get(f"{fi.qual}.<locals>.{name}")
            if nested is not None:
                return [nested]
            if name in mi.functions:
                return [mi.functions[name]]
            if name in mi.classes:
                init = mi.functions.get(f"{name}.__init__")
                return [init] if init is not None else []
            target = mi.imports.get(name, "")
            return self._from_import(target)
        # module-qualified: alias.func(...) where alias maps to a module
        head = mi.imports.get(parts[0], "")
        if head:
            hit = self._from_import(".".join([head] + parts[1:]))
            if hit:
                return hit
        return self._by_method_name(parts[-1])

    def _from_import(self, target: str) -> list[FuncInfo]:
        """Resolve a dotted target like pkg.mod.func or pkg.mod.Class."""
        if not target:
            return []
        mod, _, leaf = target.rpartition(".")
        mi = self.modules.get(mod)
        if mi is None:
            return []
        if leaf in mi.functions:
            return [mi.functions[leaf]]
        if leaf in mi.classes:
            init = mi.functions.get(f"{leaf}.__init__")
            return [init] if init is not None else []
        # re-export through __init__: follow one import hop
        fwd = mi.imports.get(leaf, "")
        if fwd and fwd != target:
            return self._from_import(fwd)
        return []

    def _by_method_name(self, meth: str) -> list[FuncInfo]:
        if meth in _FALLBACK_STOPLIST:
            return []
        cands = self.methods.get(meth, [])
        if not cands or len(cands) > _FALLBACK_MAX_CANDIDATES:
            return []
        return list(cands)


def build_index(paths: Iterable[Path]) -> PackageIndex:
    idx = PackageIndex()
    for root in paths:
        for p in iter_py_files(Path(root)):
            idx.add_module(p, p.read_text(encoding="utf-8"))
    return idx


# ---------------------------------------------------------------------------
# call graph + reachability
# ---------------------------------------------------------------------------

def _own_body(fi: FuncInfo) -> Iterator[ast.AST]:
    """Walk a function body, not descending into nested defs/lambdas
    (those are separate graph nodes, reached only if called)."""
    stack: list[ast.AST] = list(fi.node.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def call_edges(idx: PackageIndex,
               fi: FuncInfo) -> list[tuple[ast.Call, FuncInfo]]:
    out: list[tuple[ast.Call, FuncInfo]] = []
    for node in _own_body(fi):
        if isinstance(node, ast.Call):
            for callee in idx.resolve_call(node, fi):
                out.append((node, callee))
    return out


def reachable_from(idx: PackageIndex, roots: list[FuncInfo]
                   ) -> dict[str, tuple[str | None, int]]:
    """BFS over the call graph.  Returns {qname: (caller qname, call line)}
    with roots mapped to (None, 0) — enough to rebuild a witness chain."""
    pred: dict[str, tuple[str | None, int]] = {r.qname: (None, 0)
                                               for r in roots}
    queue = list(roots)
    while queue:
        fi = queue.pop(0)
        for call, callee in call_edges(idx, fi):
            if callee.qname in pred:
                continue
            pred[callee.qname] = (fi.qname, call.lineno)
            queue.append(callee)
    return pred


def witness_chain(idx: PackageIndex, pred: dict[str, tuple[str | None, int]],
                  qname: str, limit: int = 6) -> str:
    chain: list[str] = []
    cur: str | None = qname
    while cur is not None and len(chain) < limit:
        fi = idx.funcs.get(cur)
        chain.append(fi.display if fi else cur)
        cur = pred.get(cur, (None, 0))[0]
    return " <- ".join(chain)


# ---------------------------------------------------------------------------
# intraprocedural reaching defs (single-assignment approximation)
# ---------------------------------------------------------------------------

def reaching_defs(fi: FuncInfo) -> dict[str, ast.AST]:
    defs: dict[str, ast.AST] = {}
    for node in _own_body(fi):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    defs[tgt.id] = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            if isinstance(node.target, ast.Name):
                defs[node.target.id] = node.value
    return defs


def _expr_taints_fault_delay(expr: ast.AST,
                             defs: dict[str, ast.AST],
                             depth: int = 0) -> bool:
    """True if the expression (transitively through local names) contains
    a fault-injector delay read — ``*.delay_s(...)`` or a call on a
    receiver whose name hints at the injector."""
    if depth > 4:
        return False
    for node in ast.walk(expr) if not isinstance(expr, ast.Name) else [expr]:
        if isinstance(node, ast.Name):
            bound = defs.get(node.id)
            if bound is not None and bound is not expr \
                    and _expr_taints_fault_delay(bound, defs, depth + 1):
                return True
        elif isinstance(node, ast.Call):
            dn = dotted_name(node.func)
            if dn.endswith(".delay_s"):
                return True
            recv = dn.rsplit(".", 2)
            if len(recv) >= 2 and any(h in recv[-2].lower()
                                      for h in _FAULT_RECEIVER_HINTS):
                return True
    return False


# ---------------------------------------------------------------------------
# rule: blocking-in-hot-path
# ---------------------------------------------------------------------------

_BLOCKING_CALLS = {
    "time.sleep": "sleep",
    "socket.create_connection": "socket",
    "socket.getaddrinfo": "socket",
    "urllib.request.urlopen": "HTTP",
    "urlopen": "HTTP",
    "subprocess.run": "subprocess", "subprocess.Popen": "subprocess",
    "subprocess.check_output": "subprocess",
    "subprocess.check_call": "subprocess", "subprocess.call": "subprocess",
    "os.system": "subprocess",
}
_BLOCKING_METHOD_SUFFIXES = {
    "read_text": "file IO", "write_text": "file IO",
    "read_bytes": "file IO", "write_bytes": "file IO",
}
_REQUESTS_VERBS = {"get", "post", "put", "delete", "head", "patch",
                   "request"}


def _classify_blocking(call: ast.Call) -> str:
    dn = dotted_name(call.func)
    if dn in _BLOCKING_CALLS:
        return f"{dn} ({_BLOCKING_CALLS[dn]})"
    parts = dn.split(".")
    if dn == "open" or (parts[-1] == "open" and len(parts) >= 2
                        and parts[-2] in ("io", "gzip", "Path")):
        return f"{dn} (file IO)"
    if len(parts) >= 2 and parts[-2] == "requests" \
            and parts[-1] in _REQUESTS_VERBS:
        return f"{dn} (HTTP)"
    if parts[-1] in _BLOCKING_METHOD_SUFFIXES:
        return f"{dn} ({_BLOCKING_METHOD_SUFFIXES[parts[-1]]})"
    if parts[-1] == "join" and len(parts) >= 2 \
            and "thread" in parts[-2].lower():
        return f"{dn} (thread join)"
    return ""


def _has_timeout_kwarg(call: ast.Call) -> bool:
    return any(kw.arg == "timeout" for kw in call.keywords)


def _is_watchdog_guarded(fi: FuncInfo) -> bool:
    for node in ast.walk(fi.node):
        if isinstance(node, ast.Attribute) \
                and node.attr in _WATCHDOG_MARKERS:
            return True
        if isinstance(node, ast.Name) and node.id in _WATCHDOG_MARKERS:
            return True
    return False


def _sanction_reason(path: str) -> str:
    norm = path.replace("\\", "/")
    for suffix, reason in SANCTIONED_BLOCKING:
        if norm.endswith(suffix):
            return reason
    return ""


def check_blocking_in_hot_path(
        idx: PackageIndex,
        entries: Iterable[tuple[str, str]] = HOT_ENTRIES) -> list[Finding]:
    roots = [fi for fi in idx.funcs.values()
             for (sfx, qual) in entries
             if fi.qual == qual and fi.path.replace("\\", "/").endswith(sfx)]
    pred = reachable_from(idx, roots)
    findings: list[Finding] = []
    for qname in pred:
        fi = idx.funcs[qname]
        if _is_watchdog_guarded(fi):
            continue
        if _sanction_reason(fi.path):
            continue
        defs = reaching_defs(fi)
        for node in _own_body(fi):
            if not isinstance(node, ast.Call):
                continue
            label = _classify_blocking(node)
            if not label:
                continue
            if _has_timeout_kwarg(node):
                continue
            if label.startswith("time.sleep") and node.args \
                    and _expr_taints_fault_delay(node.args[0], defs):
                continue
            findings.append(Finding(
                path=fi.path, line=node.lineno, col=node.col_offset,
                rule="blocking-in-hot-path",
                message=(f"blocking call '{label}' reachable from a "
                         f"serving hot entry: "
                         f"{witness_chain(idx, pred, qname)}; move it off "
                         f"the step/dispatch path or bound it with the "
                         f"watchdog")))
    return findings


# ---------------------------------------------------------------------------
# rule: recompile-hazard
# ---------------------------------------------------------------------------

_HOST_READ_CALLS = {
    "time.time", "time.monotonic", "time.perf_counter", "time.time_ns",
    "time.monotonic_ns", "datetime.now", "datetime.utcnow",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "os.getenv",
    "random.seed", "random.random", "random.randint", "random.uniform",
    "random.choice", "random.randrange", "random.getrandbits",
}
_SYNC_SUFFIXES = ("item", "block_until_ready", "tolist")
_SYNC_CALLS = {"jax.device_get", "np.asarray", "numpy.asarray"}


def _jit_roots(idx: PackageIndex) -> list[FuncInfo]:
    roots: list[FuncInfo] = []
    for mi in idx.modules.values():
        for body in jit_bodies(mi.tree):
            fi = idx.node_to_func.get(id(body))
            if fi is not None:
                roots.append(fi)
    return roots


def _host_read_findings(fi: FuncInfo, is_root: bool,
                        chain: str) -> Iterator[Finding]:
    for node in _own_body(fi):
        if not isinstance(node, ast.Call):
            continue
        dn = dotted_name(node.func)
        parts = dn.split(".")
        hazard = ""
        if dn in _SYNC_CALLS or (len(parts) >= 2
                                 and parts[-1] in _SYNC_SUFFIXES
                                 and not node.args):
            hazard = (f"'{dn}()' forces a device->host sync during "
                      f"tracing (ConcretizationError or a silently baked "
                      f"value)")
        elif not is_root and (dn in _HOST_READ_CALLS
                              or dn.endswith(".seed")):
            # depth>=1 only: direct reads in the root are astlint's
            # jit-host-read; this rule adds the interprocedural cases.
            hazard = (f"'{dn}()' reads host state in a function traced "
                      f"via jit")
        if hazard:
            yield Finding(
                path=fi.path, line=node.lineno, col=node.col_offset,
                rule="recompile-hazard",
                message=f"{hazard}; traced via: {chain}")


def _capture_hazards(idx: PackageIndex, fi: FuncInfo) -> Iterator[Finding]:
    """``jax.jit(f)`` where nested ``f`` captures a name bound to a
    mutable literal in the enclosing scope: the capture is unhashable
    (TypeError at dispatch) or per-call-varying (retrace every call)."""
    from .astlint import _is_jit_expr
    mutable: dict[str, int] = {}
    for node in _own_body(fi):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and isinstance(
                        node.value, (ast.List, ast.Dict, ast.Set,
                                     ast.ListComp, ast.DictComp,
                                     ast.SetComp)):
                    mutable[tgt.id] = node.lineno
    if not mutable:
        return
    for node in _own_body(fi):
        if not (isinstance(node, ast.Call) and _is_jit_expr(node.func)
                and node.args and isinstance(node.args[0], ast.Name)):
            continue
        nested = idx.modules[fi.module].functions.get(
            f"{fi.qual}.<locals>.{node.args[0].id}")
        if nested is None:
            continue
        params = {a.arg for a in nested.node.args.args
                  + nested.node.args.kwonlyargs}
        local_defs = set(reaching_defs(nested))
        for sub in ast.walk(nested.node):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load) \
                    and sub.id in mutable \
                    and sub.id not in params and sub.id not in local_defs:
                yield Finding(
                    path=fi.path, line=node.lineno, col=node.col_offset,
                    rule="recompile-hazard",
                    message=(f"jax.jit({node.args[0].id}) captures "
                             f"'{sub.id}' bound to a mutable literal at "
                             f"line {mutable[sub.id]}; an unhashable or "
                             f"per-call-varying capture defeats the jit "
                             f"cache — pass it as a (hashable) argument"))
                break


def check_recompile_hazard(idx: PackageIndex) -> list[Finding]:
    roots = _jit_roots(idx)
    pred = reachable_from(idx, roots)
    findings: list[Finding] = []
    for qname in pred:
        fi = idx.funcs[qname]
        is_root = pred[qname][0] is None
        chain = witness_chain(idx, pred, qname)
        findings.extend(_host_read_findings(fi, is_root, chain))
    for fi in idx.funcs.values():
        findings.extend(_capture_hazards(idx, fi))
    return findings


# ---------------------------------------------------------------------------
# rule: lock-order-static
# ---------------------------------------------------------------------------

def _lock_identities(idx: PackageIndex) -> dict[tuple[str, str], str]:
    """Map (scope, attr/var) -> lock name from make_lock("name") sites.
    Scope is the class name for ``self.x = make_lock(...)`` and the
    module for module-level ``x = make_lock(...)``."""
    out: dict[tuple[str, str], str] = {}
    for mi in idx.modules.values():
        for node in ast.walk(mi.tree):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            dn = dotted_name(node.value.func)
            if dn.rsplit(".", 1)[-1] != "make_lock":
                continue
            if not (node.value.args
                    and isinstance(node.value.args[0], ast.Constant)):
                continue
            name = str(node.value.args[0].value)
            for tgt in node.targets:
                tdn = dotted_name(tgt)
                if tdn.startswith("self."):
                    # find enclosing class by scanning registered funcs
                    for fi in mi.functions.values():
                        if fi.cls and fi.node.lineno <= node.lineno \
                                <= (fi.node.end_lineno or fi.node.lineno):
                            out[(fi.cls, tdn[5:])] = name
                            break
                elif isinstance(tgt, ast.Name):
                    out[(mi.module, tgt.id)] = name
    return out


def _resolve_lock(expr: ast.AST, fi: FuncInfo,
                  idents: dict[tuple[str, str], str]) -> str | None:
    dn = dotted_name(expr)
    if not dn:
        return None
    if dn.startswith("self.") and fi.cls:
        attr = dn[5:]
        if (fi.cls, attr) in idents:
            return idents[(fi.cls, attr)]
        leaf = attr.rsplit(".", 1)[-1].lower()
        if any(k in leaf for k in ("lock", "mutex", "cond")):
            return f"{fi.cls}.{attr}"        # class-scoped identity
        return None
    if (fi.module, dn) in idents:
        return idents[(fi.module, dn)]
    leaf = dn.rsplit(".", 1)[-1].lower()
    if any(k in leaf for k in ("lock", "mutex", "cond")):
        return f"{fi.module}:{dn}"           # module-scoped identity
    return None


def _direct_acquires(idx: PackageIndex, idents: dict[tuple[str, str], str]
                     ) -> dict[str, list[tuple[str, ast.With]]]:
    out: dict[str, list[tuple[str, ast.With]]] = {}
    for fi in idx.funcs.values():
        acqs: list[tuple[str, ast.With]] = []
        for node in _own_body(fi):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lock = _resolve_lock(item.context_expr, fi, idents)
                    if lock is not None:
                        acqs.append((lock, node))
        out[fi.qname] = acqs
    return out


def _may_acquire(idx: PackageIndex,
                 direct: dict[str, list[tuple[str, ast.With]]]
                 ) -> dict[str, set[str]]:
    """Transitive lock-acquisition sets: fixpoint over the call graph."""
    edges: dict[str, set[str]] = {}
    for fi in idx.funcs.values():
        edges[fi.qname] = {c.qname for _, c in call_edges(idx, fi)}
    acq: dict[str, set[str]] = {q: {l for l, _ in direct.get(q, [])}
                                for q in idx.funcs}
    changed = True
    rounds = 0
    while changed and rounds < 50:
        changed = False
        rounds += 1
        for q, callees in edges.items():
            before = len(acq[q])
            for c in callees:
                acq[q] |= acq.get(c, set())
            if len(acq[q]) != before:
                changed = True
    return acq


def check_lock_order_static(idx: PackageIndex) -> list[Finding]:
    idents = _lock_identities(idx)
    direct = _direct_acquires(idx, idents)
    trans = _may_acquire(idx, direct)
    # order edges: (outer, inner) -> anchoring site
    sites: dict[tuple[str, str], tuple[str, int, str]] = {}

    def note(outer: str, inner: str, path: str, line: int,
             how: str) -> None:
        if outer != inner and (outer, inner) not in sites:
            sites[(outer, inner)] = (path, line, how)

    for fi in idx.funcs.values():
        def walk(node: ast.AST, held: tuple[str, ...]) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return
            new_held = held
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lock = _resolve_lock(item.context_expr, fi, idents)
                    if lock is not None:
                        for h in new_held:
                            note(h, lock, fi.path, node.lineno,
                                 "nested with")
                        new_held = new_held + (lock,)
                for sub in node.body:
                    walk(sub, new_held)
                return
            if isinstance(node, ast.Call) and held:
                for _, callee in ((node, c)
                                  for c in idx.resolve_call(node, fi)):
                    for lock in trans.get(callee.qname, set()):
                        for h in held:
                            note(h, lock, fi.path, node.lineno,
                                 f"call into {callee.display}")
            for sub in ast.iter_child_nodes(node):
                walk(sub, held)

        for stmt in fi.node.body:
            walk(stmt, ())

    # cycle detection over the order graph
    graph: dict[str, set[str]] = {}
    for (a, b) in sites:
        graph.setdefault(a, set()).add(b)
    findings: list[Finding] = []
    seen_cycles: set[tuple[str, ...]] = set()

    def dfs(start: str, cur: str, path: list[str]) -> None:
        for nxt in sorted(graph.get(cur, ())):
            if nxt == start and len(path) > 1:
                lo = path.index(min(path))
                canon = tuple(path[lo:] + path[:lo])
                if canon in seen_cycles:
                    continue
                seen_cycles.add(canon)
                edge = sites[(path[-1], start)]
                order = " -> ".join(path + [start])
                findings.append(Finding(
                    path=edge[0], line=edge[1], col=0,
                    rule="lock-order-static",
                    message=(f"lock acquisition order cycle {order} "
                             f"(closing edge via {edge[2]}); acquire in "
                             f"one global order or drop to a snapshot")))
            elif nxt not in path:
                dfs(start, nxt, path + [nxt])

    for n in sorted(graph):
        dfs(n, n, [n])
    return findings


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def analyze_index(idx: PackageIndex,
                  rules: Iterable[str] | None = None,
                  entries: Iterable[tuple[str, str]] = HOT_ENTRIES
                  ) -> list[Finding]:
    wanted = set(rules) if rules is not None else set(DATAFLOW_RULE_NAMES)
    findings: list[Finding] = []
    if "blocking-in-hot-path" in wanted:
        findings.extend(check_blocking_in_hot_path(idx, entries))
    if "recompile-hazard" in wanted:
        findings.extend(check_recompile_hazard(idx))
    if "lock-order-static" in wanted:
        findings.extend(check_lock_order_static(idx))
    # honor # graftcheck: disable=RULE on the anchoring line
    suppress_cache: dict[str, tuple[dict[int, set[str]], set[str]]] = {}
    out: list[Finding] = []
    for f in findings:
        if f.path not in suppress_cache:
            for mi in idx.modules.values():
                if mi.path == f.path:
                    suppress_cache[f.path] = _suppressions(mi.src)
                    break
            else:
                suppress_cache[f.path] = ({}, set())
        per_line, per_file = suppress_cache[f.path]
        if f.rule in per_file or "all" in per_file:
            continue
        line_rules = per_line.get(f.line, set())
        if f.rule in line_rules or "all" in line_rules:
            continue
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def analyze_paths(paths: Iterable[Path],
                  rules: Iterable[str] | None = None,
                  entries: Iterable[tuple[str, str]] = HOT_ENTRIES
                  ) -> list[Finding]:
    return analyze_index(build_index(paths), rules=rules, entries=entries)


def render(findings: list[Finding]) -> str:
    if not findings:
        return "graftcheck dataflow: clean"
    lines = [f.human() for f in findings]
    lines.append(f"graftcheck dataflow: {len(findings)} finding(s)")
    return "\n".join(lines)
