"""Contract-drift checkers: code artifacts vs docs, both directions.

The reference system's headline defect was drift, not logic: the README
documented ``POST /api/v1/query`` while the server never registered it —
an endpoint that existed only on paper.  This module makes that class of
bug structurally impossible by parsing the *real* artifacts on both
sides and diffing them:

``route-contract``
    The monitor server's ``_ROUTES`` table + ``_dispatch`` prefix routes
    and the uav-agent's route dict + ``/api/v1/command/<cmd>`` prefix,
    against every route mentioned in README.md and docs/*.md.  Both
    directions: documented-but-unregistered AND registered-but-
    undocumented.  Paths are normalized (``{name}``/``<name>`` segments
    become a wildcard, ``{a,b,c}`` alternation expands, query strings
    drop); agent routes are recognized by their ``:9090`` prefix in
    docs.

``metrics-contract``
    Every gauge/counter/histogram family the exporter emits (literal
    ``w.metric("name", ...)``/``w.histogram("name", ...)`` calls, tuple-
    literal histogram tables, and manual ``w.lines.append(f"{_PREFIX}_
    ...")`` samples) against the machine-parseable inventory table in
    ``docs/observability.md`` — both directions — plus every
    ``k8s_llm_monitor_*`` token mentioned anywhere in the docs.  Bench
    JSON keys cited in README.md/Makefile are verified against the keys
    ``bench.py`` actually emits (literal dict keys and subscript stores;
    f-string keys like ``prefill_speedup_{length}`` match as prefix
    wildcards).  A doc token counts as a bench-key claim only when its
    first two ``_``-segments match an emitted key family — identifiers
    like ``slo_class`` never enter the contract.

``env-contract``
    Every literal ``os.environ``/``os.getenv`` read of a project-
    prefixed (``K8SLLM_*``/``OPENAI_*``) key must appear in the
    ``ENV_KEYS`` registry in ``monitor/config.py``; every registry entry
    must map to a real config dataclass field (``Class.field``,
    validated against the package AST) or an explicit runtime-toggle
    owner module that reads it; every registry key must be read
    somewhere and mentioned in the docs; and every ``K8SLLM_*`` token in
    the docs must be registered.  Keys derived generically by
    ``_apply_env`` (``fleet.role`` -> ``FLEET_ROLE``) are computed from
    the config dataclass tree and accepted as documented aliases.

All checkers take source text (so tests can feed deliberately drifted
fixtures) and anchor findings at real file:line positions, honoring the
``# graftcheck: disable=RULE`` convention — though the policy for drift
findings is to reconcile, never suppress.  Run via
``graftcheck --contracts``.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Iterable

from .astlint import Finding, _suppressions

CONTRACT_RULE_NAMES = ("route-contract", "metrics-contract", "env-contract")

PACKAGE = "k8s_llm_monitor_tpu"
METRIC_PREFIX = "k8s_llm_monitor"
ENV_PREFIXES = ("K8SLLM_", "OPENAI_")


# ---------------------------------------------------------------------------
# path normalization
# ---------------------------------------------------------------------------

def _norm_route(path: str) -> list[str]:
    """Normalize a documented/registered path; returns one or more
    normalized forms (brace alternation expands).  Param segments become
    ``*``; a trailing ``*`` marks a prefix route."""
    path = path.split("?")[0].rstrip(".,;:)")
    m = re.search(r"\{([^{}]*,[^{}]*)\}", path)
    if m:
        out: list[str] = []
        for alt in m.group(1).split(","):
            out.extend(_norm_route(path[:m.start()] + alt.strip()
                                   + path[m.end():]))
        return out
    segs = []
    for seg in path.split("/"):
        if (seg.startswith("{") and seg.endswith("}")) or \
                (seg.startswith("<") and seg.endswith(">")):
            segs.append("*")
        else:
            segs.append(seg)
    norm = "/".join(segs)
    return [norm if norm == "/" else norm.rstrip("/")
            or "/"] if norm else []


def _route_matches(doc: str, registered: set[str]) -> bool:
    if doc in registered:
        return True
    for reg in registered:
        if reg.endswith("/*") and (
                doc.startswith(reg[:-1]) or doc == reg[:-2]):
            return True
        if doc.endswith("/*") and (
                reg.startswith(doc[:-1]) or reg == doc[:-2]):
            return True
    return False


# ---------------------------------------------------------------------------
# registered routes (AST extraction)
# ---------------------------------------------------------------------------

def extract_server_routes(src: str) -> dict[tuple[str, str], int]:
    """(method, normalized path) -> line, from the monitor server's
    ``_ROUTES`` dict and the ``startswith`` prefix routes in
    ``_dispatch``.  A prefix route's method comes from its inline
    ``if method != "X": ...405...`` guard; GET when unguarded."""
    tree = ast.parse(src)
    out: dict[tuple[str, str], int] = {}
    for node in ast.walk(tree):
        is_routes = False
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Dict):
            is_routes = "_ROUTES" in {getattr(t, "id", "")
                                      for t in node.targets}
        elif isinstance(node, ast.AnnAssign) and isinstance(
                node.value, ast.Dict):
            is_routes = getattr(node.target, "id", "") == "_ROUTES"
        if is_routes:
            for key in node.value.keys:
                if isinstance(key, ast.Tuple) and len(key.elts) == 2 \
                        and all(isinstance(e, ast.Constant)
                                for e in key.elts):
                    method, path = (e.value for e in key.elts)
                    for norm in _norm_route(str(path)):
                        out[(str(method), norm)] = key.lineno
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == "_dispatch":
            for sub in ast.walk(node):
                if not (isinstance(sub, ast.If)
                        and isinstance(sub.test, ast.Call)
                        and isinstance(sub.test.func, ast.Attribute)
                        and sub.test.func.attr == "startswith"
                        and sub.test.args
                        and isinstance(sub.test.args[0], ast.Constant)
                        and str(sub.test.args[0].value).startswith("/")):
                    continue
                prefix = str(sub.test.args[0].value).rstrip("/")
                method = "GET"
                for guard in sub.body:
                    if isinstance(guard, ast.If) \
                            and isinstance(guard.test, ast.Compare) \
                            and isinstance(guard.test.left, ast.Name) \
                            and guard.test.left.id == "method" \
                            and len(guard.test.ops) == 1 \
                            and isinstance(guard.test.ops[0], ast.NotEq) \
                            and isinstance(guard.test.comparators[0],
                                           ast.Constant):
                        method = str(guard.test.comparators[0].value)
                        break
                out[(method, f"{prefix}/*")] = sub.lineno
    return out


def extract_agent_routes(src: str) -> dict[tuple[str, str], int]:
    """(method, normalized path) -> line for the uav-agent: the route
    dict in ``do_GET`` plus each ``command == "x"`` branch under the
    ``/api/v1/command/`` POST prefix."""
    tree = ast.parse(src)
    out: dict[tuple[str, str], int] = {}
    post_prefix = ""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name == "do_GET":
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Dict) and sub.keys and all(
                            isinstance(k, ast.Constant)
                            and str(k.value).startswith("/")
                            for k in sub.keys):
                        for k in sub.keys:
                            for norm in _norm_route(str(k.value)):
                                out[("GET", norm)] = k.lineno
            elif node.name == "do_POST":
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call) \
                            and isinstance(sub.func, ast.Attribute) \
                            and sub.func.attr == "startswith" \
                            and sub.args \
                            and isinstance(sub.args[0], ast.Constant):
                        post_prefix = str(sub.args[0].value).rstrip("/")
                        out[("POST", f"{post_prefix}/*")] = sub.lineno
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Compare) \
                            and isinstance(sub.left, ast.Name) \
                            and sub.left.id == "command" \
                            and len(sub.comparators) == 1 \
                            and isinstance(sub.comparators[0], ast.Constant) \
                            and post_prefix:
                        cmd = str(sub.comparators[0].value)
                        out[("POST", f"{post_prefix}/{cmd}")] = sub.lineno
    return out


# ---------------------------------------------------------------------------
# documented routes
# ---------------------------------------------------------------------------

_METHOD_PATH_RE = re.compile(
    r"\b(GET|POST|PUT|DELETE|PATCH)\s+(:\d+)?"
    r"(/[A-Za-z0-9_\-./{}<>,]*)")
_AGENT_PATH_RE = re.compile(
    r"(?:localhost)?:9090(/[A-Za-z0-9_\-./{}<>,]*)")
_BARE_PATH_RE = re.compile(
    r"`((?:GET|POST|PUT|DELETE|PATCH)?\s*/(?:api/v1|health|readyz|metrics"
    r"|debug)[A-Za-z0-9_\-./{}<>,]*)`")


@dataclasses.dataclass(frozen=True)
class DocRoute:
    server: str          # "monitor" | "agent"
    method: str | None   # None: bare path mention, method unknown
    path: str            # normalized
    file: str
    line: int


def extract_doc_routes(doc_texts: dict[str, str]) -> list[DocRoute]:
    out: list[DocRoute] = []
    seen: set[tuple[str, str | None, str]] = set()

    def add(server: str, method: str | None, raw: str,
            file: str, line: int) -> None:
        for norm in _norm_route(raw):
            if len(norm) < 2 or norm in ("/api", "/api/v1"):
                continue  # namespace mentions, not routes
            if "." in norm.rsplit("/", 1)[-1]:
                continue  # static asset (served by h_static catch-all)
            key = (server, method, norm)
            if key not in seen:
                seen.add(key)
                out.append(DocRoute(server, method, norm, file, line))

    for file, text in doc_texts.items():
        for lineno, linetext in enumerate(text.splitlines(), start=1):
            for m in _METHOD_PATH_RE.finditer(linetext):
                server = "agent" if m.group(2) == ":9090" else "monitor"
                add(server, m.group(1), m.group(3), file, lineno)
            for m in _AGENT_PATH_RE.finditer(linetext):
                before = linetext[:m.start()]
                xm = re.search(r"-X\s+(POST|PUT|DELETE|PATCH)\s*$|"
                               r"\b(GET|POST|PUT|DELETE|PATCH)\s+$", before)
                method = (xm.group(1) or xm.group(2)) if xm else "GET"
                add("agent", method, m.group(1), file, lineno)
            for m in _BARE_PATH_RE.finditer(linetext):
                token = m.group(1)
                vm = re.match(r"(GET|POST|PUT|DELETE|PATCH)\s+(/.*)", token)
                if vm:
                    add("monitor", vm.group(1), vm.group(2), file, lineno)
                else:
                    add("monitor", None, token, file, lineno)
    return out


def check_routes(server_src: str, agent_src: str,
                 doc_texts: dict[str, str],
                 server_path: str = "k8s_llm_monitor_tpu/monitor/server.py",
                 agent_path: str = "k8s_llm_monitor_tpu/monitor/agent.py"
                 ) -> list[Finding]:
    registered = {
        "monitor": (extract_server_routes(server_src), server_path),
        "agent": (extract_agent_routes(agent_src), agent_path),
    }
    doc_routes = extract_doc_routes(doc_texts)
    findings: list[Finding] = []
    # direction 1: documented but unregistered
    for dr in doc_routes:
        routes, _ = registered[dr.server]
        paths_any = {p for (_, p) in routes}
        if dr.method is None:
            ok = _route_matches(dr.path, paths_any)
        else:
            paths_m = {p for (mth, p) in routes if mth == dr.method}
            ok = _route_matches(dr.path, paths_m)
        if not ok:
            where = f"{dr.method} " if dr.method else ""
            findings.append(Finding(
                path=dr.file, line=dr.line, col=0, rule="route-contract",
                message=(f"documented route '{where}{dr.path}' "
                         f"({dr.server} server) is not registered — the "
                         f"reference's ghost-endpoint bug; register it or "
                         f"fix the doc")))
    # direction 2: registered but undocumented (path-level, method-lenient)
    doc_paths = {(dr.server, dr.path) for dr in doc_routes}
    for server, (routes, src_path) in registered.items():
        doc_for_server = {p for (s, p) in doc_paths if s == server}
        for (method, path), lineno in sorted(routes.items()):
            if not _route_matches(path, doc_for_server):
                findings.append(Finding(
                    path=src_path, line=lineno, col=0,
                    rule="route-contract",
                    message=(f"registered route '{method} {path}' "
                             f"({server} server) is not documented in "
                             f"README.md or docs/")))
    return findings


# ---------------------------------------------------------------------------
# metrics contract
# ---------------------------------------------------------------------------

def _collapse_family(name: str) -> str:
    for sfx in ("_bucket", "_sum", "_count"):
        if name.endswith(sfx):
            return name[: -len(sfx)]
    return name


def extract_exporter_metrics(src: str) -> dict[str, int]:
    """family name -> first-emission line, from ``w.metric``/
    ``w.histogram`` calls (literal or via a local tuple table of
    ``(name, help, hist)`` rows) and manual f-string sample lines."""
    tree = ast.parse(src)
    out: dict[str, int] = {}

    def note(name: str, line: int) -> None:
        if name and name not in out:
            out[name] = line

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) \
                or not isinstance(node.func, ast.Attribute):
            continue
        if node.func.attr in ("metric", "histogram") and node.args:
            first = node.args[0]
            if isinstance(first, ast.Constant) and isinstance(
                    first.value, str):
                note(first.value, node.lineno)  # already a family name
        elif node.func.attr == "append" and node.args and isinstance(
                node.args[0], ast.JoinedStr):
            # w.lines.append(f"{_PREFIX}_name_suffix ...") — a sample
            # line, so collapse _sum/_count/_bucket to the family
            parts = node.args[0].values
            if len(parts) >= 2 and isinstance(parts[0], ast.FormattedValue) \
                    and getattr(parts[0].value, "id", "") == "_PREFIX" \
                    and isinstance(parts[1], ast.Constant):
                text = str(parts[1].value)
                m = re.match(r"_([a-zA-Z0-9_]+)", text)
                if m:
                    note(_collapse_family(m.group(1)), node.lineno)
    # local tuple tables iterated into w.histogram(name, ...)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(
                node.value, (ast.Tuple, ast.List)):
            for elt in node.value.elts:
                if isinstance(elt, (ast.Tuple, ast.List)) and elt.elts \
                        and isinstance(elt.elts[0], ast.Constant) \
                        and isinstance(elt.elts[0].value, str) \
                        and re.fullmatch(r"[a-z][a-z0-9_]+",
                                         elt.elts[0].value):
                    note(elt.elts[0].value, elt.lineno)
    return out


_INVENTORY_ROW_RE = re.compile(
    rf"^\|\s*`?{METRIC_PREFIX}_([a-zA-Z0-9_]+)`?\s*\|")
_METRIC_MENTION_RE = re.compile(rf"\b{METRIC_PREFIX}_([a-zA-Z0-9_]+)")


def extract_doc_metric_inventory(obs_text: str) -> dict[str, int]:
    """Rows of the machine-parseable inventory table in
    docs/observability.md: metric family -> line."""
    out: dict[str, int] = {}
    for lineno, line in enumerate(obs_text.splitlines(), start=1):
        m = _INVENTORY_ROW_RE.match(line.strip())
        if m:
            out.setdefault(m.group(1), lineno)
    return out


def extract_bench_keys(src: str) -> tuple[set[str], set[str]]:
    """(exact keys, f-string prefix wildcards) emitted by bench.py:
    literal dict keys and literal subscript stores."""
    tree = ast.parse(src)
    exact: set[str] = set()
    prefixes: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Dict):
            for k in node.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    exact.add(k.value)
        elif isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, ast.Store):
            sl = node.slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                exact.add(sl.value)
            elif isinstance(sl, ast.JoinedStr) and sl.values and isinstance(
                    sl.values[0], ast.Constant):
                prefixes.add(str(sl.values[0].value))
    return exact, prefixes


def _bench_family(token: str) -> str:
    return "_".join(token.split("_")[:2])


_DOC_TOKEN_RE = re.compile(r"`([a-z][a-z0-9]*(?:_[a-z0-9*]+)+)\*?`|"
                           r"\b([a-z][a-z0-9]*(?:_[a-z0-9]+)+_\*)")


def check_metrics(exporter_src: str, obs_text: str, bench_src: str,
                  doc_texts: dict[str, str],
                  exporter_path: str =
                  "k8s_llm_monitor_tpu/monitor/exporter.py",
                  obs_path: str = "docs/observability.md") -> list[Finding]:
    emitted = extract_exporter_metrics(exporter_src)
    inventory = extract_doc_metric_inventory(obs_text)
    findings: list[Finding] = []
    # exporter -> inventory
    for fam, line in sorted(emitted.items()):
        if fam not in inventory:
            findings.append(Finding(
                path=exporter_path, line=line, col=0,
                rule="metrics-contract",
                message=(f"exporter emits '{METRIC_PREFIX}_{fam}' but the "
                         f"inventory table in {obs_path} does not list "
                         f"it")))
    # inventory -> exporter
    for fam, line in sorted(inventory.items()):
        if fam not in emitted:
            findings.append(Finding(
                path=obs_path, line=line, col=0, rule="metrics-contract",
                message=(f"inventory lists '{METRIC_PREFIX}_{fam}' but "
                         f"the exporter never emits it")))
    # every prefixed mention anywhere in the docs must be a real family
    for file, text in doc_texts.items():
        for lineno, line in enumerate(text.splitlines(), start=1):
            for m in _METRIC_MENTION_RE.finditer(line):
                tok = m.group(1)
                if tok == "tpu" or tok.startswith(("tpu_", "tpu.")):
                    continue  # the package is named k8s_llm_monitor_tpu
                fam = _collapse_family(tok).rstrip("_")
                if tok.rstrip("_") not in emitted and fam not in emitted:
                    findings.append(Finding(
                        path=file, line=lineno, col=0,
                        rule="metrics-contract",
                        message=(f"doc mentions metric "
                                 f"'{METRIC_PREFIX}_{m.group(1)}' which "
                                 f"the exporter never emits")))
    # bench-JSON keys cited in README/Makefile
    exact, prefixes = extract_bench_keys(bench_src)
    families = ({_bench_family(k) for k in exact}
                | {_bench_family(p) for p in prefixes})
    for file, text in doc_texts.items():
        if not (file.endswith("README.md") or file.endswith("Makefile")):
            continue
        for lineno, line in enumerate(text.splitlines(), start=1):
            for m in _DOC_TOKEN_RE.finditer(line):
                token = (m.group(1) or m.group(2)).rstrip("*").rstrip("_")
                wildcard = (m.group(0).rstrip("`").endswith("*"))
                if _bench_family(token) not in families:
                    continue  # not a bench-key claim
                if not wildcard and token.count("_") < 2:
                    continue  # 2-segment tokens (slo_class) are too
                    # generic to be a bench-key claim
                if _collapse_family(token) in emitted:
                    continue  # exporter metric name, not a bench key
                if wildcard:
                    ok = any(k.startswith(token) for k in exact) or \
                        any(p.startswith(token) or token.startswith(p)
                            for p in prefixes)
                else:
                    ok = token in exact or \
                        any(token.startswith(p) for p in prefixes)
                if not ok:
                    findings.append(Finding(
                        path=file, line=lineno, col=0,
                        rule="metrics-contract",
                        message=(f"doc cites bench key '{token}' which "
                                 f"bench.py never emits")))
    return findings


# ---------------------------------------------------------------------------
# env contract
# ---------------------------------------------------------------------------

def _module_str_constants(tree: ast.Module) -> dict[str, str]:
    out: dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                       ast.Constant) \
                and isinstance(node.value.value, str):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = node.value.value
    return out


def extract_env_reads(py_sources: dict[str, str]
                      ) -> dict[str, list[tuple[str, int]]]:
    """Literal project-prefixed env reads across the package:
    key -> [(file, line)].  Resolves module-level string constants used
    as the key (``os.environ.get(ENV_FLAG)``)."""
    out: dict[str, list[tuple[str, int]]] = {}

    def note(key: str, file: str, line: int) -> None:
        if any(key.startswith(p) for p in ENV_PREFIXES):
            out.setdefault(key, []).append((file, line))

    for file, src in py_sources.items():
        try:
            tree = ast.parse(src)
        except SyntaxError:
            continue
        consts = _module_str_constants(tree)

        def resolve(node: ast.AST) -> str:
            if isinstance(node, ast.Constant) and isinstance(node.value,
                                                             str):
                return node.value
            if isinstance(node, ast.Name):
                return consts.get(node.id, "")
            return ""

        for node in ast.walk(tree):
            from .astlint import dotted_name
            if isinstance(node, ast.Call):
                dn = dotted_name(node.func)
                if dn in ("os.environ.get", "os.getenv",
                          "os.environ.setdefault", "os.environ.pop") \
                        and node.args:
                    key = resolve(node.args[0])
                    if key:
                        note(key, file, node.lineno)
            elif isinstance(node, ast.Subscript) and dotted_name(
                    node.value) == "os.environ":
                key = resolve(node.slice)
                if key:
                    note(key, file, node.lineno)
            elif isinstance(node, ast.Compare) and len(
                    node.comparators) == 1 and dotted_name(
                    node.comparators[0]) == "os.environ":
                key = resolve(node.left)
                if key:
                    note(key, file, node.lineno)
    return out


def extract_env_registry(config_src: str) -> dict[str, tuple[str, int]]:
    """``ENV_KEYS`` dict literal in monitor/config.py:
    key -> (target, line)."""
    tree = ast.parse(config_src)
    out: dict[str, tuple[str, int]] = {}
    for node in ast.walk(tree):
        value = None
        if isinstance(node, ast.Assign):
            names = {getattr(t, "id", "") for t in node.targets}
            value = node.value if "ENV_KEYS" in names else None
        elif isinstance(node, ast.AnnAssign):
            if getattr(node.target, "id", "") == "ENV_KEYS":
                value = node.value
        if isinstance(value, ast.Dict):
            for k, v in zip(value.keys, value.values):
                if isinstance(k, ast.Constant) and isinstance(
                        v, ast.Constant):
                    out[str(k.value)] = (str(v.value), k.lineno)
    return out


def extract_dataclass_fields(py_sources: dict[str, str]) -> set[str]:
    """All ``Class.field`` pairs from annotated class bodies across the
    package (lint-grade: any annotated class attribute counts)."""
    out: set[str] = set()
    for src in py_sources.values():
        try:
            tree = ast.parse(src)
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and isinstance(
                        stmt.target, ast.Name):
                    out.add(f"{node.name}.{stmt.target.id}")
    return out


def derived_env_keys(config_src: str) -> set[str]:
    """Env keys ``_apply_env`` derives from the config dataclass tree:
    dotted path ``fleet.role`` -> ``FLEET_ROLE``, rooted at ``Config``."""
    tree = ast.parse(config_src)
    classes: dict[str, list[tuple[str, str]]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            fields = []
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and isinstance(
                        stmt.target, ast.Name):
                    ann = stmt.annotation
                    ann_name = ann.value if isinstance(
                        ann, ast.Constant) else getattr(ann, "id", "")
                    fields.append((stmt.target.id, str(ann_name)))
            classes[node.name] = fields
    out: set[str] = set()

    def walk(cls: str, prefix: str, depth: int = 0) -> None:
        if depth > 6:
            return
        for fname, ann in classes.get(cls, []):
            if ann in classes:
                walk(ann, prefix + fname + "_", depth + 1)
            else:
                out.add((prefix + fname).upper())

    walk("Config", "")
    return out


_ENV_MENTION_RE = re.compile(r"\b(K8SLLM_[A-Z0-9_]+|OPENAI_[A-Z0-9_]+)\b")


def check_env(py_sources: dict[str, str], config_src: str,
              doc_texts: dict[str, str],
              config_path: str = "k8s_llm_monitor_tpu/monitor/config.py"
              ) -> list[Finding]:
    reads = extract_env_reads(py_sources)
    registry = extract_env_registry(config_src)
    fields = extract_dataclass_fields(py_sources)
    derived = derived_env_keys(config_src)
    doc_mentions: dict[str, tuple[str, int]] = {}
    for file, text in doc_texts.items():
        for lineno, line in enumerate(text.splitlines(), start=1):
            for m in _ENV_MENTION_RE.finditer(line):
                doc_mentions.setdefault(m.group(1), (file, lineno))
    findings: list[Finding] = []
    # 1. every read key is registered
    for key, sites in sorted(reads.items()):
        if key not in registry:
            file, line = sites[0]
            findings.append(Finding(
                path=file, line=line, col=0, rule="env-contract",
                message=(f"env read of '{key}' is not declared in "
                         f"ENV_KEYS ({config_path}); register it with "
                         f"its config field or runtime owner")))
    for key, (target, line) in sorted(registry.items()):
        # 2. registry target is a real config field or a runtime owner
        if target.startswith("runtime:"):
            owner = target.split(":", 1)[1]
            owner_files = [f for f in py_sources
                           if f.replace("\\", "/").endswith(owner)]
            if not owner_files or not any(
                    f in {s[0] for s in reads.get(key, [])}
                    for f in owner_files):
                findings.append(Finding(
                    path=config_path, line=line, col=0,
                    rule="env-contract",
                    message=(f"ENV_KEYS declares '{key}' as a runtime "
                             f"toggle owned by {owner}, but that module "
                             f"never reads it")))
        elif target not in fields:
            findings.append(Finding(
                path=config_path, line=line, col=0, rule="env-contract",
                message=(f"ENV_KEYS maps '{key}' to '{target}' which is "
                         f"not a dataclass field anywhere in the "
                         f"package")))
        # 3. every registered key is actually read somewhere
        if key not in reads:
            findings.append(Finding(
                path=config_path, line=line, col=0, rule="env-contract",
                message=(f"ENV_KEYS declares '{key}' but no module reads "
                         f"it — dead configuration surface")))
        # 4. every registered key has a doc mention
        if key not in doc_mentions:
            findings.append(Finding(
                path=config_path, line=line, col=0, rule="env-contract",
                message=(f"env key '{key}' is undocumented — mention it "
                         f"in README.md or docs/")))
    # 5. every doc-mentioned project key is registered or derivable
    for key, (file, line) in sorted(doc_mentions.items()):
        if key in registry or key in derived:
            continue
        findings.append(Finding(
            path=file, line=line, col=0, rule="env-contract",
            message=(f"doc mentions env key '{key}' which is neither in "
                     f"ENV_KEYS nor derivable from the config tree")))
    return findings


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def _doc_texts(repo_root: Path) -> dict[str, str]:
    out: dict[str, str] = {}
    for p in [repo_root / "README.md", repo_root / "Makefile",
              *sorted((repo_root / "docs").glob("*.md"))]:
        if p.is_file():
            out[str(p.relative_to(repo_root))] = p.read_text(
                encoding="utf-8")
    return out


def run_contracts(repo_root: Path,
                  rules: Iterable[str] | None = None) -> list[Finding]:
    repo_root = Path(repo_root)
    wanted = set(rules) if rules is not None else set(CONTRACT_RULE_NAMES)
    pkg = repo_root / PACKAGE
    docs = _doc_texts(repo_root)

    def rel(p: Path) -> str:
        return str(p.relative_to(repo_root))

    py_sources = {rel(p): p.read_text(encoding="utf-8")
                  for p in sorted(pkg.rglob("*.py"))
                  if "__pycache__" not in p.parts}
    findings: list[Finding] = []
    if "route-contract" in wanted:
        findings.extend(check_routes(
            py_sources[f"{PACKAGE}/monitor/server.py"],
            py_sources[f"{PACKAGE}/monitor/agent.py"],
            {f: t for f, t in docs.items() if f.endswith(".md")}))
    if "metrics-contract" in wanted:
        obs = docs.get("docs/observability.md", "")
        bench = (repo_root / "bench.py")
        findings.extend(check_metrics(
            py_sources[f"{PACKAGE}/monitor/exporter.py"], obs,
            bench.read_text(encoding="utf-8") if bench.is_file() else "",
            docs))
    if "env-contract" in wanted:
        findings.extend(check_env(
            py_sources, py_sources[f"{PACKAGE}/monitor/config.py"],
            {f: t for f, t in docs.items() if f.endswith(".md")}))
    # suppressions on the anchoring line (policy: reconcile, don't
    # suppress — but the mechanism stays uniform across graftcheck)
    out: list[Finding] = []
    cache: dict[str, tuple[dict[int, set[str]], set[str]]] = {}
    for f in findings:
        if f.path not in cache:
            src = py_sources.get(f.path)
            if src is None:
                src = docs.get(f.path, "")
            cache[f.path] = _suppressions(src)
        per_line, per_file = cache[f.path]
        if f.rule in per_file or "all" in per_file:
            continue
        line_rules = per_line.get(f.line, set())
        if f.rule in line_rules or "all" in line_rules:
            continue
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def render(findings: list[Finding]) -> str:
    if not findings:
        return "graftcheck contracts: clean"
    lines = [f.human() for f in findings]
    lines.append(f"graftcheck contracts: {len(findings)} finding(s)")
    return "\n".join(lines)
