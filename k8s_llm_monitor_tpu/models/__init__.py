"""Model family: Llama-3/Qwen2 decoder LMs + BGE-style embedding encoder."""

from k8s_llm_monitor_tpu.models.config import (
    ENCODER_PRESETS,
    PRESETS,
    EncoderConfig,
    ModelConfig,
)
from k8s_llm_monitor_tpu.models import encoder, llama

__all__ = ["ModelConfig", "EncoderConfig", "PRESETS", "ENCODER_PRESETS",
           "encoder", "llama"]
