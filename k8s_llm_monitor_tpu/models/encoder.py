"""BERT-family bidirectional text encoder (BGE-large), pure-functional JAX.

The embedding backbone for the anomaly detector (analysis/anomaly.py,
BASELINE.md config #3): cluster events and log lines are embedded and
outliers flagged by cosine distance.  The reference has no embedding or
anomaly model at all — its anomaly surface is fixed thresholds
(reference internal/metrics/manager.go:546-564); this is part of the
north-star Analysis Engine obligation.

Architecture follows the BERT post-LayerNorm transformer exactly so HF
``bge-large-en``/``bert-base`` safetensors load verbatim:
  embeddings  = LN(word + position + token_type)
  layer       = LN(x + attn(x)); LN(x + ffn(gelu))
  pooling     = CLS token (BGE convention) or masked mean, L2-normalized.

TPU notes: the whole forward is one jittable function of static shapes —
pad batches to fixed (B, S) buckets; masked positions contribute nothing
(attention bias -inf, pooling mask).  bf16-safe; LayerNorms run in f32.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from k8s_llm_monitor_tpu.models.config import EncoderConfig

Params = dict[str, Any]

NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)


def init_params(rng: jax.Array, cfg: EncoderConfig) -> Params:
    dtype = jnp.dtype(cfg.dtype)
    H, I = cfg.hidden_size, cfg.intermediate_size

    def dense(key, in_f, out_f):
        w = jax.random.normal(key, (in_f, out_f), jnp.float32) * 0.02
        return {"kernel": w.astype(dtype), "bias": jnp.zeros((out_f,), dtype)}

    def ln():
        return {"scale": jnp.ones((H,), dtype), "bias": jnp.zeros((H,), dtype)}

    keys = jax.random.split(rng, 4 + cfg.num_layers)
    layers = []
    for i in range(cfg.num_layers):
        lk = jax.random.split(keys[4 + i], 6)
        layers.append({
            "q": dense(lk[0], H, H),
            "k": dense(lk[1], H, H),
            "v": dense(lk[2], H, H),
            "attn_out": dense(lk[3], H, H),
            "attn_ln": ln(),
            "ffn_in": dense(lk[4], H, I),
            "ffn_out": dense(lk[5], I, H),
            "ffn_ln": ln(),
        })
    return {
        "word_embed": (jax.random.normal(
            keys[0], (cfg.vocab_size, H), jnp.float32) * 0.02).astype(dtype),
        "pos_embed": (jax.random.normal(
            keys[1], (cfg.max_position_embeddings, H),
            jnp.float32) * 0.02).astype(dtype),
        "type_embed": (jax.random.normal(
            keys[2], (cfg.type_vocab_size, H), jnp.float32) * 0.02
        ).astype(dtype),
        "embed_ln": ln(),
        "layers": layers,
    }


def _layer_norm(x: jnp.ndarray, p: Params, eps: float) -> jnp.ndarray:
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    out = (xf - mean) * jax.lax.rsqrt(var + eps)
    return (out * p["scale"].astype(jnp.float32)
            + p["bias"].astype(jnp.float32)).astype(x.dtype)


def _dense(p: Params, x: jnp.ndarray) -> jnp.ndarray:
    return x @ p["kernel"] + p["bias"]


def forward(
    params: Params,
    cfg: EncoderConfig,
    tokens: jnp.ndarray,
    mask: jnp.ndarray,
) -> jnp.ndarray:
    """Token-level hidden states.

    Args:
      tokens: [B, S] int32 (right-padded).
      mask: [B, S] — 1 for real tokens, 0 for padding.

    Returns:
      [B, S, H] hidden states (padding positions are garbage; mask them).
    """
    B, S = tokens.shape
    H, nH, D = cfg.hidden_size, cfg.num_heads, cfg.head_dim

    pos = jnp.arange(S, dtype=jnp.int32)
    x = (params["word_embed"][tokens]
         + params["pos_embed"][pos][None, :, :]
         + params["type_embed"][jnp.zeros((B, S), jnp.int32)])
    x = _layer_norm(x, params["embed_ln"], cfg.layer_norm_eps)

    # additive attention bias: padding keys masked out for every query
    bias = jnp.where(mask[:, None, None, :] > 0, 0.0, NEG_INF)  # [B,1,1,S]
    scale = 1.0 / (D ** 0.5)

    for layer in params["layers"]:
        q = _dense(layer["q"], x).reshape(B, S, nH, D)
        k = _dense(layer["k"], x).reshape(B, S, nH, D)
        v = _dense(layer["v"], x).reshape(B, S, nH, D)
        logits = jnp.einsum("bshd,bthd->bhst",
                            q.astype(jnp.float32), k.astype(jnp.float32))
        logits = logits * scale + bias
        probs = jax.nn.softmax(logits, axis=-1)
        attn = jnp.einsum("bhst,bthd->bshd", probs, v.astype(jnp.float32))
        attn = attn.astype(x.dtype).reshape(B, S, H)
        x = _layer_norm(x + _dense(layer["attn_out"], attn),
                        layer["attn_ln"], cfg.layer_norm_eps)
        h = jax.nn.gelu(_dense(layer["ffn_in"], x), approximate=False)
        x = _layer_norm(x + _dense(layer["ffn_out"], h),
                        layer["ffn_ln"], cfg.layer_norm_eps)
    return x


def encode(
    params: Params,
    cfg: EncoderConfig,
    tokens: jnp.ndarray,
    mask: jnp.ndarray,
    *,
    pooling: str = "cls",
) -> jnp.ndarray:
    """Sentence embeddings: pooled + L2-normalized, [B, H] float32.

    ``pooling``: "cls" (BGE convention — first token) or "mean" (masked).
    """
    hidden = forward(params, cfg, tokens, mask).astype(jnp.float32)
    if pooling == "cls":
        pooled = hidden[:, 0, :]
    elif pooling == "mean":
        m = mask.astype(jnp.float32)[:, :, None]
        pooled = jnp.sum(hidden * m, axis=1) / jnp.maximum(
            jnp.sum(m, axis=1), 1.0)
    else:
        raise ValueError(f"unknown pooling {pooling!r}")
    norm = jnp.linalg.norm(pooled, axis=-1, keepdims=True)
    return pooled / jnp.maximum(norm, 1e-9)


# ---------------------------------------------------------------------------
# HF checkpoint loading (BertModel layout: bge-large-en, bert-base, ...)
# ---------------------------------------------------------------------------

_HF_LAYER_MAP = {
    "attention.self.query": "q",
    "attention.self.key": "k",
    "attention.self.value": "v",
    "attention.output.dense": "attn_out",
    "intermediate.dense": "ffn_in",
    "output.dense": "ffn_out",
}


def params_from_hf_state(state: dict[str, Any], cfg: EncoderConfig) -> Params:
    """Convert an HF BertModel state dict (numpy arrays) to our tree.

    Accepts both bare (``embeddings.word_embeddings.weight``) and prefixed
    (``bert.embeddings...``) key styles.  Linear weights are transposed to
    the ``[in, out]`` layout the forward uses.
    """
    import numpy as np

    def get(key):
        for k in (key, "bert." + key):
            if k in state:
                return np.asarray(state[k])
        raise KeyError(key)

    dtype = jnp.dtype(cfg.dtype)

    def dense(prefix):
        return {
            "kernel": jnp.asarray(get(prefix + ".weight").T, dtype),
            "bias": jnp.asarray(get(prefix + ".bias"), dtype),
        }

    def ln(prefix):
        return {
            "scale": jnp.asarray(get(prefix + ".weight"), dtype),
            "bias": jnp.asarray(get(prefix + ".bias"), dtype),
        }

    layers = []
    for i in range(cfg.num_layers):
        base = f"encoder.layer.{i}."
        layer = {ours: dense(base + hf) for hf, ours in _HF_LAYER_MAP.items()}
        layer["attn_ln"] = ln(base + "attention.output.LayerNorm")
        layer["ffn_ln"] = ln(base + "output.LayerNorm")
        layers.append(layer)
    return {
        "word_embed": jnp.asarray(
            get("embeddings.word_embeddings.weight"), dtype),
        "pos_embed": jnp.asarray(
            get("embeddings.position_embeddings.weight"), dtype),
        "type_embed": jnp.asarray(
            get("embeddings.token_type_embeddings.weight"), dtype),
        "embed_ln": ln("embeddings.LayerNorm"),
        "layers": layers,
    }


def load_hf_encoder(path: str) -> tuple[EncoderConfig, Params]:
    """Load a BertModel-family checkpoint directory (config.json +
    safetensors) into (EncoderConfig, params)."""
    import json
    import os
    import pathlib

    from k8s_llm_monitor_tpu.utils.checkpoint import _SafetensorsDict

    with open(os.path.join(path, "config.json"), encoding="utf-8") as fh:
        hf = json.load(fh)
    cfg = EncoderConfig(
        name=hf.get("_name_or_path", "hf-encoder"),
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        intermediate_size=hf["intermediate_size"],
        num_layers=hf["num_hidden_layers"],
        num_heads=hf["num_attention_heads"],
        max_position_embeddings=hf["max_position_embeddings"],
        type_vocab_size=hf.get("type_vocab_size", 2),
        layer_norm_eps=hf.get("layer_norm_eps", 1e-12),
    )
    state = _SafetensorsDict(pathlib.Path(path))
    return cfg, params_from_hf_state(state, cfg)
