"""Model configurations for the decoder LM family and embedding encoders.

The flagship serving targets come from BASELINE.md's benchmark matrix:
Llama-3-8B (v5e-1 / v5e-8), Llama-3-70B / Qwen2-72B (v5p-16), and a
BGE-large-class encoder for the anomaly detector's embedding path.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Architecture hyperparameters for a Llama/Qwen2-family decoder LM.

    The family covers:
      - Llama-3:  GQA, RoPE (high theta), SwiGLU MLP, RMSNorm, no biases.
      - Qwen2:    same skeleton + QKV projection biases.
    """

    name: str = "tiny"
    vocab_size: int = 256
    hidden_size: int = 64
    intermediate_size: int = 128
    num_layers: int = 2
    num_heads: int = 4
    num_kv_heads: int = 2
    head_dim: Optional[int] = None  # defaults to hidden_size // num_heads
    rope_theta: float = 500_000.0
    # HF-style rope_scaling dict (e.g. Llama-3.1's {"rope_type": "llama3",
    # "factor": 8.0, ...}); None = unscaled.  See ops/rope.py.
    rope_scaling: Optional[dict] = None
    rms_norm_eps: float = 1e-5
    max_seq_len: int = 8192
    qkv_bias: bool = False          # True for Qwen2
    tie_embeddings: bool = False
    dtype: str = "bfloat16"
    # KV cache dtype ('' = same as dtype).  "float8_e4m3fn" halves the KV
    # pool and the decode-attention DMA traffic; Q stays bf16 and the
    # kernel/softmax run f32, so logits track the bf16-KV model closely
    # (tested).  Opt-in: accuracy headroom is workload-dependent.
    kv_dtype: str = ""
    # W8A8: dynamically quantize activations (per-token symmetric int8) at
    # every linear so the matmul runs s8 x s8 on the MXU's int8 path —
    # above the bf16 matmul rate on v5e (measured ~1.4x end-to-end on
    # dense prefill shapes), i.e. faster prefill for
    # int8-quantized weights.  Requires kernel_q weights
    # (utils/quantize.py).  Attention, norms, and residuals stay bf16.
    act_quant: bool = False
    # Mixture-of-experts MLP (Mixtral family): > 0 replaces every layer's
    # SwiGLU with num_experts expert FFNs behind a top-k router (GShard
    # capacity dispatch, models/llama.py:_moe_mlp).  Expert weights carry a
    # leading [num_experts] axis sharded over the mesh's ``model`` axis —
    # expert parallelism rides the same axis tensor parallelism uses, and
    # XLA inserts the dispatch/combine all-to-alls from the shardings.
    num_experts: int = 0
    num_experts_per_tok: int = 2
    # Per-expert token capacity = ceil(tokens * top_k * capacity_factor /
    # num_experts); overflow tokens skip the MLP (residual passes through).
    capacity_factor: float = 1.25
    # --- Gemma-2 family knobs (defaults = Llama conventions) -----------
    # MLP activation: "silu" (SwiGLU) or "gelu_tanh" (Gemma GeGLU).
    mlp_activation: str = "silu"
    # Sandwich norms: extra RMSNorm on the attention and MLP OUTPUTS
    # (post_attn_norm / post_mlp_norm) before the residual add; the
    # existing post_norm plays Gemma's pre_feedforward role.
    sandwich_norms: bool = False
    # Gemma RMSNorm convention: stored weight is a zero-centered delta,
    # effective scale = 1 + w (ops/norms.py unit_offset).
    rmsnorm_unit_offset: bool = False
    # tanh soft caps (0 = off): attention logits and final lm logits.
    attn_logit_softcap: float = 0.0
    final_logit_softcap: float = 0.0
    # Query scale = query_pre_attn_scalar**-0.5 (None = head_dim**-0.5).
    query_pre_attn_scalar: Optional[float] = None
    # Multiply embeddings by sqrt(hidden_size) (Gemma).
    embed_scale: bool = False
    # Sliding-window attention: window size (0 = global) and the per-layer
    # pattern ("sliding_attention"/"full_attention" per layer; None = all
    # sliding when sliding_window > 0).
    sliding_window: int = 0
    layer_types: Optional[tuple] = None

    @property
    def attn_scale(self) -> Optional[float]:
        """Explicit query scale, or None for the default head_dim**-0.5."""
        if self.query_pre_attn_scalar is not None:
            return float(self.query_pre_attn_scalar) ** -0.5
        return None

    def layer_window(self, i: int) -> int:
        """Sliding-window size for layer ``i`` (0 = global attention)."""
        if self.sliding_window <= 0:
            return 0
        if self.layer_types is not None:
            return (self.sliding_window
                    if self.layer_types[i] == "sliding_attention" else 0)
        return self.sliding_window

    @property
    def has_attn_extras(self) -> bool:
        """True when attention needs non-Llama parameters threaded (forces
        the gather attention impls — ops/attention.py selection gates)."""
        return bool(self.attn_logit_softcap or self.sliding_window
                    or self.query_pre_attn_scalar is not None)

    @property
    def head_dim_(self) -> int:
        return self.head_dim if self.head_dim is not None else self.hidden_size // self.num_heads

    @property
    def q_per_kv(self) -> int:
        return self.num_heads // self.num_kv_heads


# ---------------------------------------------------------------------------
# Presets.  Shapes follow the public architecture cards for each model family.
# ---------------------------------------------------------------------------

TINY = ModelConfig(name="tiny")

TINY_QWEN = ModelConfig(name="tiny-qwen", qkv_bias=True)

# 8 experts so the expert axis divides TP-8 like the production MoE preset.
TINY_MOE = ModelConfig(name="tiny-moe", num_experts=8, num_experts_per_tok=2)

LLAMA3_8B = ModelConfig(
    name="llama3-8b",
    vocab_size=128_256,
    hidden_size=4096,
    intermediate_size=14_336,
    num_layers=32,
    num_heads=32,
    num_kv_heads=8,
    rope_theta=500_000.0,
    max_seq_len=8192,
)

LLAMA3_70B = ModelConfig(
    name="llama3-70b",
    vocab_size=128_256,
    hidden_size=8192,
    intermediate_size=28_672,
    num_layers=80,
    num_heads=64,
    num_kv_heads=8,
    rope_theta=500_000.0,
    max_seq_len=8192,
)

MIXTRAL_8X7B = ModelConfig(
    name="mixtral-8x7b",
    vocab_size=32_000,
    hidden_size=4096,
    intermediate_size=14_336,
    num_layers=32,
    num_heads=32,
    num_kv_heads=8,
    rope_theta=1_000_000.0,
    max_seq_len=32_768,
    num_experts=8,
    num_experts_per_tok=2,
)

def gemma2_layer_types(n_layers: int) -> tuple:
    """Gemma-2's attention pattern: alternating sliding/full, sliding
    first.  The ONE definition shared by the presets and the HF-config
    fallback (utils/checkpoint.py) so they cannot drift."""
    return tuple("sliding_attention" if i % 2 == 0 else "full_attention"
                 for i in range(n_layers))


def _gemma2(name: str, *, hidden: int, inter: int, layers: int, heads: int,
            kv: int, qpas: float) -> ModelConfig:
    return ModelConfig(
        name=name,
        vocab_size=256_000,
        hidden_size=hidden,
        intermediate_size=inter,
        num_layers=layers,
        num_heads=heads,
        num_kv_heads=kv,
        head_dim=256,
        rope_theta=10_000.0,
        rms_norm_eps=1e-6,
        max_seq_len=8192,
        tie_embeddings=True,
        mlp_activation="gelu_tanh",
        sandwich_norms=True,
        rmsnorm_unit_offset=True,
        attn_logit_softcap=50.0,
        final_logit_softcap=30.0,
        query_pre_attn_scalar=qpas,
        embed_scale=True,
        sliding_window=4096,
        layer_types=gemma2_layer_types(layers),
    )


GEMMA2_2B = _gemma2("gemma2-2b", hidden=2304, inter=9216, layers=26,
                    heads=8, kv=4, qpas=256.0)
GEMMA2_9B = _gemma2("gemma2-9b", hidden=3584, inter=14_336, layers=42,
                    heads=16, kv=8, qpas=256.0)

# Mistral-7B (v0.3+: no sliding window, full GQA) — same skeleton as
# Llama-3 with 32k vocab and theta 1e6; loads from HF safetensors via the
# same key map (utils/checkpoint.py).
MISTRAL_7B = ModelConfig(
    name="mistral-7b",
    vocab_size=32_768,
    hidden_size=4096,
    intermediate_size=14_336,
    num_layers=32,
    num_heads=32,
    num_kv_heads=8,
    rope_theta=1_000_000.0,
    max_seq_len=32_768,
)

QWEN2_7B = ModelConfig(
    name="qwen2-7b",
    vocab_size=152_064,
    hidden_size=3584,
    intermediate_size=18_944,
    num_layers=28,
    num_heads=28,
    num_kv_heads=4,
    rope_theta=1_000_000.0,
    max_seq_len=32_768,
    qkv_bias=True,
)

QWEN2_72B = ModelConfig(
    name="qwen2-72b",
    vocab_size=152_064,
    hidden_size=8192,
    intermediate_size=29_568,
    num_layers=80,
    num_heads=64,
    num_kv_heads=8,
    rope_theta=1_000_000.0,
    max_seq_len=32_768,
    qkv_bias=True,
)

# A ~1.1B config used for single-chip benchmarks when full 8B weights would not
# leave headroom for the KV cache on a 16 GB v5e chip with random-init weights.
LLAMA_1B = ModelConfig(
    name="llama-1b",
    vocab_size=128_256,
    hidden_size=2048,
    intermediate_size=8192,
    num_layers=16,
    num_heads=32,
    num_kv_heads=8,
    head_dim=64,
    rope_theta=500_000.0,
    max_seq_len=8192,
)

PRESETS = {
    c.name: c
    for c in [TINY, TINY_QWEN, TINY_MOE, LLAMA3_8B, LLAMA3_70B, MISTRAL_7B,
              MIXTRAL_8X7B, QWEN2_7B, QWEN2_72B, GEMMA2_2B, GEMMA2_9B,
              LLAMA_1B]
}


@dataclasses.dataclass(frozen=True)
class EncoderConfig:
    """BERT-family bidirectional encoder (BGE-large) for embeddings.

    Used by the anomaly detector (analysis/anomaly.py) to embed log lines and
    cluster events; BASELINE.md config #3.
    """

    name: str = "tiny-encoder"
    vocab_size: int = 512
    hidden_size: int = 64
    intermediate_size: int = 128
    num_layers: int = 2
    num_heads: int = 4
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    dtype: str = "float32"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


TINY_ENCODER = EncoderConfig()

BGE_LARGE = EncoderConfig(
    name="bge-large",
    vocab_size=30_522,
    hidden_size=1024,
    intermediate_size=4096,
    num_layers=24,
    num_heads=16,
    max_position_embeddings=512,
)

# bf16 variant for TPU serving: ~2x the matmul rate and half the weight
# traffic; pooling/normalization stay f32 (models/encoder.py), so cosine
# rankings track the f32 encoder closely.
BGE_LARGE_BF16 = dataclasses.replace(
    BGE_LARGE, name="bge-large-bf16", dtype="bfloat16")

ENCODER_PRESETS = {c.name: c for c in [TINY_ENCODER, BGE_LARGE,
                                       BGE_LARGE_BF16]}
