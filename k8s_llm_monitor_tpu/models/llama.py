"""Llama-3 / Qwen2-family decoder LM, pure-functional JAX.

Design notes (TPU-first, not a port):
  - Params are a plain pytree (nested dicts + per-layer list).  Linear kernels
    are stored ``[in_features, out_features]`` so the forward pass is a single
    ``x @ W`` that XLA tiles onto the MXU; HF checkpoints are transposed once
    at load time (utils/checkpoint.py).
  - Three entry points, all shape-static and jittable:
      * ``forward_full``  — dense causal forward (training / logit parity).
      * ``prefill``       — padded-batch prompt ingestion that scatters K/V
                            into a paged block cache and returns last-token
                            logits.
      * ``decode_step``   — one-token step over the paged cache.
  - The paged KV cache is a pytree of per-layer page arrays
    ``[num_blocks, block_size, kv_heads * head_dim]`` (fused lane layout —
    see ``KVPages``).  Block id 0 is reserved
    as the null block: masked/inactive lanes scatter their writes there, which
    keeps every write shape-static without corrupting live sequences
    (serving/kv_cache.py never allocates block 0).

Capability context: this model is the Analysis Engine backend the reference
only configured but never implemented (reference internal/config/config.go:
141-145 holds the entire LLM integration; README.md:89-95 documents the
/api/v1/query endpoint that cmd/server/main.go never registers).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp

from k8s_llm_monitor_tpu.models.config import ModelConfig
from k8s_llm_monitor_tpu.ops.attention import (
    causal_attention,
    gather_pages,
    paged_decode_attention,
    paged_decode_attention_quant,
)
from k8s_llm_monitor_tpu.ops.norms import rms_norm
from k8s_llm_monitor_tpu.ops.rope import apply_rope, rope_angles

Params = dict[str, Any]


class KVPages(NamedTuple):
    """Paged KV cache: per-layer lists of page arrays.

    k[i], v[i]: [num_blocks, block_size, kv_heads * head_dim]

    The kv-heads and head-dim axes are stored FUSED.  This is the Pallas
    decode kernel's native DMA layout (128-lane-aligned page rows); keeping
    the resident arrays in that layout means the per-step attention call
    consumes them directly.  Storing [..., KVH, D] instead costs a physical
    relayout copy of every page array on every decode step (~4.6 GB/step
    for 8B at 2200 blocks — measured as 64 materialized reshapes in the
    compiled HLO, and most of the decode step time).

    Mesh execution: the fused lane dim is kv-head-MAJOR (``reshape(KVH*D)``
    of ``[..., KVH, D]``), so sharding it ``model``-ways when tp divides
    KVH is exactly a per-chip contiguous slice of ``KVH/tp`` whole heads —
    ``SpecLayout.kv_pages`` (parallel/sharding.py) relies on this, and it
    is why head-sharded paged attention needs no resharding collective at
    the page boundary.  The page/block axes are NEVER sharded: block ids
    stay global (serving/kv_cache.py module docstring), every chip
    scatters/gathers with the same block table, and the host allocator
    stays mesh-agnostic.
    """

    k: list[jnp.ndarray]
    v: list[jnp.ndarray]
    # Quantized-KV tier (serving/kv_tier.py, docs/serving.md): per-layer
    # scale arrays [num_blocks, block_size, kv_heads] float32 — one
    # symmetric scale per (token, head).  Empty tuples (the default) mean
    # an unquantized pool: no extra pytree leaves, so every pre-existing
    # jitted program keeps its exact treedef and donation layout.
    k_scale: tuple | list = ()
    v_scale: tuple | list = ()

    @property
    def num_blocks(self) -> int:
        return self.k[0].shape[0]

    @property
    def block_size(self) -> int:
        return self.k[0].shape[1]

    @property
    def quantized(self) -> bool:
        return len(self.k_scale) > 0


def kv_quant_spec(kv_quant: str) -> tuple[Any, float]:
    """(storage dtype, qmax) for a KV quantization mode.

    ``int8`` is always available; ``fp8`` selects float8_e4m3fn when this
    jax build ships it and otherwise falls back to int8 (the engine warns).
    """
    if kv_quant == "fp8" and hasattr(jnp, "float8_e4m3fn"):
        return jnp.dtype(jnp.float8_e4m3fn), 448.0
    return jnp.dtype(jnp.int8), 127.0


def quantize_kv(x: jnp.ndarray, num_kv_heads: int, qdtype,
                qmax: float) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-(token, head) symmetric quantization of fused-lane KV rows.

    x [..., KVH*D] -> (x_q [..., KVH*D] qdtype, scale [..., KVH] float32).
    Mirrors ``_quant_act``'s amax/qmax idiom; int8 rounds-and-clips, fp8
    casts (saturating on TPU).
    """
    shp = x.shape
    D = shp[-1] // num_kv_heads
    xr = x.astype(jnp.float32).reshape(*shp[:-1], num_kv_heads, D)
    amax = jnp.max(jnp.abs(xr), axis=-1)
    scale = jnp.maximum(amax / qmax, 1e-8)
    xq = xr / scale[..., None]
    if jnp.dtype(qdtype) == jnp.int8:
        xq = jnp.clip(jnp.round(xq), -qmax, qmax)
    return xq.astype(qdtype).reshape(shp), scale


def dequantize_kv(x_q: jnp.ndarray, scale: jnp.ndarray,
                  dtype=jnp.float32) -> jnp.ndarray:
    """Inverse of ``quantize_kv``: x_q [..., KVH*D] + scale [..., KVH]
    -> float rows [..., KVH*D]."""
    shp = x_q.shape
    KVH = scale.shape[-1]
    xr = x_q.astype(jnp.float32).reshape(*shp[:-1], KVH, shp[-1] // KVH)
    return (xr * scale[..., None]).reshape(shp).astype(dtype)


def init_kv_pages(cfg: ModelConfig, num_blocks: int, block_size: int,
                  kv_quant: str = "") -> KVPages:
    """Allocate the paged KV pool.  ``kv_quant`` ("int8"/"fp8") selects the
    quantized tier: page arrays in the storage dtype plus per-(token, head)
    float32 scale arrays; "" keeps the historical unquantized layout."""
    shape = (num_blocks, block_size, cfg.num_kv_heads * cfg.head_dim_)
    if kv_quant:
        qdtype, _ = kv_quant_spec(kv_quant)
        sshape = (num_blocks, block_size, cfg.num_kv_heads)
        return KVPages(
            k=[jnp.zeros(shape, qdtype) for _ in range(cfg.num_layers)],
            v=[jnp.zeros(shape, qdtype) for _ in range(cfg.num_layers)],
            k_scale=[jnp.zeros(sshape, jnp.float32)
                     for _ in range(cfg.num_layers)],
            v_scale=[jnp.zeros(sshape, jnp.float32)
                     for _ in range(cfg.num_layers)],
        )
    dtype = jnp.dtype(cfg.kv_dtype or cfg.dtype)
    return KVPages(
        k=[jnp.zeros(shape, dtype) for _ in range(cfg.num_layers)],
        v=[jnp.zeros(shape, dtype) for _ in range(cfg.num_layers)],
    )


# ---------------------------------------------------------------------------
# Init
# ---------------------------------------------------------------------------


def init_params(rng: jax.Array, cfg: ModelConfig) -> Params:
    """Random-init parameters (truncated-normal-ish scaled normals)."""
    dtype = jnp.dtype(cfg.dtype)
    H, D = cfg.hidden_size, cfg.head_dim_
    nH, nKV, I = cfg.num_heads, cfg.num_kv_heads, cfg.intermediate_size

    def dense(key, in_f, out_f, bias):
        w = jax.random.normal(key, (in_f, out_f), jnp.float32) * (in_f ** -0.5)
        p = {"kernel": w.astype(dtype)}
        if bias:
            p["bias"] = jnp.zeros((out_f,), dtype)
        return p

    def expert_dense(key, in_f, out_f):
        # Stacked expert kernels [E, in, out]; leading axis shards over the
        # mesh's ``model`` axis (expert parallelism).
        w = jax.random.normal(
            key, (cfg.num_experts, in_f, out_f), jnp.float32) * (in_f ** -0.5)
        return {"kernel": w.astype(dtype)}

    # Gemma stores norm weights as zero-centered deltas (effective scale
    # 1 + w), so identity-init is zeros there, ones elsewhere.
    norm_init = jnp.zeros if cfg.rmsnorm_unit_offset else jnp.ones

    keys = jax.random.split(rng, 2 + cfg.num_layers)
    layers = []
    for i in range(cfg.num_layers):
        lk = jax.random.split(keys[2 + i], 8)
        layer = {
            "input_norm": norm_init((H,), dtype),
            "post_norm": norm_init((H,), dtype),
            "q": dense(lk[0], H, nH * D, cfg.qkv_bias),
            "k": dense(lk[1], H, nKV * D, cfg.qkv_bias),
            "v": dense(lk[2], H, nKV * D, cfg.qkv_bias),
            "o": dense(lk[3], nH * D, H, False),
        }
        if cfg.sandwich_norms:
            layer["post_attn_norm"] = norm_init((H,), dtype)
            layer["post_mlp_norm"] = norm_init((H,), dtype)
        if cfg.num_experts > 0:
            layer["router"] = dense(lk[7], H, cfg.num_experts, False)
            layer["gate_e"] = expert_dense(lk[4], H, I)
            layer["up_e"] = expert_dense(lk[5], H, I)
            layer["down_e"] = expert_dense(lk[6], I, H)
        else:
            layer["gate"] = dense(lk[4], H, I, False)
            layer["up"] = dense(lk[5], H, I, False)
            layer["down"] = dense(lk[6], I, H, False)
        layers.append(layer)
    params: Params = {
        "embed": {
            "weight": (
                jax.random.normal(keys[0], (cfg.vocab_size, H), jnp.float32) * 0.02
            ).astype(dtype)
        },
        "layers": layers,
        "final_norm": norm_init((H,), dtype),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = dense(keys[1], H, cfg.vocab_size, False)
    return params


# ---------------------------------------------------------------------------
# Building blocks
# ---------------------------------------------------------------------------


def _quant_act(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Dynamic per-token symmetric int8: (x_q int8, scale f32 [..., 1])."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True)
    scale = jnp.maximum(amax / 127.0, 1e-8)
    x_q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale),
                   -127, 127).astype(jnp.int8)
    return x_q, scale


def _linear(p: Params, x: jnp.ndarray, act_quant: bool = False) -> jnp.ndarray:
    if act_quant and "kernel_q" not in p:
        # Trace-time check: act_quant requires int8 weights; silently
        # running bf16 matmuls would hide the misconfiguration behind
        # benchmarks that show no speedup.
        import warnings

        warnings.warn(
            "act_quant=True but weights are not int8-quantized "
            "(no kernel_q); running the bf16 path — quantize the params "
            "(utils/quantize.py) to get the s8 x s8 MXU speedup",
            stacklevel=2)
    if "kernel_q" in p:
        if act_quant:
            # W8A8: s8 x s8 -> s32 on the MXU int8 path (measured ~1.4x the bf16
            # rate on v5e); both scales factor out of the contraction.
            x_q, xs = _quant_act(x)
            y32 = jax.lax.dot_general(
                x_q, p["kernel_q"],
                (((x.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )
            y = (y32.astype(jnp.float32) * xs
                 * p["scale"]).astype(x.dtype)
        else:
            # Weight-only int8 (utils/quantize.py): per-output-channel
            # scale commutes with the contraction, so dequant is a
            # [out]-vector multiply on the result, never a materialized
            # bf16 weight.  The int8->activation-dtype cast fuses into
            # the MXU operand read.
            y = ((x @ p["kernel_q"].astype(x.dtype))
                 * p["scale"].astype(x.dtype))
    else:
        y = x @ p["kernel"]
    if "bias" in p:
        y = y + p["bias"]
    return y


def row_parallel_partial(p: Params, x: jnp.ndarray, act_quant: bool,
                         axis_name: str):
    """Shard-local half of a row-parallel ``_linear`` for hand-staged
    reduction under ``shard_map`` (parallel/overlap.py).

    Returns ``(partial, finish)``: ``partial`` is this shard's
    un-reduced contribution [..., out] (int32 under W8A8 — integer
    addition is associative, so reducing the raw dot output across
    shards is bit-exact in any order); ``finish`` maps the reduced (or
    reduce-scattered) array back to activation dtype, slicing the
    per-out-channel dequant scale to the shard's chunk when the caller
    hands it a scattered slice.

    Exactness contract vs the GSPMD-auto psum of ``_linear``:
      * W8A8: the per-token amax is GLOBAL over the contraction dim —
        GSPMD computes it on the replicated activation, so the shard-local
        amax must be ``pmax``-combined (max is order-independent, exact)
        before quantizing, and the int32 partials must be reduced BEFORE
        the float scales apply, in the same multiply order.
      * weight-only int8: per-out-channel scales commute with the
        contraction, so they apply after the reduce, sliced to the chunk.
    Row projections never carry a bias in the supported model families
    (``overlap_supported`` gates on it): a bias must be added exactly
    once, not once per shard.
    """
    if "kernel_q" in p and act_quant:
        amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1,
                       keepdims=True)
        amax = jax.lax.pmax(amax, axis_name)
        scale = jnp.maximum(amax / 127.0, 1e-8)
        x_q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale),
                       -127, 127).astype(jnp.int8)
        part = jax.lax.dot_general(
            x_q, p["kernel_q"],
            (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )

        def finish(y: jnp.ndarray) -> jnp.ndarray:
            ws = _out_chunk(p["scale"], y.shape[-1], axis_name)
            return (y.astype(jnp.float32) * scale * ws).astype(x.dtype)
    elif "kernel_q" in p:
        part = x @ p["kernel_q"].astype(x.dtype)

        def finish(y: jnp.ndarray) -> jnp.ndarray:
            ws = _out_chunk(p["scale"], y.shape[-1], axis_name)
            return y * ws.astype(y.dtype)
    else:
        part = x @ p["kernel"]

        def finish(y: jnp.ndarray) -> jnp.ndarray:
            return y
    return part, finish


def _out_chunk(vec: jnp.ndarray, chunk: int, axis_name: str) -> jnp.ndarray:
    """This shard's contiguous chunk of a replicated per-out-channel
    vector (row-parallel o/down scales replicate under partition_rules —
    no regex matches them — so each shard slices its own piece)."""
    if vec.shape[0] == chunk:
        return vec
    idx = jax.lax.axis_index(axis_name)
    return jax.lax.dynamic_slice_in_dim(vec, idx * chunk, chunk, axis=0)


def _embed_lookup(params: Params, cfg: ModelConfig,
                  tokens: jnp.ndarray) -> jnp.ndarray:
    """Token embedding lookup, handling int8-quantized tables."""
    emb = params["embed"]
    dtype = jnp.dtype(cfg.dtype)
    if "weight_q" in emb:
        rows = emb["weight_q"][tokens].astype(dtype)
        rows = rows * emb["scale"][tokens][..., None].astype(dtype)
    else:
        rows = emb["weight"][tokens]
    if cfg.embed_scale:   # Gemma: sqrt(H) normalizer, rounded to dtype
        rows = rows * jnp.asarray(cfg.hidden_size ** 0.5, rows.dtype)
    return rows


def _qkv_proj(layer: Params, cfg: ModelConfig, x: jnp.ndarray):
    """Projections only (no rope).  x: [B, S, H] -> q [B,S,nH,D],
    k/v [B,S,nKV,D].  The fused decode kernel takes these raw and applies
    rope in-kernel; every other path ropes via ``_qkv``."""
    B, S, _ = x.shape
    D = cfg.head_dim_
    aq = cfg.act_quant
    q = _linear(layer["q"], x, aq).reshape(B, S, cfg.num_heads, D)
    k = _linear(layer["k"], x, aq).reshape(B, S, cfg.num_kv_heads, D)
    v = _linear(layer["v"], x, aq).reshape(B, S, cfg.num_kv_heads, D)
    return q, k, v


def _qkv(layer: Params, cfg: ModelConfig, x: jnp.ndarray, cos, sin):
    """Project + rope.  x: [B, S, H] -> q [B,S,nH,D], k/v [B,S,nKV,D]."""
    q, k, v = _qkv_proj(layer, cfg, x)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    return q, k, v


def is_fused_decode_impl(attn_impl) -> bool:
    """True when ``attn_impl`` uses the fused decode calling convention
    (ops/pallas_attention.py:paged_decode_attention_fused — raw q/k/v +
    rope angles in, attention + updated pages out).  Survives a
    functools.partial wrap (tests bind interpret=True that way)."""
    return bool(getattr(attn_impl, "fused_decode", False)
                or getattr(getattr(attn_impl, "func", None),
                           "fused_decode", False))


def is_fused_quant_decode_impl(attn_impl) -> bool:
    """True when ``attn_impl`` is the quantized-KV fused decode kernel
    (ops/pallas_attention.py:paged_decode_attention_fused_quant — takes
    page scales, returns updated scales).  A fused impl WITHOUT this marker
    must never be handed a quantized pool; decode_step falls back to the
    gather/dequant path in that case."""
    return bool(getattr(attn_impl, "quant_kv", False)
                or getattr(getattr(attn_impl, "func", None),
                           "quant_kv", False))


def is_flash_prefill_impl(attn_impl) -> bool:
    """True when ``attn_impl`` is the flash paged-prefill kernel
    (ops/pallas_attention.py:flash_prefill_attention — tiled online
    softmax reading K/V straight from the pool, scale planes as kwargs
    for quantized pools).  Survives a functools.partial wrap (CPU runs
    bind interpret=True that way)."""
    return bool(getattr(attn_impl, "flash_prefill", False)
                or getattr(getattr(attn_impl, "func", None),
                           "flash_prefill", False))


def _expert_weights(p: Params, dtype, act_quant: bool = False):
    """Expert kernel stack for einsum use: bf16 passthrough, or the int8
    stack (cast fuses into the MXU operand read) + its [E, out] scales."""
    if act_quant and "kernel_q" not in p:
        # Trace-time check, mirroring _linear: the MoE MLP is the dominant
        # FLOPs — silently running it bf16 under act_quant would hide the
        # misconfiguration behind benchmarks showing no W8A8 speedup.
        import warnings

        warnings.warn(
            "act_quant=True but expert stacks are not int8-quantized "
            "(no kernel_q); MoE MLP runs the bf16 path — quantize the "
            "params (utils/quantize.py) for the s8 x s8 MXU speedup",
            stacklevel=2)
    if "kernel_q" in p:
        return p["kernel_q"].astype(dtype), p["scale"]
    return p["kernel"], None


def _moe_mlp(layer: Params, cfg: ModelConfig,
             x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Mixture-of-experts SwiGLU with GShard capacity dispatch.

    x [B, S, H] -> (y [B, S, H], aux scalar).  Everything is expressed as
    dense einsums over a static per-expert capacity C, so the computation
    is one fixed XLA program: with the stacked expert kernels sharded
    [E over ``model``] and the dispatched activations [E, C, H] sharded the
    same way, GSPMD inserts the token all-to-alls automatically — expert
    parallelism with zero manual collectives, the same way the TP specs
    work (parallel/sharding.py).  Overflow beyond C skips the MLP: the
    residual connection passes those tokens through unchanged (standard
    GShard/Switch behavior).

    ``aux`` is the Switch-style load-balancing loss (num_experts * sum of
    mean router probability x mean dispatch fraction per expert, computed
    over the top-1 choice); forward_full folds it out for training.
    """
    B, S, H = x.shape
    T = B * S
    E, K = cfg.num_experts, cfg.num_experts_per_tok
    # GShard token grouping: dispatch within fixed-size groups so the
    # one-hot tensors stay O(T) — ungrouped, [T, E, C] with C ~ T*K/E is
    # quadratic in T and OOMs at long-context training shapes.
    Tg = next(g for g in (512, 256, 128, 64, 32, 16, 8, 4, 2, 1)
              if T % g == 0)
    G = T // Tg
    C = max(1, -(-Tg * K * int(100 * cfg.capacity_factor) // (100 * E)))
    xt = x.reshape(G, Tg, H)

    logits = _linear(layer["router"], xt)                      # [G, Tg, E]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    topv, topi = jax.lax.top_k(probs, K)                       # [G, Tg, K]
    topv = topv / jnp.sum(topv, axis=-1, keepdims=True)        # renorm

    # Capacity assignment per choice rank within each group: tokens claim
    # slots in index order; a token whose expert is full at its rank is
    # dropped (for that choice only).  dispatch [G, Tg, E, C] one-hot;
    # combine adds the router weight.
    dispatch = jnp.zeros((G, Tg, E, C), jnp.float32)
    combine = jnp.zeros((G, Tg, E, C), jnp.float32)
    used = jnp.zeros((G, E), jnp.int32)     # slots claimed by earlier ranks
    for j in range(K):
        mask_j = jax.nn.one_hot(topi[..., j], E, dtype=jnp.float32)
        pos_j = (jnp.cumsum(mask_j, axis=1) - 1.0
                 + used[:, None, :].astype(jnp.float32))
        keep = (pos_j < C) & (mask_j > 0)
        slot = jax.nn.one_hot(pos_j.astype(jnp.int32), C,
                              dtype=jnp.float32) * keep[..., None]
        dispatch = dispatch + mask_j[..., None] * slot
        combine = combine + (topv[..., j][..., None, None]
                             * mask_j[..., None] * slot)
        used = used + jnp.sum(mask_j * keep, axis=1).astype(jnp.int32)

    xs = jnp.einsum("gtec,gth->gech", dispatch.astype(x.dtype), xt)
    if cfg.act_quant and "kernel_q" in layer["gate_e"]:
        # W8A8 experts: s8 x s8 -> s32 on the MXU int8 path, same contract
        # as _linear (activation scale per token row, weight scale per
        # (expert, out-channel), both factor out of the contraction).
        xs_q, xs_s = _quant_act(xs)
        gate = (jnp.einsum("gech,ehi->geci", xs_q,
                           layer["gate_e"]["kernel_q"],
                           preferred_element_type=jnp.int32)
                .astype(jnp.float32) * xs_s
                * layer["gate_e"]["scale"][None, :, None, :]).astype(x.dtype)
        up = (jnp.einsum("gech,ehi->geci", xs_q,
                         layer["up_e"]["kernel_q"],
                         preferred_element_type=jnp.int32)
              .astype(jnp.float32) * xs_s
              * layer["up_e"]["scale"][None, :, None, :]).astype(x.dtype)
        h2 = jax.nn.silu(gate) * up
        h2_q, h2_s = _quant_act(h2)
        ys = (jnp.einsum("geci,eih->gech", h2_q,
                         layer["down_e"]["kernel_q"],
                         preferred_element_type=jnp.int32)
              .astype(jnp.float32) * h2_s
              * layer["down_e"]["scale"][None, :, None, :]).astype(x.dtype)
    else:
        gk, gs = _expert_weights(layer["gate_e"], x.dtype, cfg.act_quant)
        uk, us = _expert_weights(layer["up_e"], x.dtype)
        dk, ds = _expert_weights(layer["down_e"], x.dtype)
        gate = jnp.einsum("gech,ehi->geci", xs, gk)
        up = jnp.einsum("gech,ehi->geci", xs, uk)
        if gs is not None:   # weight-only int8: dequant on the result
            gate = gate * gs[None, :, None, :].astype(gate.dtype)
            up = up * us[None, :, None, :].astype(up.dtype)
        ys = jnp.einsum("geci,eih->gech", jax.nn.silu(gate) * up, dk)
        if ds is not None:
            ys = ys * ds[None, :, None, :].astype(ys.dtype)
    y = jnp.einsum("gtec,gech->gth", combine.astype(x.dtype), ys)

    # Load balance on the top-1 assignment (Switch Transformer eq. 4).
    top1 = jax.nn.one_hot(topi[..., 0].reshape(-1), E, dtype=jnp.float32)
    aux = E * jnp.sum(jnp.mean(top1, axis=0)
                      * jnp.mean(probs.reshape(-1, E), axis=0))
    return y.reshape(B, S, H), aux


def _moe_mlp_dropless(layer: Params, cfg: ModelConfig,
                      x: jnp.ndarray) -> jnp.ndarray:
    """Dropless MoE for inference: every token gets its full top-k experts.

    The capacity dispatch above is a TRAINING convention — at inference a
    capacity drop would make a request's output depend on what else is
    co-batched (and diverge from HF Mixtral, which is dropless).  This
    path runs every expert's SwiGLU on all tokens as STACKED einsums over
    the expert axis and contracts against the scattered router weights —
    E/K more MLP FLOPs than routed dispatch, which decode never notices
    (it is bound by streaming the expert weights, paid identically either
    way).  Keeping E as an einsum axis (never a Python-loop index) is what
    preserves expert parallelism on a serving mesh: each device computes
    only its local expert shard over the (model-replicated) activations,
    and the final contraction over E becomes the GSPMD psum — a per-expert
    slice loop would instead all-gather every expert's kernel to every
    device.  The [E, B, S, I] transient is per-device E/tp-sliced; on a
    single chip it bounds the dropless chunk size (tiny test configs and
    decode shapes are fine — Mixtral-class weights need a mesh anyway).
    """
    B, S, H = x.shape
    E, K = cfg.num_experts, cfg.num_experts_per_tok
    logits = _linear(layer["router"], x)                       # [B, S, E]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    topv, topi = jax.lax.top_k(probs, K)
    topv = topv / jnp.sum(topv, axis=-1, keepdims=True)
    # Router weights scattered back to [B, S, E] (zero for unchosen).
    w = jnp.sum(jax.nn.one_hot(topi, E, dtype=jnp.float32)
                * topv[..., None], axis=2)
    if cfg.act_quant and "kernel_q" in layer["gate_e"]:
        # W8A8 experts (see _moe_mlp): s8 x s8 MXU path for the dominant
        # MLP FLOPs — without this, quantize=w8a8 on MoE models would
        # silently run bf16 expert matmuls.
        x_q, x_s = _quant_act(x)
        gate = (jnp.einsum("bsh,ehi->ebsi", x_q,
                           layer["gate_e"]["kernel_q"],
                           preferred_element_type=jnp.int32)
                .astype(jnp.float32) * x_s[None]
                * layer["gate_e"]["scale"][:, None, None, :]).astype(x.dtype)
        up = (jnp.einsum("bsh,ehi->ebsi", x_q,
                         layer["up_e"]["kernel_q"],
                         preferred_element_type=jnp.int32)
              .astype(jnp.float32) * x_s[None]
              * layer["up_e"]["scale"][:, None, None, :]).astype(x.dtype)
        h2 = jax.nn.silu(gate) * up
        h2_q, h2_s = _quant_act(h2)
        ys = (jnp.einsum("ebsi,eih->ebsh", h2_q,
                         layer["down_e"]["kernel_q"],
                         preferred_element_type=jnp.int32)
              .astype(jnp.float32) * h2_s
              * layer["down_e"]["scale"][:, None, None, :]).astype(x.dtype)
    else:
        gk, gs = _expert_weights(layer["gate_e"], x.dtype, cfg.act_quant)
        uk, us = _expert_weights(layer["up_e"], x.dtype)
        dk, ds = _expert_weights(layer["down_e"], x.dtype)
        gate = jnp.einsum("bsh,ehi->ebsi", x, gk)
        up = jnp.einsum("bsh,ehi->ebsi", x, uk)
        if gs is not None:   # weight-only int8: dequant on the result
            gate = gate * gs[:, None, None, :].astype(gate.dtype)
            up = up * us[:, None, None, :].astype(up.dtype)
        ys = jnp.einsum("ebsi,eih->ebsh", jax.nn.silu(gate) * up, dk)
        if ds is not None:
            ys = ys * ds[:, None, None, :].astype(ys.dtype)
    return jnp.einsum("ebsh,bse->bsh", ys, w.astype(x.dtype))


def _mlp_act(cfg: ModelConfig, x: jnp.ndarray) -> jnp.ndarray:
    if cfg.mlp_activation == "gelu_tanh":      # Gemma GeGLU
        return jax.nn.gelu(x, approximate=True)
    return jax.nn.silu(x)


def _mlp(layer: Params, cfg: ModelConfig, x: jnp.ndarray) -> jnp.ndarray:
    if cfg.num_experts > 0:
        return _moe_mlp_dropless(layer, cfg, x)
    aq = cfg.act_quant
    gate = _linear(layer["gate"], x, aq)
    up = _linear(layer["up"], x, aq)
    return _linear(layer["down"], _mlp_act(cfg, gate) * up, aq)


def _residual_tail(layer: Params, cfg: ModelConfig, x: jnp.ndarray,
                   o: jnp.ndarray, collect_aux: bool = False
                   ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Everything after the attention output projection: the (optionally
    sandwich-normed) attention residual, the pre-MLP norm, the MLP (or MoE
    path), and the MLP residual.  The ONE definition shared by
    layer_block, _prefill_impl, and decode_step so the serving loops can
    never drift from the dense reference.  Returns (x, aux)."""
    uo = cfg.rmsnorm_unit_offset
    if cfg.sandwich_norms:
        o = rms_norm(o, layer["post_attn_norm"], cfg.rms_norm_eps, uo)
    x = x + o
    h = rms_norm(x, layer["post_norm"], cfg.rms_norm_eps, uo)
    if cfg.num_experts > 0 and collect_aux:
        y, aux = _moe_mlp(layer, cfg, h)
    else:
        y, aux = _mlp(layer, cfg, h), jnp.zeros((), jnp.float32)
    if cfg.sandwich_norms:
        y = rms_norm(y, layer["post_mlp_norm"], cfg.rms_norm_eps, uo)
    return x + y, aux


def _attn_extras(cfg: ModelConfig, layer_idx: int) -> dict:
    """Per-layer attention kwargs for Gemma-style models; {} for the Llama
    conventions (so stub/custom attention impls never see surprises)."""
    if not cfg.has_attn_extras:
        return {}
    return {"scale": cfg.attn_scale,
            "logit_softcap": cfg.attn_logit_softcap,
            "window": cfg.layer_window(layer_idx)}


def _unembed(params: Params, cfg: ModelConfig, x: jnp.ndarray) -> jnp.ndarray:
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps,
                 cfg.rmsnorm_unit_offset)
    if cfg.tie_embeddings:
        emb = params["embed"]
        if "weight_q" in emb:
            logits = ((x @ emb["weight_q"].T.astype(x.dtype))
                      * emb["scale"].astype(x.dtype))
        else:
            logits = x @ emb["weight"].T
    else:
        # The vocab projection stays weight-only even under act_quant:
        # int8 noise on the pre-logits hidden state flips near-tied argmax
        # (and the tied-embeddings path is weight-only too) — standard
        # W8A8 practice excludes the head.
        logits = _linear(params["lm_head"], x)
    logits = logits.astype(jnp.float32)
    if cfg.final_logit_softcap:
        logits = cfg.final_logit_softcap * jnp.tanh(
            logits / cfg.final_logit_softcap)
    return logits


# ---------------------------------------------------------------------------
# Dense forward (training / parity)
# ---------------------------------------------------------------------------


def layer_block(
    layer: Params,
    cfg: ModelConfig,
    x: jnp.ndarray,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    positions: jnp.ndarray,
    attn_fn=None,
    collect_aux: bool = False,
    layer_idx: int = 0,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """One transformer layer (norm/QKV/attention/residual/MLP) — the single
    definition shared by forward_full and the pipeline stage scan
    (parallel/pipeline.py), so the layer semantics cannot drift between
    the dense and pipelined paths.

    ``collect_aux`` selects the TRAINING MoE path (capacity dispatch +
    load-balance aux); otherwise MoE configs run the dropless inference
    path.  ``layer_idx`` feeds the per-layer sliding-window pattern
    (Gemma-2 alternates local/global).  Returns (x, aux scalar — 0.0
    unless collecting).
    """
    if attn_fn is None:
        attn_fn = causal_attention
    B, S = x.shape[:2]
    h = rms_norm(x, layer["input_norm"], cfg.rms_norm_eps,
                 cfg.rmsnorm_unit_offset)
    q, k, v = _qkv(layer, cfg, h, cos, sin)
    attn = attn_fn(q, k, v, q_positions=positions,
                   **_attn_extras(cfg, layer_idx))
    o = _linear(layer["o"], attn.reshape(B, S, -1), cfg.act_quant)
    return _residual_tail(layer, cfg, x, o, collect_aux)


def forward_full(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,
    *,
    positions: Optional[jnp.ndarray] = None,
    attn_fn=None,
    return_aux: bool = False,
) -> jnp.ndarray:
    """Dense causal forward.  tokens [B, S] -> logits [B, S, V] (float32).

    ``attn_fn`` swaps the attention implementation (default dense
    ``causal_attention``; pass ``parallel.ring_attention.make_ring_attention``
    output for sequence-parallel long-context training).

    ``return_aux`` additionally returns the mean MoE load-balancing loss
    over layers (0.0 for dense models) — the training path folds it into
    the objective.  It also selects the MoE TRAINING dispatch (GShard
    capacity, tokens can drop); without it MoE runs dropless (inference
    semantics, HF parity).
    """
    B, S = tokens.shape
    x = _embed_lookup(params, cfg, tokens)
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    cos, sin = rope_angles(positions, cfg.head_dim_, cfg.rope_theta,
                           scaling=cfg.rope_scaling)
    aux_total = jnp.zeros((), jnp.float32)
    for li, layer in enumerate(params["layers"]):
        x, aux = layer_block(layer, cfg, x, cos, sin, positions,
                             attn_fn=attn_fn, collect_aux=return_aux,
                             layer_idx=li)
        aux_total = aux_total + aux
    logits = _unembed(params, cfg, x)
    if return_aux:
        return logits, aux_total / max(len(params["layers"]), 1)
    return logits


# ---------------------------------------------------------------------------
# Paged-cache scatter
# ---------------------------------------------------------------------------


def _scatter_pages(
    pages: jnp.ndarray,
    vals: jnp.ndarray,
    block_table: jnp.ndarray,
    positions: jnp.ndarray,
    valid: jnp.ndarray,
) -> jnp.ndarray:
    """Write vals[b, s] to pages[block_table[b, pos//bs], pos%bs].

    Invalid lanes are redirected to the null block 0.

    pages: [num_blocks, bs, KVH*D] (fused lane layout); vals: [B, S, KVH, D];
    block_table: [B, max_blocks]; positions/valid: [B, S].
    """
    bs = pages.shape[1]
    B, S = positions.shape
    raw_blk = positions // bs                        # [B, S] index into table
    blk_idx = jnp.clip(raw_blk, 0, block_table.shape[1] - 1)
    block_ids = jnp.take_along_axis(block_table, blk_idx, axis=1)  # [B, S]
    # Positions past the table redirect to the null block rather than
    # clipping into the lane's LAST real block: a speculative verify at the
    # capacity boundary writes rejected-draft K/V beyond the per-seq cap,
    # and a clip would overwrite live cache there (silent wrong logits).
    block_ids = jnp.where(valid & (raw_blk < block_table.shape[1]),
                          block_ids, 0)
    offs = positions % bs
    flat_blocks = block_ids.reshape(-1)
    flat_offs = offs.reshape(-1)
    flat_vals = vals.reshape(B * S, -1)              # fuse [KVH, D] -> lanes
    # Explicit cast: fp8 KV pages (ModelConfig.kv_dtype) have no implicit
    # promotion path from the bf16 projections.
    return pages.at[flat_blocks, flat_offs].set(
        flat_vals.astype(pages.dtype))


def _qmax_for(dtype) -> float:
    return 127.0 if jnp.dtype(dtype) == jnp.int8 else 448.0


def _scatter_pages_quant(
    pages: jnp.ndarray,
    spages: jnp.ndarray,
    vals: jnp.ndarray,
    block_table: jnp.ndarray,
    positions: jnp.ndarray,
    valid: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Quantize-on-append twin of ``_scatter_pages`` for the quantized KV
    tier: per-(token, head) symmetric quantization of ``vals`` [B, S, KVH, D]
    into the storage-dtype pages plus a parallel scatter of the float32
    scales into ``spages`` [num_blocks, bs, KVH].  Values are rounded before
    the int8 cast (``.astype`` alone truncates toward zero)."""
    qmax = _qmax_for(pages.dtype)
    xf = vals.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax / qmax, 1e-8)
    xq = xf / scale[..., None]
    if jnp.dtype(pages.dtype) == jnp.int8:
        xq = jnp.clip(jnp.round(xq), -qmax, qmax)
    return (_scatter_pages(pages, xq, block_table, positions, valid),
            _scatter_pages(spages, scale, block_table, positions, valid))


# ---------------------------------------------------------------------------
# Prefill
# ---------------------------------------------------------------------------


def _prefill_impl(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,
    positions: jnp.ndarray,
    valid: jnp.ndarray,
    lengths: jnp.ndarray,
    kv_len: jnp.ndarray,
    pages: KVPages,
    block_tables: jnp.ndarray,
    attend_to_pages: bool,
    return_all_logits: bool = False,
    paged_attn_fn=None,
) -> tuple[jnp.ndarray, KVPages]:
    """Shared prefill layer loop.

    ``attend_to_pages`` selects the attention K/V source: False = the chunk's
    own in-flight k/v (first chunk, positions start at 0); True = gather the
    paged cache after scattering (continuation chunks attending to a cached
    prefix).  Everything else — embed, qkv+rope, scatter, residual/MLP,
    last-valid-token unembed — is identical and lives here exactly once.

    ``return_all_logits`` switches the unembed from the last valid token
    ([B, V]) to every position ([B, S, V]) — the speculative-decode verify
    pass needs per-position logits to score its draft tokens.
    """
    B, S = tokens.shape
    cos, sin = rope_angles(positions, cfg.head_dim_, cfg.rope_theta,
                           scaling=cfg.rope_scaling)

    x = _embed_lookup(params, cfg, tokens)
    uo = cfg.rmsnorm_unit_offset
    quant = pages.quantized
    new_k, new_v = [], []
    new_ks, new_vs = [], []
    for li, layer in enumerate(params["layers"]):
        h = rms_norm(x, layer["input_norm"], cfg.rms_norm_eps, uo)
        q, k, v = _qkv(layer, cfg, h, cos, sin)
        if quant:
            pk, psk = _scatter_pages_quant(pages.k[li], pages.k_scale[li],
                                           k, block_tables, positions, valid)
            pv, psv = _scatter_pages_quant(pages.v[li], pages.v_scale[li],
                                           v, block_tables, positions, valid)
            new_ks.append(psk)
            new_vs.append(psv)
        else:
            pk = _scatter_pages(pages.k[li], k, block_tables, positions,
                                valid)
            pv = _scatter_pages(pages.v[li], v, block_tables, positions,
                                valid)
        new_k.append(pk)
        new_v.append(pv)
        if paged_attn_fn is not None and is_flash_prefill_impl(paged_attn_fn):
            # Flash paged prefill: the scatter above already wrote this
            # chunk's K/V into the pages, so fresh prefill (positions
            # start at 0) and continuation chunks are the same kernel
            # call — no gather_pages round-trip, no [S, T] score matrix.
            # Quantized pools hand the kernel their scale planes and
            # dequantize in-kernel; the pool never widens in HBM.
            if quant:
                attn = paged_attn_fn(q, pk, pv, block_tables,
                                     positions[:, 0], lengths,
                                     k_scale=psk, v_scale=psv)
            else:
                attn = paged_attn_fn(q, pk, pv, block_tables,
                                     positions[:, 0], lengths)
        elif attend_to_pages and paged_attn_fn is not None and not quant:
            # Page-streaming path (Pallas verify kernel): queries are
            # contiguous at positions[:, 0] + i, which both verify_step
            # and prefill_chunk guarantee.  (select_verify_impl returns
            # None for attn-extras models, so no kwargs needed here.
            # Quantized pools take the gather branch below instead — the
            # verify kernel has no scale inputs; the engine mirrors this
            # by dropping its verify impl under kv quant.)
            attn = paged_attn_fn(q, pk, pv, block_tables,
                                 positions[:, 0], lengths)
        else:
            if attend_to_pages and quant:
                # Dequantize-on-read: gather pages AND scales, apply the
                # per-(token, head) scale on the small gathered activation
                # (never the resident pool).
                ks = gather_pages(psk, block_tables)       # [B, T, KVH]
                vs = gather_pages(psv, block_tables)
                kk = (gather_pages(pk, block_tables).astype(jnp.float32)
                      .reshape(B, -1, cfg.num_kv_heads, cfg.head_dim_)
                      * ks[..., None]).astype(k.dtype)
                vv = (gather_pages(pv, block_tables).astype(jnp.float32)
                      .reshape(B, -1, cfg.num_kv_heads, cfg.head_dim_)
                      * vs[..., None]).astype(v.dtype)
            elif attend_to_pages:
                # Gathered view is [B, T, KVH*D]; unfuse for attention (the
                # reshape touches the small gathered activation, never the
                # resident page arrays).
                kk = gather_pages(pk, block_tables).reshape(
                    B, -1, cfg.num_kv_heads, cfg.head_dim_)
                vv = gather_pages(pv, block_tables).reshape(
                    B, -1, cfg.num_kv_heads, cfg.head_dim_)
            else:
                kk, vv = k, v
            attn = causal_attention(q, kk, vv, q_positions=positions,
                                    kv_len=kv_len,
                                    **_attn_extras(cfg, li))
        o = _linear(layer["o"], attn.reshape(B, S, -1), cfg.act_quant)
        x, _ = _residual_tail(layer, cfg, x, o)

    out_pages = KVPages(k=new_k, v=new_v,
                        k_scale=new_ks if quant else (),
                        v_scale=new_vs if quant else ())
    if return_all_logits:
        return _unembed(params, cfg, x), out_pages
    last_idx = jnp.maximum(lengths - 1, 0)
    x_last = jnp.take_along_axis(x, last_idx[:, None, None], axis=1)  # [B,1,H]
    logits = _unembed(params, cfg, x_last)[:, 0, :]
    return logits, out_pages


def prefill(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,
    lengths: jnp.ndarray,
    pages: KVPages,
    block_tables: jnp.ndarray,
    *,
    attn_impl=None,
) -> tuple[jnp.ndarray, KVPages]:
    """Ingest padded prompts, writing K/V into the paged cache.

    Args:
      tokens: [B, S_pad] int32 (right-padded).
      lengths: [B] int32 true prompt lengths (0 = inactive lane).
      pages: paged KV cache.
      block_tables: [B, max_blocks] int32.
      attn_impl: optional flash paged-prefill kernel (ops/attention.py:
        select_prefill_impl); None = dense in-flight attention.  The
        scatter-before-attention order makes the two equivalent: the
        pages already hold exactly this call's K/V when attention runs.

    Returns:
      (last_logits [B, V] float32, updated pages)
    """
    B, S = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    valid = positions < lengths[:, None]
    return _prefill_impl(params, cfg, tokens, positions, valid, lengths,
                         lengths, pages, block_tables, attend_to_pages=False,
                         paged_attn_fn=attn_impl)


def prefill_chunk(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,
    start: jnp.ndarray,
    lengths: jnp.ndarray,
    pages: KVPages,
    block_tables: jnp.ndarray,
    *,
    attn_impl=None,
) -> tuple[jnp.ndarray, KVPages]:
    """Continuation prefill: ingest a chunk of a prompt whose first ``start``
    tokens are already in the paged cache.

    Used for (a) prompts longer than the largest prefill bucket and (b)
    re-admission after recompute-preemption, where the folded prompt can
    exceed any single bucket.  Unlike ``prefill``, attention here runs
    against the paged cache (prefix + chunk) rather than the in-flight
    buffer, masked causally by absolute position.

    Args:
      tokens: [B, S] chunk tokens (right-padded).
      start: [B] int32 — tokens already in the cache for each sequence.
      lengths: [B] int32 — valid tokens in this chunk (0 = inactive lane).
      pages / block_tables: paged cache state.
      attn_impl: optional flash paged-prefill kernel — skips the dense
        ``gather_pages`` prefix materialization entirely.

    Returns:
      (last-chunk-token logits [B, V] float32, updated pages)
    """
    B, S = tokens.shape
    offs = jnp.arange(S, dtype=jnp.int32)
    positions = start[:, None] + offs[None, :]
    valid = offs[None, :] < lengths[:, None]
    return _prefill_impl(params, cfg, tokens, positions, valid, lengths,
                         start + lengths, pages, block_tables,
                         attend_to_pages=True, paged_attn_fn=attn_impl)


def verify_step(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,
    start: jnp.ndarray,
    lengths: jnp.ndarray,
    pages: KVPages,
    block_tables: jnp.ndarray,
    *,
    attn_impl=None,
) -> tuple[jnp.ndarray, KVPages]:
    """Speculative-decode verify pass: score ``S`` candidate tokens at once.

    Identical cache semantics to ``prefill_chunk`` (tokens land at absolute
    positions ``start..start+lengths-1``, attention runs against the paged
    prefix + the chunk itself) but returns the logits of **every** position,
    [B, S, V] — position ``i``'s logits are the model's distribution for the
    token *after* ``tokens[:, i]``.  The caller accepts the longest draft
    prefix whose tokens match these distributions and advances
    ``context_lens`` by the accepted count; K/V written for rejected
    positions stays beyond ``context_lens`` and is masked out of every
    later attention read, then overwritten when real tokens arrive — so
    rejection needs no cache rollback.

    In greedy acceptance (token must equal the argmax) any draft source is
    correctness-neutral: the accepted prefix is exactly what step-by-step
    greedy decode would have produced.

    ``attn_impl``: optional paged multi-query attention (the Pallas verify
    kernel, ops/attention.py:select_verify_impl); None = XLA gather.
    """
    B, S = tokens.shape
    offs = jnp.arange(S, dtype=jnp.int32)
    positions = start[:, None] + offs[None, :]
    valid = offs[None, :] < lengths[:, None]
    return _prefill_impl(params, cfg, tokens, positions, valid, lengths,
                         start + lengths, pages, block_tables,
                         attend_to_pages=True, return_all_logits=True,
                         paged_attn_fn=attn_impl)


# ---------------------------------------------------------------------------
# Decode
# ---------------------------------------------------------------------------


def decode_step(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,
    context_lens: jnp.ndarray,
    pages: KVPages,
    block_tables: jnp.ndarray,
    *,
    attn_impl=paged_decode_attention,
) -> tuple[jnp.ndarray, KVPages]:
    """One decode step for a batch of slots.

    Args:
      tokens: [B] int32 — token to feed per slot.
      context_lens: [B] int32 — tokens already in cache (new token's position).
        0 means the slot is inactive (its writes go to the null block).
      pages / block_tables: paged cache state.
      attn_impl: paged attention implementation (XLA fallback or Pallas).

    Returns:
      (logits [B, V] float32, updated pages)
    """
    B = tokens.shape[0]
    positions = context_lens[:, None]  # [B, 1]
    active = (context_lens > 0)[:, None]
    cos, sin = rope_angles(positions, cfg.head_dim_, cfg.rope_theta,
                           scaling=cfg.rope_scaling)
    quant = pages.quantized
    fused_q = quant and is_fused_quant_decode_impl(attn_impl)
    # A fused impl without scale support must not touch a quantized pool;
    # fall through to the gather/dequant path instead.
    fused = is_fused_decode_impl(attn_impl) and (fused_q or not quant)

    x = _embed_lookup(params, cfg, tokens)[:, None, :]  # [B, 1, H]
    uo = cfg.rmsnorm_unit_offset
    new_lens = context_lens + 1
    new_k, new_v = [], []
    new_ks, new_vs = [], []
    for li, layer in enumerate(params["layers"]):
        h = rms_norm(x, layer["input_norm"], cfg.rms_norm_eps, uo)
        if fused_q:
            # Quantized fused fast-path: rope + quantize-on-append +
            # dequantize-in-kernel attention in one Pallas call; pages AND
            # scales are updated in place (aliased outputs).
            q, k, v = _qkv_proj(layer, cfg, h)
            attn, pk, pv, psk, psv = attn_impl(
                q, k, v, cos, sin, pages.k[li], pages.v[li],
                pages.k_scale[li], pages.v_scale[li],
                block_tables, context_lens)
            new_k.append(pk)
            new_v.append(pv)
            new_ks.append(psk)
            new_vs.append(psv)
        elif fused:
            # Fused fast-path: rope + KV append + attention in one Pallas
            # call; the kernel owns the scatter (in-place page update) and
            # the query/new-k rotary math.  Extras models never select
            # this path (ops/attention.py gates on has_attn_extras).
            q, k, v = _qkv_proj(layer, cfg, h)
            attn, pk, pv = attn_impl(q, k, v, cos, sin,
                                     pages.k[li], pages.v[li],
                                     block_tables, context_lens)
            new_k.append(pk)
            new_v.append(pv)
        elif quant:
            q, k, v = _qkv(layer, cfg, h, cos, sin)
            pk, psk = _scatter_pages_quant(pages.k[li], pages.k_scale[li],
                                           k, block_tables, positions,
                                           active)
            pv, psv = _scatter_pages_quant(pages.v[li], pages.v_scale[li],
                                           v, block_tables, positions,
                                           active)
            new_k.append(pk)
            new_v.append(pv)
            new_ks.append(psk)
            new_vs.append(psv)
            attn = paged_decode_attention_quant(q, pk, pv, psk, psv,
                                                block_tables, new_lens,
                                                **_attn_extras(cfg, li))
        else:
            q, k, v = _qkv(layer, cfg, h, cos, sin)
            pk = _scatter_pages(pages.k[li], k, block_tables, positions,
                                active)
            pv = _scatter_pages(pages.v[li], v, block_tables, positions,
                                active)
            new_k.append(pk)
            new_v.append(pv)
            # Extras models are guaranteed the gather impl
            # (select_attn_impl), which accepts the per-layer kwargs;
            # default models pass none so custom/Pallas impls keep their
            # fixed signature.
            attn = attn_impl(q, pk, pv, block_tables, new_lens,
                             **_attn_extras(cfg, li))
        o = _linear(layer["o"], attn.reshape(B, 1, -1), cfg.act_quant)
        x, _ = _residual_tail(layer, cfg, x, o)

    logits = _unembed(params, cfg, x)[:, 0, :]
    return logits, KVPages(k=new_k, v=new_v,
                           k_scale=new_ks if quant else (),
                           v_scale=new_vs if quant else ())
