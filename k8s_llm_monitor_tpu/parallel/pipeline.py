"""GPipe-style pipeline parallelism over a ``pipe`` mesh axis.

Completes the mesh-parallelism portfolio (data / seq / model / **pipe**):
tensor parallelism (sharding.py) splits every layer across chips and pays
a collective per matmul, which is only cheap inside an ICI domain;
pipeline parallelism instead places CONTIGUOUS LAYER BLOCKS on different
chips (or hosts) and moves a single [mb, S, H] activation between
neighbors once per stage — the natural axis to cross slower links (DCN
between hosts; reference counterpart: none, the reference's LLM layer is
config-only, internal/config/config.go:141-145).

Design (the "looped pipeline" of the public scaling playbooks, written
with explicit SPMD collectives):

  * **Stage-stacked params.**  ``stack_pipeline_params`` turns the
    per-layer list into one pytree whose leaves carry a leading
    ``[n_stages, layers_per_stage, ...]`` axis; axis 0 is sharded over
    ``pipe`` (``pipeline_param_specs``), so each device materializes only
    its own block — an 80-layer 70B on pipe-8 holds 10 layers per chip.
    Inside the ``shard_map`` each device ``lax.scan``s its block.
  * **Microbatch rotation.**  The global batch is split into M
    microbatches.  At tick t, stage 0 injects microbatch t while every
    other stage runs the activation it received from its neighbor at
    t-1; activations move stage s -> s+1 with a single ``ppermute``.
    T = M + P - 1 ticks drain the pipe (the P-1 bubble ticks are the
    standard GPipe overhead: efficiency M / (M + P - 1)).
  * **Embed / unembed stay OUTSIDE the shard_map** in plain GSPMD: the
    embedding is computed for all microbatches up front (sharded over
    ``data`` automatically) and the final hidden states come back
    replicated-over-pipe via a ``psum`` of the last stage's output
    buffer.  This keeps replicated-parameter gradients in XLA's hands —
    only the pipe-sharded layer block lives inside manual-collective
    land, where its gradient is purely local.  (The trade: activations
    for all microbatches are resident at once, fine at the scales the
    tests and dryrun run; an embed-on-stage-0 variant saves that memory
    at the cost of hand-written replicated-grad psums.)
  * **Exact gradients.**  GPipe semantics — no weight staleness; autodiff
    flows through ``ppermute``/``psum`` (both have well-defined
    transposes), so ``jax.grad`` of the pipelined loss equals the dense
    model's gradient (parity-tested).

Composes with data parallelism on a ``data x pipe`` mesh
(``create_pp_mesh``); sequence/tensor axes compose the same way but are
kept out of the stage body here — TP-within-stage is the documented
extension, not wired.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from k8s_llm_monitor_tpu.parallel.mesh import shard_map_compat


def _shard_map(f, *, mesh, in_specs, out_specs):
    # Replication checking stays off: the psum-broadcast output pattern
    # (only the last stage holds real values pre-psum) trips it.
    return shard_map_compat(f, mesh=mesh, in_specs=in_specs,
                            out_specs=out_specs, check_replication=False)
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from k8s_llm_monitor_tpu.models import llama
from k8s_llm_monitor_tpu.models.config import ModelConfig
from k8s_llm_monitor_tpu.ops.rope import rope_angles


def create_pp_mesh(data: int, pipe: int, devices=None) -> Mesh:
    """Build a ``data x pipe`` mesh.  Device order follows jax.devices():
    consecutive devices land on the ``pipe`` axis, so stage neighbors sit
    on adjacent chips (ICI) and the ``data`` axis crosses the slower
    boundary only once per step (gradient psum)."""
    if devices is None:
        devices = jax.devices()
    if data * pipe != len(devices):
        raise ValueError(f"mesh {data}x{pipe} needs {data * pipe} devices, "
                         f"have {len(devices)}")
    arr = np.asarray(devices).reshape(data, pipe)
    return Mesh(arr, ("data", "pipe"))


def stack_pipeline_params(params: dict, n_stages: int) -> dict:
    """Re-shape the per-layer param list into stage-stacked leaves.

    Returns ``{"embed", "final_norm", ["lm_head"], "layers": pytree with
    leaves [n_stages, layers_per_stage, ...]}``.  Requires the layer count
    to divide evenly (pad upstream if you must)."""
    L = len(params["layers"])
    if L % n_stages:
        raise ValueError(f"{L} layers do not divide {n_stages} stages")
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *params["layers"])
    staged = jax.tree.map(
        lambda x: x.reshape(n_stages, L // n_stages, *x.shape[1:]), stacked)
    out = {"embed": params["embed"], "final_norm": params["final_norm"],
           "layers": staged}
    if "lm_head" in params:
        out["lm_head"] = params["lm_head"]
    return out


def pipeline_param_specs(staged: dict) -> dict:
    """PartitionSpecs for the staged pytree: layer leaves shard their
    stage axis over ``pipe``; everything else is replicated."""
    specs = jax.tree.map(lambda _: P(), staged)
    specs["layers"] = jax.tree.map(
        lambda x: P("pipe", *([None] * (x.ndim - 1))), staged["layers"])
    return specs


def place_pipeline_params(staged: dict, mesh: Mesh) -> dict:
    specs = pipeline_param_specs(staged)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), staged, specs)


def place_pipeline_opt_state(opt_state, n_stages: int, mesh: Mesh):
    """Place optimizer state (e.g. restored AdamW moments) on the mesh.

    Moment leaves mirror the staged params, so anything shaped
    ``[n_stages, ...]`` with rank >= 3 is a stage-stacked layer moment
    (pipe-sharded); everything else — scalars like the optax step counter,
    embed/norm/head moments — replicates.  Needed because a host-side
    ``optimizer.init``/checkpoint-restore leaves committed single-device
    arrays that a mesh-jitted step would reject.
    """
    def put(x):
        x = jnp.asarray(x)
        if x.ndim >= 3 and x.shape[0] == n_stages:
            spec = P("pipe", *([None] * (x.ndim - 1)))
        else:
            spec = P()
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree.map(put, opt_state)


def _run_stage(cfg: ModelConfig, stage_layers, x: jnp.ndarray) -> jnp.ndarray:
    """Scan this device's layer block over x [mb, S, H] (dense causal
    attention — stages see whole sequences).  The per-layer math is
    llama.layer_block, shared with forward_full so the pipelined model
    cannot drift from the dense one."""
    mb, S, _ = x.shape
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (mb, S))
    cos, sin = rope_angles(positions, cfg.head_dim_, cfg.rope_theta,
                           scaling=cfg.rope_scaling)

    @jax.checkpoint
    def body(h, lyr):
        h, _ = llama.layer_block(lyr, cfg, h, cos, sin, positions)
        return h, None

    x, _ = jax.lax.scan(body, x, stage_layers)
    return x


def make_pipeline_forward(mesh: Mesh, cfg: ModelConfig):
    """Build the shard_mapped pipeline over the layer stack.

    Returns ``fn(staged_layers, x0) -> hidden`` where ``x0`` is the
    embedded input for all microbatches [M, B, S, H] (B sharded over
    ``data`` by GSPMD) and ``hidden`` is the post-layer-stack activation
    with identical sharding, replicated over ``pipe``.
    """
    if cfg.num_experts > 0:
        raise NotImplementedError(
            "pipeline parallelism does not thread the MoE load-balancing "
            "aux loss yet — train MoE configs on the GSPMD data x model "
            "mesh (expert parallelism, training/train.py) instead")
    if cfg.sliding_window > 0:
        raise NotImplementedError(
            "pipeline parallelism scans a stage's layers with one compiled "
            "body; per-layer sliding-window patterns (Gemma-2) need "
            "per-layer static masks — train these configs on the GSPMD "
            "mesh instead")

    def fn(staged_layers, x0):
        in_layer_specs = jax.tree.map(
            lambda x: P("pipe", *([None] * (x.ndim - 1))), staged_layers)
        act_spec = P(None, "data", None, None)

        @functools.partial(
            _shard_map, mesh=mesh,
            in_specs=(in_layer_specs, act_spec),
            out_specs=act_spec)
        def pipe(layers_local, x0_local):
            # layers_local leaves: [1, Lp, ...] -> [Lp, ...]
            layers_local = jax.tree.map(lambda x: x[0], layers_local)
            s = jax.lax.axis_index("pipe")
            # Static stage count from the mesh (jax.lax.axis_size only
            # exists in newer jax; T below must be static for the scan
            # length anyway).
            P_ = mesh.shape["pipe"]
            M, mb, S, H = x0_local.shape
            T = M + P_ - 1

            def tick(carry, t):
                recv, outbuf = carry
                x_in = jnp.where(s == 0,
                                 x0_local[jnp.clip(t, 0, M - 1)], recv)
                y = _run_stage(cfg, layers_local, x_in)
                widx = jnp.clip(t - (P_ - 1), 0, M - 1)
                write = (s == P_ - 1) & (t >= P_ - 1)
                outbuf = outbuf.at[widx].set(
                    jnp.where(write, y, outbuf[widx]))
                recv = jax.lax.ppermute(
                    y, "pipe", [(i, (i + 1) % P_) for i in range(P_)])
                return (recv, outbuf), None

            recv0 = jnp.zeros((mb, S, H), x0_local.dtype)
            out0 = jnp.zeros((M, mb, S, H), x0_local.dtype)
            (_, outbuf), _ = jax.lax.scan(
                tick, (recv0, out0), jnp.arange(T, dtype=jnp.int32))
            # Only the last stage wrote real values; psum broadcasts them
            # (and its transpose routes the backward activation gradients
            # straight back to the last stage).
            return jax.lax.psum(outbuf, "pipe")

        return pipe(staged_layers, x0)

    return fn


def pipeline_loss(cfg: ModelConfig, pipe_fwd, staged: dict,
                  tokens: jnp.ndarray, n_micro: int) -> jnp.ndarray:
    """Next-token CE of the pipelined model.  tokens [B, S] int32.

    Constraint chain: ``n_micro`` divides B, and the per-microbatch batch
    ``B / n_micro`` must divide the mesh's ``data`` axis (each microbatch
    is itself data-sharded).
    """
    B, S = tokens.shape
    if B % n_micro:
        raise ValueError(f"batch {B} does not divide {n_micro} microbatches")
    toks = tokens.reshape(n_micro, B // n_micro, S)
    x0 = llama._embed_lookup({"embed": staged["embed"]}, cfg,
                             toks.reshape(-1, S)).reshape(
        n_micro, B // n_micro, S, -1)
    hid = pipe_fwd(staged["layers"], x0)
    # _unembed applies the final norm itself.
    logits = llama._unembed(
        {"embed": staged["embed"], "final_norm": staged["final_norm"],
         **({"lm_head": staged["lm_head"]} if "lm_head" in staged else {})},
        cfg, hid.reshape(B, S, -1))
    logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
    tgt = tokens[:, 1:]
    nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)


def make_pipeline_train_step(mesh: Mesh, cfg: ModelConfig, optimizer,
                             n_micro: int):
    """Jitted AdamW train step over the ``data x pipe`` mesh.

    Returns ``step(staged_params, opt_state, tokens) -> (staged_params,
    opt_state, loss)``; place params with ``place_pipeline_params`` and
    shard tokens ``P("data", None)`` first.
    """
    import optax

    pipe_fwd = make_pipeline_forward(mesh, cfg)

    def loss_fn(staged, tokens):
        return pipeline_loss(cfg, pipe_fwd, staged, tokens, n_micro)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(staged, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(staged, tokens)
        updates, opt_state = optimizer.update(grads, opt_state, staged)
        staged = optax.apply_updates(staged, updates)
        return staged, opt_state, loss

    return step
