"""Device mesh construction.

Axis conventions used across the framework:
  ``data``  — data parallel (batch sharding; gradients psum here)
  ``model`` — tensor parallel (attention heads / MLP hidden; rides ICI)
  ``seq``   — sequence/context parallel (ring attention for long prompts)

Serving meshes are usually 1D ``model``; training meshes 2D ``data × model``;
long-context prefill adds ``seq``.  Axes of size 1 are always present so one
set of PartitionSpecs works on every mesh shape.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("data", "seq", "model")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    data: int = 1
    seq: int = 1
    model: int = 1

    @property
    def size(self) -> int:
        return self.data * self.seq * self.model


def create_mesh(
    cfg: MeshConfig | None = None,
    devices: list | None = None,
) -> Mesh:
    """Build a ``data × seq × model`` mesh.

    With no config, all devices go on the ``model`` axis (the serving
    default: TP over ICI).  Device order follows jax.devices(), which on TPU
    enumerates chips in ICI-neighbor order, so the innermost (``model``) axis
    gets the fastest links.
    """
    if devices is None:
        devices = jax.devices()
    if cfg is None:
        cfg = MeshConfig(model=len(devices))
    if cfg.size != len(devices):
        raise ValueError(f"mesh {cfg} needs {cfg.size} devices, have {len(devices)}")
    shape = (cfg.data, cfg.seq, cfg.model)
    try:
        # mesh_utils understands the physical ICI topology (2D/3D torus on
        # TPU) and orders devices so the innermost mesh axis lands on
        # nearest-neighbor links; a naive reshape of jax.devices() does NOT
        # guarantee that beyond 1D.
        from jax.experimental import mesh_utils

        arr = mesh_utils.create_device_mesh(shape, devices=devices)
    except (ValueError, AssertionError, NotImplementedError):
        # Virtual CPU meshes and odd single-host layouts fall back to
        # enumeration order, which is fine off-hardware.
        arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, AXES)
