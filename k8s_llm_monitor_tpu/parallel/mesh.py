"""Device mesh construction.

Axis conventions used across the framework:
  ``data``  — data parallel (batch sharding; gradients psum here)
  ``model`` — tensor parallel (attention heads / MLP hidden; rides ICI)
  ``seq``   — sequence/context parallel (ring attention for long prompts)

Serving meshes are usually 1D ``model``; training meshes 2D ``data × model``;
long-context prefill adds ``seq``.  Axes of size 1 are always present so one
set of PartitionSpecs works on every mesh shape.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("data", "seq", "model")

# Approximate aggregate ICI bandwidth per chip (GB/s, all links, one
# direction), keyed by substrings of jax Device.device_kind — the byte-model
# input for the engine's per-step collective-time share estimate.  CPU and
# unknown chips fall back to the v5e figure: the estimate is explicitly a
# model, and on the forced-host dev mesh it is annotated as a dryrun.
ICI_GBS = {
    "v5 lite": 200.0,   # v5e: 4 links x 400 Gbps
    "v5e": 200.0,
    "v5p": 600.0,
    "v4": 300.0,
    "v6": 448.0,        # v6e (Trillium)
}
_ICI_GBS_DEFAULT = 200.0


def ici_bandwidth_gbs(device_kind: str) -> float:
    """Per-chip aggregate ICI bandwidth for ``device_kind`` (GB/s)."""
    kind = device_kind.lower()
    for key, gbs in ICI_GBS.items():
        if key in kind:
            return gbs
    return _ICI_GBS_DEFAULT


# Per-chip HBM bandwidth (GB/s), same keying as ICI_GBS.  Paired with it
# in the overlap decode model (serving/engine.py:estimate_hidden_share):
# decode is weight-streaming bound, so the window available to hide a
# reduce-scatter/all-gather half under the next column-parallel matmul is
# the time that matmul spends streaming its weight shard from HBM.
HBM_GBS = {
    "v5 lite": 819.0,
    "v5e": 819.0,
    "v5p": 2765.0,
    "v4": 1228.0,
    "v6": 1640.0,       # v6e (Trillium)
}
_HBM_GBS_DEFAULT = 819.0


def hbm_bandwidth_gbs(device_kind: str) -> float:
    """Per-chip HBM bandwidth for ``device_kind`` (GB/s)."""
    kind = device_kind.lower()
    for key, gbs in HBM_GBS.items():
        if key in kind:
            return gbs
    return _HBM_GBS_DEFAULT


def init_multihost(coordinator: str | None = None,
                   num_processes: int | None = None,
                   process_id: int | None = None) -> int:
    """Join a multi-host JAX runtime (DCN between hosts, ICI within).

    On GKE/TPU-VM slices the environment usually carries everything and a
    bare ``jax.distributed.initialize()`` suffices; explicit arguments
    cover manual launches (`JAX_COORDINATOR` / `NUM_PROCESSES` /
    `PROCESS_ID` env vars work too).  Idempotent: repeated calls are
    no-ops.  Returns this host's process index.

    Axis placement rule for multi-host meshes (see SURVEY §5.8 / the
    scaling-book recipe): keep ``model`` (and ``seq`` for ring attention)
    within a host's ICI domain and spread ``data`` across hosts, so the
    per-step psum over ``data`` is the only collective riding DCN.
    ``create_mesh`` preserves that ordering because jax.devices()
    enumerates local devices contiguously per process.
    """
    import logging
    import os

    # Must not touch any API that initializes the XLA backend before
    # initialize() — jax.process_count() does, after which initialize()
    # raises unconditionally.  Only read distributed-client state here
    # (jax.distributed.is_initialized() where available, else the global
    # state object older jax exposes).
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is None:
        from jax._src import distributed as _dist

        def is_init():
            state = getattr(_dist, "global_state", None)
            return state is not None and state.client is not None

    if is_init():
        return jax.process_index()
    coordinator = coordinator or os.environ.get("JAX_COORDINATOR")
    num_processes = num_processes or int(os.environ.get("NUM_PROCESSES", 0))
    process_id = (process_id if process_id is not None
                  else int(os.environ.get("PROCESS_ID", -1)))
    if coordinator and num_processes > 1 and process_id >= 0:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
    else:
        try:
            jax.distributed.initialize()  # env/metadata-driven (TPU VM)
        except Exception as exc:  # noqa: BLE001 — single-host runs stay single
            logging.getLogger("k8s_llm_monitor_tpu.parallel").debug(
                "jax.distributed.initialize() not applicable (%s); "
                "continuing single-host", exc)
    return jax.process_index()


def shard_map_compat(f, *, mesh, in_specs, out_specs,
                     check_replication=True):
    """``jax.shard_map`` across the 0.8 API rename (check_rep -> check_vma)
    — the single compat point for every shard_map call site in the tree."""
    try:  # jax >= 0.8
        from jax import shard_map as _sm

        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_vma=check_replication)
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map as _sm

        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=check_replication)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    data: int = 1
    seq: int = 1
    model: int = 1

    @property
    def size(self) -> int:
        return self.data * self.seq * self.model


def create_mesh(
    cfg: MeshConfig | None = None,
    devices: list | None = None,
) -> Mesh:
    """Build a ``data × seq × model`` mesh.

    With no config, all devices go on the ``model`` axis (the serving
    default: TP over ICI).  Device order follows jax.devices(), which on TPU
    enumerates chips in ICI-neighbor order, so the innermost (``model``) axis
    gets the fastest links.
    """
    if devices is None:
        devices = jax.devices()
    if cfg is None:
        cfg = MeshConfig(model=len(devices))
    if cfg.size != len(devices):
        raise ValueError(f"mesh {cfg} needs {cfg.size} devices, have {len(devices)}")
    shape = (cfg.data, cfg.seq, cfg.model)
    try:
        # mesh_utils understands the physical ICI topology (2D/3D torus on
        # TPU) and orders devices so the innermost mesh axis lands on
        # nearest-neighbor links; a naive reshape of jax.devices() does NOT
        # guarantee that beyond 1D.
        from jax.experimental import mesh_utils

        arr = mesh_utils.create_device_mesh(shape, devices=devices)
    except (ValueError, AssertionError, NotImplementedError):
        # Virtual CPU meshes and odd single-host layouts fall back to
        # enumeration order, which is fine off-hardware.
        arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, AXES)
