"""Device mesh + GSPMD sharding: DP/TP/SP partitioning over ICI/DCN.

The design follows the scaling-book recipe: pick a mesh, annotate param and
activation shardings with PartitionSpecs, and let XLA insert the collectives
(psum after row-parallel matmuls, all-gathers where layouts change).  No
hand-rolled collective backend — ICI/DCN routing is the XLA runtime's job.
The reference has no distributed compute at all (SURVEY.md §5.8: its fabric is
K8s watch streams + HTTP); this subsystem is a new obligation from the
north-star serving targets (v5e-8 TP, v5p-16 TP for 70B-class).
"""

from k8s_llm_monitor_tpu.parallel.mesh import (
    MeshConfig,
    create_mesh,
    init_multihost,
)
from k8s_llm_monitor_tpu.parallel.sharding import (
    param_partition_specs,
    kv_pages_partition_specs,
    shard_params,
)

__all__ = [
    "MeshConfig",
    "create_mesh",
    "init_multihost",
    "param_partition_specs",
    "kv_pages_partition_specs",
    "shard_params",
]
