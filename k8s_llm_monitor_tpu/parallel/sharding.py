"""Partition specs for the Llama param pytree and KV cache.

Megatron-style tensor parallelism expressed as GSPMD annotations:
  - column-parallel (shard out_features over ``model``): q/k/v, gate/up
  - row-parallel    (shard in_features over ``model``):  o, down
  - vocab-parallel embedding + lm_head
  - norms replicated
XLA inserts the psum after row-parallel matmuls automatically from these
annotations — there is no manual collective in the model code.

KV pages shard the kv-heads axis over ``model`` when the head count divides
the TP degree.  For Llama-3-8B (8 KV heads) on v5e-8 that is exactly one KV
head per chip.  When TP exceeds the KV head count (70B/72B: 8 KV heads on
v5p-16), the kv-heads axis cannot be partitioned 16 ways — those configs
replicate the KV pages across the model axis instead — ``kv_pages_partition_
specs`` infers the choice from the pages' kv-heads axis and the mesh's
``model`` axis size — trading HBM for a spec that compiles; attention
Q-heads remain fully sharded either way.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from k8s_llm_monitor_tpu.models.config import ModelConfig
from k8s_llm_monitor_tpu.models.llama import KVPages

# Rules keyed by (parent, leaf) path suffix.
_COL = {"q", "k", "v", "gate", "up", "lm_head"}   # kernel [in, out] -> shard out
_ROW = {"o", "down"}                               # kernel [in, out] -> shard in


_EXPERT = {"gate_e", "up_e", "down_e"}   # stacked [E, in, out] kernels


def _spec_for_path(path: tuple) -> P:
    keys = [p.key for p in path if isinstance(p, jax.tree_util.DictKey)]
    leaf = keys[-1]
    parent = keys[-2] if len(keys) > 1 else ""
    if parent == "embed" and leaf in ("weight", "weight_q"):
        return P("model", None)                    # vocab-parallel
    if parent == "embed" and leaf == "scale":
        return P("model")                          # per-vocab-row scales
    if parent in _EXPERT:
        # Expert parallelism: the expert axis rides ``model`` — GSPMD
        # inserts the dispatch/combine all-to-alls from this annotation
        # (models/llama.py:_moe_mlp).  Kernels are [E, in, out]; int8
        # scales are [E, out] and shard their expert axis the same way.
        # The router stays replicated (O(H x E), every token needs it).
        if leaf == "scale":
            return P("model", None)
        return P("model", None, None)
    if leaf in ("kernel", "kernel_q"):
        if parent in _COL:
            return P(None, "model")
        if parent in _ROW:
            return P("model", None)
    if leaf in ("bias", "scale"):
        # int8 per-output-channel scales shard with the out dim, exactly
        # like biases: split for column-parallel, replicated for row.
        return P("model") if parent in _COL else P(None)
    # norms and anything else: replicated
    return P(None)


def param_partition_specs(params: Any) -> Any:
    """PartitionSpec pytree matching a llama param pytree."""
    return jax.tree_util.tree_map_with_path(lambda p, _: _spec_for_path(p), params)


def kv_pages_partition_specs(
    pages: KVPages, mesh: Mesh | None, num_kv_heads: int,
) -> KVPages:
    """[num_blocks, block_size, kv_heads*head_dim] -> shard the fused lane
    dim on kv-head boundaries.

    The fused layout is kv-head-major, so splitting the lane dim ``tp`` ways
    is exactly a kv-head split when ``tp`` divides ``num_kv_heads``.  When
    TP exceeds the kv-head count (8-KV-head 70B on v5p-16) a lane split
    would cut heads mid-``head_dim`` (every q·k dot would need a psum) —
    replicate the pages instead, trading HBM for locality.
    """
    tp = mesh.shape["model"] if mesh is not None else 1
    if mesh is not None and (tp > num_kv_heads or num_kv_heads % tp != 0):
        spec = P(None, None, None)
    else:
        spec = P(None, None, "model")
    return KVPages(
        k=[spec for _ in pages.k],
        v=[spec for _ in pages.v],
    )


def shard_params(params: Any, mesh: Mesh) -> Any:
    """Device-put params with TP sharding over ``mesh``."""
    specs = param_partition_specs(params)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs
    )


def batch_spec() -> P:
    """Activation batch sharding: batch over ``data``."""
    return P("data")
