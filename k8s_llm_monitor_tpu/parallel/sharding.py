"""Partition specs for the Llama param pytree and KV cache.

Megatron-style tensor parallelism expressed as GSPMD annotations:
  - column-parallel (shard out_features over ``model``): q/k/v, gate/up
  - row-parallel    (shard in_features over ``model``):  o, down
  - vocab-parallel embedding + lm_head
  - norms replicated
XLA inserts the psum after row-parallel matmuls automatically from these
annotations — there is no manual collective in the model code.

The layout is factored two ways (SNIPPETS.md [2]/[3]):

  * ``SpecLayout`` — a frozen dataclass with one method per parameter
    *role* (embedding, column/row projection, expert stack, norm).  It is
    the single place the axis names live; serving, tests, and the bench
    all derive their ``NamedSharding``s from it.
  * ``partition_rules()`` — the role methods bound to param-path regexes
    (the ``match_partition_rules`` idiom), so a checkpoint pytree maps to
    specs by name without the model code knowing about meshes.

KV pages shard the kv-heads axis over ``model`` when the head count divides
the TP degree.  For Llama-3-8B (8 KV heads) on v5e-8 that is exactly one KV
head per chip.  When TP exceeds the KV head count (70B/72B: 8 KV heads on
v5p-16), the kv-heads axis cannot be partitioned 16 ways — those configs
replicate the KV pages across the model axis instead (``SpecLayout.
kv_pages`` infers the choice) — trading HBM for a spec that compiles;
attention Q-heads remain fully sharded either way.

Page tables and context lengths are NEVER sharded: block ids are global
(serving/kv_cache.py allocates them host-side), every chip indexes the
same table rows and reads its own head-slice of each page.  That is the
invariant that lets ``BlockAllocator``/``PrefixCache`` stay mesh-agnostic,
and what lets the Pallas paged kernels — decode and flash prefill
(ops/attention.py ``make_tp_paged_attention`` / ``make_tp_flash_prefill``)
— run per-shard under ``shard_map`` with no collective: each shard walks
the same block table over its own kv-head slice of the pool.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from k8s_llm_monitor_tpu.models.config import ModelConfig
from k8s_llm_monitor_tpu.models.llama import KVPages


@dataclasses.dataclass(frozen=True)
class SpecLayout:
    """Axis layout for tensor-parallel serving, one method per param role.

    Frozen so a layout can key caches and be shared across engine builds;
    instantiate with different axis names for exotic meshes (tests use the
    default ``("data", "seq", "model")`` convention from parallel/mesh.py).
    """

    data_axis: str = "data"
    seq_axis: str = "seq"
    model_axis: str = "model"

    # -- parameter roles --------------------------------------------------
    def embedding(self) -> P:
        """Vocab-parallel embedding / lm_head tables: [V, H], shard V."""
        return P(self.model_axis, None)

    def embedding_scale(self) -> P:
        """Per-vocab-row int8 scales ride the sharded vocab axis."""
        return P(self.model_axis)

    def column_kernel(self) -> P:
        """q/k/v/gate/up/lm_head [in, out]: shard out_features (heads /
        MLP hidden) over ``model``."""
        return P(None, self.model_axis)

    def row_kernel(self) -> P:
        """o/down [in, out]: shard in_features; XLA inserts the psum."""
        return P(self.model_axis, None)

    def column_bias(self) -> P:
        """Biases and per-out-channel int8 scales of column-parallel
        projections split with the out dim."""
        return P(self.model_axis)

    def expert_kernel(self) -> P:
        """Stacked MoE kernels [E, in, out]: expert axis rides ``model``
        (GSPMD inserts dispatch/combine all-to-alls)."""
        return P(self.model_axis, None, None)

    def expert_scale(self) -> P:
        """MoE int8 scales [E, out] shard their expert axis the same."""
        return P(self.model_axis, None)

    def layer_norm(self) -> P:
        """Norms (and the MoE router) are O(H): replicate."""
        return P(None)

    def replicated(self) -> P:
        return P(None)

    # -- serving-state roles ----------------------------------------------
    def kv_pages(self, num_kv_heads: int, tp: int) -> P:
        """[num_blocks, block_size, kv_heads*head_dim]: shard the fused
        lane dim on kv-head boundaries when ``tp`` divides the head count
        (the layout is kv-head-major, so a ``tp``-way lane split IS a head
        split); otherwise replicate — a lane split that cuts a head
        mid-``head_dim`` would psum every q·k dot."""
        if tp > 1 and (tp > num_kv_heads or num_kv_heads % tp != 0):
            return P(None, None, None)
        if tp <= 1:
            return P(None, None, None)
        return P(None, None, self.model_axis)

    def kv_scales(self, num_kv_heads: int, tp: int) -> P:
        """Quantized-KV scale arrays [num_blocks, block_size, kv_heads]:
        the kv-heads axis shards exactly when the pages' fused lane dim
        does (same divisibility condition), so each chip holds the scales
        for precisely its own head slice; otherwise replicate."""
        if self.kv_pages(num_kv_heads, tp) == P(None, None, None):
            return P(None, None, None)
        return P(None, None, self.model_axis)

    def page_table(self) -> P:
        """Block tables / context lengths: replicated.  Page ids are
        GLOBAL — each chip reads the same table and its own head-slice of
        every page, so the host allocator needs no mesh awareness."""
        return P(None, None)

    def prefill_tokens(self) -> P:
        """Seq-parallel prefill: token batches [P, bucket] shard their
        sequence axis when the mesh has a nontrivial ``seq`` degree."""
        return P(None, self.seq_axis)

    def batch(self) -> P:
        """Activation batch sharding: batch over ``data``."""
        return P(self.data_axis)


#: The default layout every serving entry point derives its shardings from.
DEFAULT_LAYOUT = SpecLayout()


def partition_rules(
    layout: SpecLayout = DEFAULT_LAYOUT,
) -> tuple[tuple[str, P], ...]:
    """(path-regex, spec) pairs, first match wins; paths join the pytree's
    dict keys with ``/`` (list indices dropped), e.g. ``layers/q/kernel``.
    Expert rules precede column rules so ``up_e`` never matches ``up``."""
    return (
        (r"(^|/)embed/(weight|weight_q)$", layout.embedding()),
        (r"(^|/)embed/scale$", layout.embedding_scale()),
        (r"(^|/)(gate_e|up_e|down_e)/scale$", layout.expert_scale()),
        (r"(^|/)(gate_e|up_e|down_e)/", layout.expert_kernel()),
        (r"(^|/)(q|k|v|gate|up|lm_head)/(kernel|kernel_q)$",
         layout.column_kernel()),
        (r"(^|/)(o|down)/(kernel|kernel_q)$", layout.row_kernel()),
        (r"(^|/)(q|k|v|gate|up|lm_head)/(bias|scale)$",
         layout.column_bias()),
        (r".*norm", layout.layer_norm()),
    )


def _param_path_name(path: tuple) -> str:
    return "/".join(
        str(p.key) for p in path if isinstance(p, jax.tree_util.DictKey))


def match_partition_rules(rules, params: Any) -> Any:
    """Map a param pytree to PartitionSpecs by path regex (SNIPPETS.md
    [2] idiom); unmatched leaves replicate."""
    def spec_for(path, _leaf) -> P:
        name = _param_path_name(path)
        for pattern, spec in rules:
            if re.search(pattern, name):
                return spec
        return P(None)

    return jax.tree_util.tree_map_with_path(spec_for, params)


def param_partition_specs(
    params: Any, layout: SpecLayout = DEFAULT_LAYOUT,
) -> Any:
    """PartitionSpec pytree matching a llama param pytree."""
    return match_partition_rules(partition_rules(layout), params)


def param_named_shardings(
    params: Any, mesh: Mesh, layout: SpecLayout = DEFAULT_LAYOUT,
) -> Any:
    """The ``SpecLayout``-derived ``NamedSharding`` pytree the engine
    device-puts weights with."""
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s), param_partition_specs(params, layout),
        is_leaf=lambda x: isinstance(x, P))


def kv_pages_partition_specs(
    pages: KVPages, mesh: Mesh | None, num_kv_heads: int,
    layout: SpecLayout = DEFAULT_LAYOUT,
) -> KVPages:
    """[num_blocks, block_size, kv_heads*head_dim] -> shard the fused lane
    dim on kv-head boundaries (see ``SpecLayout.kv_pages``)."""
    tp = mesh.shape[layout.model_axis] if mesh is not None else 1
    spec = layout.kv_pages(num_kv_heads, tp)
    sspec = layout.kv_scales(num_kv_heads, tp)
    return KVPages(
        k=[spec for _ in pages.k],
        v=[spec for _ in pages.v],
        k_scale=[sspec for _ in pages.k_scale],
        v_scale=[sspec for _ in pages.v_scale],
    )


def shard_params(
    params: Any, mesh: Mesh, layout: SpecLayout = DEFAULT_LAYOUT,
) -> Any:
    """Device-put params with TP sharding over ``mesh``."""
    return jax.tree.map(
        jax.device_put, params, param_named_shardings(params, mesh, layout))


def batch_spec() -> P:
    """Activation batch sharding: batch over ``data``."""
    return DEFAULT_LAYOUT.batch()
