"""Ring attention: causal attention sequence-sharded over the ``seq`` mesh
axis (SURVEY §5.7 / §7 step 7 — long-context beyond one chip's HBM).

Each device holds one sequence shard of Q, K and V.  K/V shards rotate
around the ring with ``jax.lax.ppermute`` (nearest-neighbor ICI traffic, no
all-gather) while every device folds each visiting chunk into a running
online-softmax accumulator for its local queries — the memory footprint per
device stays O(S/n) regardless of total sequence length, and the ppermute
for chunk t+1 overlaps the matmuls for chunk t in XLA's schedule.

Composes with the other mesh axes: inside ``shard_map`` the block math is
purely local over ``data`` (batch) and ``model`` (heads), so the same
function runs on any data x seq x model mesh.  Drop-in for
``ops.attention.causal_attention`` via ``llama.forward_full(attn_fn=...)``;
``training.make_train_step(..., mesh=...)`` selects it when the mesh has a
nontrivial ``seq`` axis and config asks for it.

The reference has no model execution at all (its "long context" concern is
prompt-size config, reference internal/config/config.go:94); this is part of
the new TPU serving/training obligation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from k8s_llm_monitor_tpu.ops.attention import NEG_INF, _repeat_kv

from jax.sharding import Mesh, PartitionSpec as P

from k8s_llm_monitor_tpu.parallel.mesh import shard_map_compat as _shard_map


def _block_update(q, k, v, q_pos, kv_pos, kv_len, m, l, acc):
    """Fold one K/V chunk into the online-softmax state.

    q: [b, sq, h, d]; k/v: [b, sk, kvh, d] (GQA: kvh divides h); q_pos:
    [b, sq]; kv_pos: [sk]; kv_len: [b] or None; m/l: [b, h, sq, 1];
    acc: [b, sq, h, d] (f32).
    """
    k = _repeat_kv(k, q.shape[2] // k.shape[2])
    v = _repeat_kv(v, q.shape[2] // v.shape[2])
    scale = 1.0 / (q.shape[-1] ** 0.5)
    logits = jnp.einsum("bshd,bthd->bhst", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale       # [b, h, sq, sk]
    causal = q_pos[:, :, None] >= kv_pos[None, None, :]       # [b, sq, sk]
    if kv_len is not None:
        causal = causal & (kv_pos[None, None, :] < kv_len[:, None, None])
    logits = jnp.where(causal[:, None, :, :], logits, NEG_INF)

    m_new = jnp.maximum(m, jnp.max(logits, axis=-1, keepdims=True))
    # Fully-masked-so-far rows keep m == NEG_INF; exponentiate against 0 so
    # they contribute exact zeros instead of NaNs.
    m_safe = jnp.where(m_new <= NEG_INF, 0.0, m_new)
    p = jnp.exp(logits - m_safe)                              # [b, h, sq, sk]
    alpha = jnp.exp(jnp.where(m <= NEG_INF, NEG_INF, m - m_safe))
    l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
    pv = jnp.einsum("bhst,bthd->bshd", p, v.astype(jnp.float32))
    acc_new = alpha.transpose(0, 2, 1, 3) * acc + pv          # [b, sq, h, d]
    return m_new, l_new, acc_new


def make_ring_attention(mesh: Mesh, axis: str = "seq"):
    """Build a ``causal_attention``-compatible fn that rings over ``axis``.

    Returned signature: ``fn(q, k, v, *, q_positions=None, kv_len=None)``
    with q/k/v ``[B, S, H, D]`` where S is the *global* sequence (sharded
    over ``axis`` by GSPMD) and H may be sharded over ``model``.
    """
    n = mesh.shape[axis]

    def local(q, k, v, q_pos, kv_len):
        # Shapes here are per-device shards.
        b, s_loc, h, d = q.shape
        idx = jax.lax.axis_index(axis)

        m = jnp.full((b, h, s_loc, 1), NEG_INF, jnp.float32)
        l = jnp.zeros((b, h, s_loc, 1), jnp.float32)
        acc = jnp.zeros((b, s_loc, h, d), jnp.float32)

        kv = (k, v)
        for step in range(n):
            src = (idx - step) % n                 # owner of the visiting kv
            kv_pos = src * k.shape[1] + jnp.arange(k.shape[1],
                                                   dtype=jnp.int32)
            m, l, acc = _block_update(q, kv[0], kv[1], q_pos, kv_pos,
                                      kv_len, m, l, acc)
            if step + 1 < n:
                kv = jax.lax.ppermute(
                    kv, axis, perm=[(i, (i + 1) % n) for i in range(n)])

        out = acc / jnp.maximum(l.transpose(0, 2, 1, 3), 1e-30)
        return out.astype(q.dtype)

    def ring_attention(q, k, v, *, q_positions=None, kv_len=None):
        if n == 1:
            from k8s_llm_monitor_tpu.ops.attention import causal_attention

            return causal_attention(q, k, v, q_positions=q_positions,
                                    kv_len=kv_len)
        B, S = q.shape[0], q.shape[1]
        T = k.shape[1]
        if q_positions is None:
            q_positions = jnp.broadcast_to(
                jnp.arange(S, dtype=jnp.int32)[None, :] + (T - S), (B, S))
        if kv_len is None:
            kv_len = jnp.full((B,), T, jnp.int32)
        qkv_spec = P("data", axis, "model", None)
        fn = _shard_map(
            local, mesh=mesh,
            in_specs=(qkv_spec, qkv_spec, qkv_spec,
                      P("data", axis), P("data")),
            out_specs=qkv_spec,
        )
        return fn(q, k, v, q_positions, kv_len)

    return ring_attention
