"""Latency-hiding tensor-parallel decode: hand-staged collective schedule.

The GSPMD decode path annotates o/down projections row-parallel and lets
XLA insert a blocking ring all-reduce after each one — 2 per layer, each
serializing ``2*(tp-1)/tp`` of a [B, H] activation over ICI before the
next matmul may start (the ``engine_decode_collective_share`` model,
PR 7).  This module replaces that schedule for the per-layer decode loop
with an explicit ``shard_map`` program that keeps the residual stream
REDUCE-SCATTERED between sub-blocks:

    per layer (all shard-local unless marked):
      x_full   = all_gather(x_scat)                   <- AG half
      h        = rms_norm(x_full, input_norm)
      q,k,v    = column-parallel projections (local head slice) + rope
      pages    = scatter k/v into the LOCAL kv-head page slice
      attn     = paged attention over local heads (no collective — pages
                 shard on kv-head boundaries, parallel/sharding.py)
      o_part   = row-parallel o partial
      o_scat   = psum_scatter(o_part)                 <- RS half
      x_scat  += o_scat
      h        = rms_norm(all_gather(x_scat), post_norm)   <- AG half
      gate,up  = column-parallel (local I slice); act fuse
      d_part   = row-parallel down partial
      x_scat  += psum_scatter(d_part)                 <- RS half
    final: all_gather(x_scat) -> replicated residual for the unembed

Why this hides wire time: a blocking all-reduce is one fused
collective-permute chain the scheduler cannot split, so the weight
streaming (HBM->VMEM) of the NEXT column-parallel matmul — which does not
depend on the in-flight activation — waits behind it.  Decomposed into
reduce-scatter + all-gather, each half lowers to an async
collective-start/done pair, and XLA's latency-hiding scheduler hoists the
data-independent weight prefetch (and the page-scatter DMAs) between
start and done.  Decode is weight-streaming bound, so that window is
normally larger than the wire time (``estimate_hidden_share``'s byte
model: v5e-8 / 8B streams ~18 MB/layer against ~1 MB/layer of wire).

Exactness vs the GSPMD reference (the parity tests in
tests/test_overlap.py prove byte-identical greedy tokens):

  * ``all_gather`` is a pure concatenation of a consistent scatter;
    ``dynamic_slice`` of the replicated residual is its inverse.
  * Chunked residual adds commute with slicing elementwise.
  * Column-parallel projections run the SAME shard-local matmul GSPMD
    partitions to (params arrive pre-sharded; per-out-channel int8
    scales shard with the out dim, so ``_linear`` applies unchanged).
  * Row-parallel reductions go through
    ``models/llama.py:row_parallel_partial``: W8A8 combines the global
    per-token amax with ``pmax`` (max is order-independent) and reduces
    the raw int32 partials (integer addition is associative) before the
    float scales apply — the same reduce-then-scale order GSPMD uses.
  * Per-shard paged attention is per-head independent; GQA groups align
    with the shard cuts when ``tp | num_kv_heads`` (the support gate).

Embed lookup and the unembed stay OUTSIDE the shard_map under plain
GSPMD (vocab-parallel, replicated result) — they run once per step, not
per layer, and keeping them on the reference path removes two parity
surfaces for free.

Flag-selectable exactly like the PR 1 decode-path oracle:
``EngineConfig.tp_overlap`` / ``K8SLLM_TP_OVERLAP`` ("auto" | "on" |
"off"), with the GSPMD program kept as the always-available correctness
reference.
"""

from __future__ import annotations

import functools

import jax
from jax.sharding import PartitionSpec as P

from k8s_llm_monitor_tpu.models.config import ModelConfig
from k8s_llm_monitor_tpu.models.llama import (
    KVPages,
    _attn_extras,
    _embed_lookup,
    _linear,
    _mlp_act,
    _scatter_pages,
    _scatter_pages_quant,
    _unembed,
    row_parallel_partial,
)
from k8s_llm_monitor_tpu.ops.attention import (
    _pallas_geometry_ok,
    paged_decode_attention,
    paged_decode_attention_quant,
)
from k8s_llm_monitor_tpu.ops.norms import rms_norm
from k8s_llm_monitor_tpu.ops.rope import apply_rope, rope_angles
from k8s_llm_monitor_tpu.parallel.mesh import shard_map_compat
from k8s_llm_monitor_tpu.parallel.sharding import (
    kv_pages_partition_specs,
    param_partition_specs,
)

#: The TP axis every collective in the staged schedule runs over.
MODEL_AXIS = "model"


def overlap_supported(cfg: ModelConfig, mesh, params=None) -> str:
    """"" when the staged overlap schedule can serve ``(cfg, mesh)``;
    otherwise a human-readable reason.  The engine logs the reason and
    keeps the GSPMD program ("auto"), or raises it ("on") — never a
    silent numerics change.

    The gates mirror the regimes where the hand schedule would NOT be a
    pure re-staging of the GSPMD program:
      * no mesh / model axis 1 — nothing to overlap;
      * TP not dividing the (kv-)head count — pages replicate instead of
        head-sharding (SpecLayout.kv_pages), so per-shard attention is no
        longer collective-free;
      * MoE — the expert all-to-alls follow a different schedule
        entirely (models/llama.py:_moe_mlp_dropless);
      * sandwich norms — post_attn_norm consumes the FULL o projection
        before the residual add, so the o reduce cannot stay scattered;
      * a bias on a row-parallel projection — it must be added exactly
        once, after the reduce (no supported checkpoint carries one).
    """
    if mesh is None:
        return "no mesh"
    tp = mesh.shape.get(MODEL_AXIS, 1)
    if tp <= 1:
        return "model axis is 1"
    if (tp > cfg.num_kv_heads or cfg.num_kv_heads % tp != 0
            or cfg.num_heads % tp != 0):
        return (f"TP={tp} does not divide {cfg.num_heads} heads / "
                f"{cfg.num_kv_heads} KV heads (pages replicate)")
    if cfg.hidden_size % tp or cfg.intermediate_size % tp:
        return (f"TP={tp} does not divide hidden {cfg.hidden_size} / "
                f"intermediate {cfg.intermediate_size} (uneven scatter)")
    if cfg.num_experts > 0:
        return "MoE layers route through expert all-to-alls"
    if cfg.sandwich_norms:
        return "sandwich norms consume the full o projection pre-residual"
    if params is not None:
        layer0 = params["layers"][0]
        if "bias" in layer0["o"] or "bias" in layer0["down"]:
            return "row-parallel projection carries a bias"
    return ""


def _per_shard_attn(cfg: ModelConfig, tp: int, attn_path: str):
    """Per-shard paged decode attention matching the engine's resolved
    decode path, so overlap-on vs overlap-off differ ONLY in collective
    staging: "gather" keeps the XLA reference; anything else takes the
    Pallas kernel per shard (interpreter off-TPU), exactly what
    ops/attention.py:make_tp_paged_attention wraps for the GSPMD path."""
    if attn_path != "gather" and not cfg.has_attn_extras:
        interpret = jax.default_backend() != "tpu"
        if interpret or _pallas_geometry_ok(cfg, tp):
            try:
                from k8s_llm_monitor_tpu.ops.pallas_attention import (
                    paged_decode_attention_pallas,
                )

                return functools.partial(paged_decode_attention_pallas,
                                         interpret=interpret)
            except Exception:  # pragma: no cover - lowering unavailable
                pass
    return paged_decode_attention


def make_overlap_decode_step(mesh, cfg: ModelConfig, params, pages: KVPages,
                             *, attn_path: str = "gather"):
    """Build the staged decode step.

    Returns ``step(params, tokens, context_lens, pages, tables) ->
    (logits [B, V] float32, updated KVPages)`` — the exact calling
    convention of ``llama.decode_step`` minus ``attn_impl`` (the per-shard
    attention is resolved here from ``attn_path``), so the engine's
    ``_step_core`` swaps it in without touching the scan programs.

    ``params``/``pages`` are used for spec derivation only (tree
    structure); the returned step traces against whatever arrays the
    jitted caller passes.
    """
    tp = mesh.shape[MODEL_AXIS]
    quant = pages.quantized
    attn_fn = _per_shard_attn(cfg, tp, attn_path)
    aq = cfg.act_quant
    uo = cfg.rmsnorm_unit_offset
    eps = cfg.rms_norm_eps
    Hc = cfg.hidden_size // tp
    n_head_local = cfg.num_heads // tp
    n_kv_local = cfg.num_kv_heads // tp
    D = cfg.head_dim_

    layer_specs = param_partition_specs(params)["layers"]
    kv_specs = kv_pages_partition_specs(pages, mesh,
                                        num_kv_heads=cfg.num_kv_heads)
    rep2, rep3 = P(None, None), P(None, None, None)

    def _layers(layers, x_full, cos, sin, positions, active, new_lens,
                k_pages, v_pages, k_scales, v_scales, tables):
        B = x_full.shape[0]
        idx = jax.lax.axis_index(MODEL_AXIS)
        # Residual enters replicated (embed runs under GSPMD outside);
        # keep it reduce-scattered from here on.
        x_scat = jax.lax.dynamic_slice_in_dim(x_full, idx * Hc, Hc, axis=2)
        new_k, new_v, new_ks, new_vs = [], [], [], []
        for li, layer in enumerate(layers):
            x_full = jax.lax.all_gather(x_scat, MODEL_AXIS, axis=2,
                                        tiled=True)
            h = rms_norm(x_full, layer["input_norm"], eps, uo)
            # Column-parallel projections: params arrive as their local
            # shard, so _linear computes exactly the per-device matmul
            # GSPMD partitions to (out-dim int8 scales shard along).
            q = _linear(layer["q"], h, aq).reshape(B, 1, n_head_local, D)
            k = _linear(layer["k"], h, aq).reshape(B, 1, n_kv_local, D)
            v = _linear(layer["v"], h, aq).reshape(B, 1, n_kv_local, D)
            q = apply_rope(q, cos, sin)
            k = apply_rope(k, cos, sin)
            if quant:
                pk, psk = _scatter_pages_quant(
                    k_pages[li], k_scales[li], k, tables, positions, active)
                pv, psv = _scatter_pages_quant(
                    v_pages[li], v_scales[li], v, tables, positions, active)
                new_ks.append(psk)
                new_vs.append(psv)
                attn = paged_decode_attention_quant(
                    q, pk, pv, psk, psv, tables, new_lens,
                    **_attn_extras(cfg, li))
            else:
                pk = _scatter_pages(k_pages[li], k, tables, positions,
                                    active)
                pv = _scatter_pages(v_pages[li], v, tables, positions,
                                    active)
                attn = attn_fn(q, pk, pv, tables, new_lens,
                               **_attn_extras(cfg, li))
            new_k.append(pk)
            new_v.append(pv)
            part, fin = row_parallel_partial(
                layer["o"], attn.reshape(B, 1, -1), aq, MODEL_AXIS)
            x_scat = x_scat + fin(jax.lax.psum_scatter(
                part, MODEL_AXIS, scatter_dimension=2, tiled=True))
            h = rms_norm(
                jax.lax.all_gather(x_scat, MODEL_AXIS, axis=2, tiled=True),
                layer["post_norm"], eps, uo)
            gate = _linear(layer["gate"], h, aq)
            up = _linear(layer["up"], h, aq)
            part, fin = row_parallel_partial(
                layer["down"], _mlp_act(cfg, gate) * up, aq, MODEL_AXIS)
            x_scat = x_scat + fin(jax.lax.psum_scatter(
                part, MODEL_AXIS, scatter_dimension=2, tiled=True))
        x_full = jax.lax.all_gather(x_scat, MODEL_AXIS, axis=2, tiled=True)
        return x_full, new_k, new_v, new_ks, new_vs

    sharded_layers = shard_map_compat(
        _layers, mesh=mesh,
        in_specs=(layer_specs, rep3, rep3, rep3, rep2, rep2, P(None),
                  kv_specs.k, kv_specs.v, list(kv_specs.k_scale),
                  list(kv_specs.v_scale), rep2),
        out_specs=(rep3, kv_specs.k, kv_specs.v, list(kv_specs.k_scale),
                   list(kv_specs.v_scale)),
        check_replication=False)

    def step(params, tokens, context_lens, pages, tables):
        positions = context_lens[:, None]
        active = (context_lens > 0)[:, None]
        cos, sin = rope_angles(positions, cfg.head_dim_, cfg.rope_theta,
                               scaling=cfg.rope_scaling)
        x = _embed_lookup(params, cfg, tokens)[:, None, :]
        x, new_k, new_v, new_ks, new_vs = sharded_layers(
            params["layers"], x, cos, sin,
            positions, active, context_lens + 1,
            pages.k, pages.v, list(pages.k_scale), list(pages.v_scale),
            tables)
        logits = _unembed(params, cfg, x)[:, 0, :]
        # Container canon (KVPages defaults / llama.prefill /
        # llama.decode_step): unquantized pools carry EMPTY TUPLES for
        # the scale leaves; quantized pools carry lists (init_kv_pages'
        # quant path).  Deviating flips the treedef and silently forces
        # a fresh jit variant of every downstream program that takes
        # pages (the traceguard overlap gate catches this).
        return logits, KVPages(k=new_k, v=new_v,
                               k_scale=new_ks if quant else (),
                               v_scale=new_vs if quant else ())

    return step
