"""Tokenizers.

Two implementations behind one duck-typed interface (encode/decode/bos/eos):
  - ``HFTokenizer``: wraps a local HuggingFace tokenizer directory for real
    Llama/Qwen checkpoints.
  - ``ByteTokenizer``: dependency-free UTF-8 byte fallback used by tests,
    benchmarks, and any deployment without downloaded tokenizer files.
    ids: 0=pad, 1=bos, 2=eos, bytes at 3..258.
"""

from __future__ import annotations


class ByteTokenizer:
    PAD, BOS, EOS = 0, 1, 2
    OFFSET = 3

    vocab_size = 259

    @property
    def bos_id(self) -> int:
        return self.BOS

    @property
    def eos_id(self) -> int:
        return self.EOS

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        ids = [b + self.OFFSET for b in text.encode("utf-8")]
        return ([self.BOS] if add_bos else []) + ids

    def decode(self, ids: list[int]) -> str:
        # Ids beyond the byte range can appear when a model's vocab is larger
        # than 259 (e.g. random-init dev weights); skip them like specials.
        data = bytes(
            i - self.OFFSET for i in ids if self.OFFSET <= i < self.OFFSET + 256
        )
        return data.decode("utf-8", errors="replace")


class HFTokenizer:
    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)

    @property
    def bos_id(self) -> int:
        return self._tok.bos_token_id

    @property
    def eos_id(self) -> int:
        return self._tok.eos_token_id

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        ids = self._tok.encode(text, add_special_tokens=False)
        if add_bos and self.bos_id is not None:
            ids = [self.bos_id] + ids
        return ids

    def decode(self, ids: list[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)


def load_tokenizer(path: str | None):
    if path:
        return HFTokenizer(path)
    return ByteTokenizer()
