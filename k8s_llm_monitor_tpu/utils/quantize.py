"""Weight-only int8 quantization for the decoder LM.

Why weight-only: TPU decode is HBM-bandwidth-bound — every decode step
streams the full weight set through the MXU for one token per lane.  Halving
weight bytes (bf16 -> int8 + per-channel scales) both halves that traffic and
makes the Llama-3-8B target (~8 GB quantized) fit a 16 GB v5e chip next to
the paged KV pool, which bf16 weights (~16 GB) cannot.  Activations and the
KV cache stay bf16: their traffic is small next to weights at serving batch
sizes, and keeping them wide preserves accuracy.

Scheme: symmetric per-output-channel int8.

    w_q[i, o]  = round(w[i, o] / scale[o]),  scale[o] = max_i |w[i, o]| / 127

The forward pass never materializes a dequantized weight matrix: because the
scale is per *output* channel it commutes with the contraction,

    x @ (w_q * scale) == (x @ w_q) * scale

so ``models/llama.py:_linear`` runs the matmul on the int8 kernel (upcast to
the activation dtype on the fly — a cast XLA fuses into the MXU operand
read, so HBM still only moves int8 bytes) and applies the scale to the
[.., out] result.  int8 values are exact in bfloat16 (|v| <= 127 < 2^8), so
the upcast loses nothing.

Embedding / unembedding use the same scheme per vocab row (the embed matrix
is its own transpose-partner when tied).

Quantized pytree leaves replace their bf16 counterparts in place:

    linear:  {"kernel": [in, out] bf16}        -> {"kernel_q": int8, "scale": f32 [out]}
    embed:   {"weight": [vocab, H] bf16}       -> {"weight_q": int8, "scale": f32 [vocab]}

``bias`` entries (Qwen2 QKV) stay in the activation dtype.

Capability context: the reference's LLM layer is config-only (reference
internal/config/config.go:141-145); serving the real Llama-3-8B target on a
single 16 GB chip is a north-star obligation (BASELINE.md configs #2/#4),
and this module is what makes the geometry fit.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from k8s_llm_monitor_tpu.models.config import ModelConfig

Params = dict[str, Any]

_EPS = 1e-12


def quantize_array(w: np.ndarray, axis: int = 0) -> tuple[np.ndarray, np.ndarray]:
    """Symmetric int8 quantization of ``w`` with scales over ``axis``.

    Host-side numpy (streaming checkpoint load must not touch the device).
    Returns (w_q int8 same shape, scale float32 with ``axis`` reduced).
    """
    w = np.asarray(w, np.float32)
    amax = np.max(np.abs(w), axis=axis)
    scale = np.maximum(amax / 127.0, _EPS).astype(np.float32)
    w_q = np.rint(w / np.expand_dims(scale, axis)).astype(np.int8)
    return w_q, scale


def quantize_linear(p: Params) -> Params:
    """{"kernel": [in, out], ...} -> {"kernel_q", "scale", ...}."""
    w_q, scale = quantize_array(np.asarray(p["kernel"]), axis=0)
    out: Params = {"kernel_q": jnp.asarray(w_q), "scale": jnp.asarray(scale)}
    if "bias" in p:
        out["bias"] = p["bias"]
    return out


def quantize_embed(p: Params) -> Params:
    """{"weight": [vocab, H]} -> {"weight_q", "scale"} (per-row scales)."""
    w_q, scale = quantize_array(np.asarray(p["weight"]), axis=1)
    return {"weight_q": jnp.asarray(w_q), "scale": jnp.asarray(scale)}


def quantize_expert_stack(p: Params) -> Params:
    """{"kernel": [E, in, out]} -> {"kernel_q" int8, "scale" [E, out]}.

    Per-expert per-output-channel symmetric int8 — the exact analogue of
    quantize_linear with the expert axis carried through; dequant stays a
    per-(expert, out) multiply on the einsum result (models/llama.py).
    """
    w_q, scale = quantize_array(np.asarray(p["kernel"]), axis=1)
    return {"kernel_q": jnp.asarray(w_q), "scale": jnp.asarray(scale)}


def quantize_params(params: Params) -> Params:
    """Quantize a full llama param pytree (see models/llama.py layout).

    Norm vectors stay in their original dtype — they are O(hidden) bytes and
    scale-sensitive.
    """
    layers = []
    for layer in params["layers"]:
        ql: Params = {
            "input_norm": layer["input_norm"],
            "post_norm": layer["post_norm"],
        }
        for name in ("post_attn_norm", "post_mlp_norm"):  # Gemma sandwich
            if name in layer:
                ql[name] = layer[name]
        for name in ("q", "k", "v", "o"):
            ql[name] = quantize_linear(layer[name])
        if "router" in layer:
            # MoE layers: the router stays bf16 (tiny, routing-decision
            # sensitive); expert stacks quantize per-expert-per-channel.
            ql["router"] = layer["router"]
            for name in ("gate_e", "up_e", "down_e"):
                ql[name] = quantize_expert_stack(layer[name])
        else:
            for name in ("gate", "up", "down"):
                ql[name] = quantize_linear(layer[name])
        layers.append(ql)
    out: Params = {
        "embed": quantize_embed(params["embed"]),
        "layers": layers,
        "final_norm": params["final_norm"],
    }
    if "lm_head" in params:
        out["lm_head"] = quantize_linear(params["lm_head"])
    return out


# ---------------------------------------------------------------------------
# Direct quantized random init (benchmarks)
# ---------------------------------------------------------------------------


def init_params_quantized(rng: jax.Array, cfg: ModelConfig) -> Params:
    """Random-init parameters directly in int8 + scales.

    The 8B-class bench configs cannot materialize bf16 weights first (16 GB
    on a 16 GB chip) — this builds each tensor already quantized, with scales
    matching the magnitude ``models/llama.py:init_params`` would produce
    (kernel std in**-0.5, embed std 0.02), so activations have realistic
    dynamic range.
    """
    dtype = jnp.dtype(cfg.dtype)
    H, D = cfg.hidden_size, cfg.head_dim_
    nH, nKV, I = cfg.num_heads, cfg.num_kv_heads, cfg.intermediate_size

    def qdense(key, in_f, out_f, bias):
        # ~N(0, in**-0.5) truncated at 3 sigma -> amax ~= 3 * std.
        w_q = jax.random.randint(key, (in_f, out_f), -127, 128, jnp.int8)
        scale = jnp.full((out_f,), 3.0 * (in_f ** -0.5) / 127.0, jnp.float32)
        p: Params = {"kernel_q": w_q, "scale": scale}
        if bias:
            p["bias"] = jnp.zeros((out_f,), dtype)
        return p

    if cfg.num_experts > 0:
        # MoE: bf16 init then quantize (the direct-int8 trick below skips
        # the bf16 materialization, but expert stacks need the real value
        # distribution for per-expert scales; the transient bf16 peak is
        # fine at dev/random-init scales — real MoE checkpoints stream
        # through convert_hf_state_dict(quantize=True) tensor-by-tensor).
        from k8s_llm_monitor_tpu.models.llama import init_params

        return quantize_params(init_params(rng, cfg))

    keys = jax.random.split(rng, 2 + cfg.num_layers)
    layers = []
    for i in range(cfg.num_layers):
        lk = jax.random.split(keys[2 + i], 7)
        layers.append(
            {
                "input_norm": jnp.ones((H,), dtype),
                "post_norm": jnp.ones((H,), dtype),
                "q": qdense(lk[0], H, nH * D, cfg.qkv_bias),
                "k": qdense(lk[1], H, nKV * D, cfg.qkv_bias),
                "v": qdense(lk[2], H, nKV * D, cfg.qkv_bias),
                "o": qdense(lk[3], nH * D, H, False),
                "gate": qdense(lk[4], H, I, False),
                "up": qdense(lk[5], H, I, False),
                "down": qdense(lk[6], I, H, False),
            }
        )
    params: Params = {
        "embed": {
            "weight_q": jax.random.randint(
                keys[0], (cfg.vocab_size, H), -127, 128, jnp.int8),
            "scale": jnp.full((cfg.vocab_size,), 3.0 * 0.02 / 127.0,
                              jnp.float32),
        },
        "layers": layers,
        "final_norm": jnp.ones((H,), dtype),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = qdense(keys[1], H, cfg.vocab_size, False)
    return params


def param_bytes(params: Params) -> int:
    """Total weight bytes as stored (int8 kernels count 1 byte/element)."""
    return sum(x.size * x.dtype.itemsize
               for x in jax.tree_util.tree_leaves(params))
