"""Checkpoint IO: HuggingFace safetensors -> JAX params, plus orbax-native
save/restore.

Weight-layout note: HF/torch ``nn.Linear`` stores ``[out, in]``; our kernels
are ``[in, out]`` (see models/llama.py).  The transpose happens exactly once,
here, at load time — never in the forward pass.

Supports the two checkpoint families from BASELINE.md: Llama-3 (no biases)
and Qwen2 (QKV biases), in single-file or index-sharded safetensors form.
"""

from __future__ import annotations

import json
import pathlib
from typing import Any, Iterable, Mapping

import jax.numpy as jnp
import numpy as np

from k8s_llm_monitor_tpu.models.config import ModelConfig

Params = dict[str, Any]


def config_from_hf(hf: Mapping[str, Any], name: str = "hf-model") -> ModelConfig:
    """Translate a HF ``config.json`` dict (Llama/Qwen2/Mixtral/Gemma-2
    configs) to ours.  Keys equal to a HF class default are OMITTED from
    saved config.json (diff-serialization), so family-specific defaults
    must be reproduced here, not read with neutral fallbacks."""
    num_heads = hf["num_attention_heads"]
    gemma2 = hf.get("model_type") == "gemma2"
    n_layers = hf["num_hidden_layers"]
    # Sliding windows: Qwen2 ships sliding_window=131072 with
    # use_sliding_window=false — the raw value alone must not enable
    # window masking (it would force the gather attention impls and
    # reject pipeline/ring training for a model that has no windows).
    # Gemma-2's CLASS default is 4096, omitted by diff-serialization.
    sliding = hf.get("sliding_window", 4096 if gemma2 else 0) or 0
    if hf.get("use_sliding_window") is False:
        sliding = 0
    layer_types = tuple(hf["layer_types"]) if hf.get("layer_types") else None
    if gemma2 and sliding and layer_types is None:
        # Gemma-2 configs released before HF serialized layer_types.
        from k8s_llm_monitor_tpu.models.config import gemma2_layer_types

        layer_types = gemma2_layer_types(n_layers)
    return ModelConfig(
        name=name,
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        intermediate_size=hf["intermediate_size"],
        num_layers=hf["num_hidden_layers"],
        num_heads=num_heads,
        num_kv_heads=hf.get("num_key_value_heads", num_heads),
        head_dim=hf.get("head_dim"),
        rope_theta=hf.get("rope_theta", 10_000.0),
        rope_scaling=hf.get("rope_scaling"),
        rms_norm_eps=hf.get("rms_norm_eps", 1e-5),
        max_seq_len=hf.get("max_position_embeddings", 8192),
        qkv_bias=hf.get("model_type") == "qwen2",
        # Gemma-2 ties embeddings by CLASS default, so saved configs omit
        # the key — a neutral False default would demand a lm_head tensor
        # tied checkpoints don't ship.
        tie_embeddings=hf.get("tie_word_embeddings", gemma2),
        # Mixtral: MoE geometry from the HF keys (0/absent = dense).
        num_experts=hf.get("num_local_experts", 0),
        num_experts_per_tok=hf.get("num_experts_per_tok", 2),
        # Gemma-2 conventions (defaults reproduce Llama for other types;
        # class-default-omitted keys fall back per family).
        mlp_activation=("gelu_tanh" if gemma2 or hf.get("hidden_activation")
                        == "gelu_pytorch_tanh" else "silu"),
        sandwich_norms=gemma2,
        rmsnorm_unit_offset=gemma2,
        attn_logit_softcap=hf.get(
            "attn_logit_softcapping", 50.0 if gemma2 else 0.0) or 0.0,
        final_logit_softcap=hf.get(
            "final_logit_softcapping", 30.0 if gemma2 else 0.0) or 0.0,
        query_pre_attn_scalar=(hf.get("query_pre_attn_scalar", 256.0)
                               if gemma2 else None),
        embed_scale=gemma2,
        sliding_window=sliding,
        layer_types=layer_types,
    )


_LINEAR_MAP = {
    "q": "self_attn.q_proj",
    "k": "self_attn.k_proj",
    "v": "self_attn.v_proj",
    "o": "self_attn.o_proj",
    "gate": "mlp.gate_proj",
    "up": "mlp.up_proj",
    "down": "mlp.down_proj",
}


def convert_hf_state_dict(
    state: Mapping[str, np.ndarray], cfg: ModelConfig,
    dtype: str | None = None, quantize: bool = False,
) -> Params:
    """Map a HF Llama/Qwen2 state dict (numpy arrays) to our param pytree.

    With ``quantize=True``, every linear kernel and the embed/unembed tables
    are int8-quantized (utils/quantize.py) tensor-by-tensor on the host
    before transfer — the only way an 8B-class checkpoint fits next to the
    KV pool on a 16 GB chip.  Quantization happens on the host-side numpy
    copy, so peak device memory is the quantized size, never the bf16 size.
    """
    dt = jnp.dtype(dtype or cfg.dtype)

    def get(name: str) -> jnp.ndarray:
        return jnp.asarray(np.asarray(state[name]), dtype=dt)

    def linear(weight_key: str, bias_key: str | None = None) -> Params:
        w = np.asarray(state[weight_key]).T               # [in, out]
        if quantize:
            from k8s_llm_monitor_tpu.utils.quantize import quantize_array

            # quantize_array upcasts to f32 internally; the dense path
            # below converts straight to the target dtype instead.
            w_q, scale = quantize_array(w, axis=0)
            p: Params = {"kernel_q": jnp.asarray(w_q),
                         "scale": jnp.asarray(scale)}
        else:
            p = {"kernel": jnp.asarray(w, dtype=dt)}
        if bias_key is not None and bias_key in state:
            p["bias"] = get(bias_key)
        return p

    def expert_stack(pre: str, hf_name: str) -> Params:
        # Mixtral: block_sparse_moe.experts.<e>.{w1,w3,w2} -> stacked
        # [E, in, out] (w1=gate, w3=up, w2=down).
        ws = np.stack([np.asarray(
            state[f"{pre}block_sparse_moe.experts.{e}.{hf_name}.weight"]).T
            for e in range(cfg.num_experts)])
        if quantize:
            from k8s_llm_monitor_tpu.utils.quantize import (
                quantize_expert_stack,
            )

            return quantize_expert_stack({"kernel": ws})
        return {"kernel": jnp.asarray(ws, dtype=dt)}

    layers = []
    for i in range(cfg.num_layers):
        pre = f"model.layers.{i}."
        if cfg.sandwich_norms:
            # Gemma-2: post_attention norm applies to the attention OUTPUT,
            # pre/post_feedforward sandwich the MLP (our post_norm plays
            # the pre_feedforward role — models/llama.py:layer_block).
            layer: Params = {
                "input_norm": get(pre + "input_layernorm.weight"),
                "post_attn_norm": get(pre + "post_attention_layernorm.weight"),
                "post_norm": get(pre + "pre_feedforward_layernorm.weight"),
                "post_mlp_norm": get(pre + "post_feedforward_layernorm.weight"),
            }
        else:
            layer = {
                "input_norm": get(pre + "input_layernorm.weight"),
                "post_norm": get(pre + "post_attention_layernorm.weight"),
            }
        for ours, theirs in _LINEAR_MAP.items():
            if cfg.num_experts > 0 and ours in ("gate", "up", "down"):
                continue
            layer[ours] = linear(f"{pre}{theirs}.weight",
                                 f"{pre}{theirs}.bias")
        if cfg.num_experts > 0:
            layer["router"] = {"kernel": jnp.asarray(np.asarray(
                state[f"{pre}block_sparse_moe.gate.weight"]).T, dtype=dt)}
            layer["gate_e"] = expert_stack(pre, "w1")
            layer["up_e"] = expert_stack(pre, "w3")
            layer["down_e"] = expert_stack(pre, "w2")
        layers.append(layer)

    if quantize:
        from k8s_llm_monitor_tpu.utils.quantize import quantize_array

        w_q, scale = quantize_array(
            np.asarray(state["model.embed_tokens.weight"], np.float32),
            axis=1)
        embed: Params = {"weight_q": jnp.asarray(w_q),
                         "scale": jnp.asarray(scale)}
    else:
        embed = {"weight": get("model.embed_tokens.weight")}
    params: Params = {
        "embed": embed,
        "layers": layers,
        "final_norm": get("model.norm.weight"),
    }
    if not cfg.tie_embeddings:
        head_key = ("lm_head.weight" if "lm_head.weight" in state
                    else "model.embed_tokens.weight")  # ties despite config
        params["lm_head"] = linear(head_key)
    return params


class _SafetensorsDict(Mapping[str, np.ndarray]):
    """Lazy mapping over (possibly sharded) safetensors files."""

    def __init__(self, model_dir: pathlib.Path):
        from safetensors import safe_open

        self._files: dict[str, pathlib.Path] = {}
        index = model_dir / "model.safetensors.index.json"
        if index.exists():
            weight_map = json.loads(index.read_text())["weight_map"]
            for key, fname in weight_map.items():
                self._files[key] = model_dir / fname
        else:
            for f in sorted(model_dir.glob("*.safetensors")):
                with safe_open(str(f), framework="np") as sf:
                    for key in sf.keys():
                        self._files[key] = f
        self._safe_open = safe_open

    def __getitem__(self, key: str) -> np.ndarray:
        with self._safe_open(str(self._files[key]), framework="np") as sf:
            return sf.get_tensor(key)

    def __iter__(self) -> Iterable[str]:
        return iter(self._files)

    def __len__(self) -> int:
        return len(self._files)

    def __contains__(self, key: object) -> bool:
        return key in self._files


def load_hf_checkpoint(
    model_dir: str | pathlib.Path, dtype: str | None = None,
    quantize: bool = False,
) -> tuple[ModelConfig, Params]:
    """Load a HF-format model directory (config.json + safetensors).

    ``quantize=True`` streams each tensor through host-side int8
    quantization (see convert_hf_state_dict) — required for 8B-class
    checkpoints on a single 16 GB chip.
    """
    model_dir = pathlib.Path(model_dir)
    hf_cfg = json.loads((model_dir / "config.json").read_text())
    cfg = ModelConfig(**{
        **config_from_hf(hf_cfg, name=model_dir.name).__dict__,
        **({"dtype": dtype} if dtype else {}),
    })
    state = _SafetensorsDict(model_dir)
    return cfg, convert_hf_state_dict(state, cfg, dtype=dtype,
                                      quantize=quantize)


# ---------------------------------------------------------------------------
# Orbax-native checkpoints (training / snapshot persistence)
# ---------------------------------------------------------------------------


def save_checkpoint(path: str | pathlib.Path, params: Params) -> None:
    import orbax.checkpoint as ocp

    path = pathlib.Path(path).absolute()
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, params, force=True)


def restore_checkpoint(path: str | pathlib.Path, like: Params | None = None) -> Params:
    import orbax.checkpoint as ocp

    path = pathlib.Path(path).absolute()
    with ocp.StandardCheckpointer() as ckptr:
        if like is not None:
            return ckptr.restore(path, like)
        return ckptr.restore(path)
