"""Shared utilities: checkpoint IO, tokenizers, logging."""
