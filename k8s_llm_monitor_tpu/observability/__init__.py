"""In-tree observability: request tracing, latency histograms, the
crash flight recorder, and the fleet telemetry plane (time-series store
+ signal scraper) (docs/observability.md).

Zero external dependencies.  Everything here is host-side bookkeeping —
nothing in this package may be called from inside a traced (jitted)
program; spans are recorded at dispatch/reconcile time on the step
thread or on HTTP handler threads (the same discipline as
``GenerationRequest``: host-side scheduling metadata only, zero
recompiles).
"""

from .tracing import (  # noqa: F401
    Span,
    TraceContext,
    Tracer,
    format_traceparent,
    get_tracer,
    parse_traceparent,
)
from .metrics import ClassHistogram  # noqa: F401
from .flight import FlightRecorder, get_flight_recorder  # noqa: F401
from .timeseries import TimeSeriesStore  # noqa: F401
from .signals import SignalScraper  # noqa: F401
