"""Signal scraper + derived autoscaler signals (docs/observability.md).

The telemetry plane's sampling and derivation layer on top of
:class:`~k8s_llm_monitor_tpu.observability.timeseries.TimeSeriesStore`:

  * ``SignalScraper`` runs one background thread that samples the local
    engine (queue tokens by SLO class, TTFT EMAs, brownout rung,
    admission headroom, KV tier occupancy, preemptions, sheds) and — on
    the router role — every replica's last ``/api/v1/stats`` probe via
    the ``ReplicaRegistry`` (``FleetRouter.telemetry_sample()``; the
    scraper never does its own HTTP, the probe loop already did).
  * A derived layer computes the ROADMAP-item-1 autoscaler contract per
    target: queue-token growth rate by class, sustained TTFT-EMA trend
    vs the per-class SLO budget, brownout dwell fraction, headroom
    slope, folded into one ``scale_hint`` (``up``/``steady``/``down``).
  * Anomaly flags (monotonic queue growth, TTFT budget breach, replica
    scrape staleness) are edge-triggered with a cooldown and injected
    into the diagnosis pipeline's event ring as synthetic Warning events
    tagged ``source="self_monitor"`` — the monitor diagnosing its own
    serving stack.

Staleness discipline (PR 7's NaN rule): a replica whose last successful
probe is older than ``stale_after_probes`` probe intervals gets NaN
markers recorded for its gauges instead of frozen values, and its
derived block carries ``stale: true``.
"""

from __future__ import annotations

import logging
import math
import threading
import time
from collections import deque
from typing import Any, Callable, Optional

from k8s_llm_monitor_tpu.devtools.lockcheck import guarded_by, make_lock
from k8s_llm_monitor_tpu.observability.timeseries import TimeSeriesStore
from k8s_llm_monitor_tpu.resilience.slo import SLO_CLASSES

logger = logging.getLogger("observability.signals")

__all__ = ["SignalScraper"]

_NAN = float("nan")

# Local-engine series carry this target label so router-merged and
# replica-local stores share one series catalog.
LOCAL_TARGET = "local"


def _num(value: float, digits: int = 4) -> Optional[float]:
    """JSON-safe number: round finite values, map NaN/Inf to None (the
    wire marker for "not measured" — strict-JSON clients choke on NaN)."""
    try:
        v = float(value)
    except (TypeError, ValueError):
        return None
    return round(v, digits) if math.isfinite(v) else None


@guarded_by("_lock", "scrapes_total", "scrape_errors_total",
            "anomalies_total", "_probe_interval_s", "_fleet_targets",
            "evicted_targets_total")
class SignalScraper:
    """Samples load signals into a ``TimeSeriesStore`` and derives the
    autoscaler/anomaly contract from the recorded windows.

    Construction order: the scraper is built before the ``MonitorServer``
    that owns it, so the server is wired in afterwards via ``attach()``.
    ``scrape_once()`` is the synchronous seam tests and the bench drive
    directly; ``start()`` runs it on a daemon thread every
    ``cfg.scrape_interval_s``.
    """

    def __init__(self, store: Optional[TimeSeriesStore] = None,
                 cfg=None, *, pipeline: Any = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        from k8s_llm_monitor_tpu.monitor.config import TelemetryConfig

        self.cfg = cfg or TelemetryConfig()
        self._clock = clock
        self.store = store or TimeSeriesStore(
            capacity=self.cfg.ring_points,
            max_series=self.cfg.max_series,
            clock=clock)
        # diagnosis.pipeline.DiagnosisPipeline (anything with
        # ``offer(EventInfo)``); None = anomalies are reported on
        # /api/v1/signals but never trigger a diagnosis.
        self.pipeline = pipeline
        self._server: Any = None
        self.scrapes_total = 0
        self.scrape_errors_total = 0
        self.anomalies_total = 0
        self.anomalies_by_flag: dict[str, int] = {}
        self._recent_anomalies: deque[dict] = deque(maxlen=32)
        self._last_emit: dict[str, float] = {}
        self._probe_interval_s: float = 0.0
        # Fleet targets seen on the previous scrape — membership GC:
        # a replica that left the registry gets its series evicted
        # instead of lingering as a permanently-stale alarm target.
        self._fleet_targets: set[str] = set()
        self.evicted_targets_total = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Created last (lockcheck).
        self._lock = make_lock("observability.signals")

    # -- wiring ----------------------------------------------------------

    def attach(self, server: Any) -> None:
        """Wire the ``MonitorServer`` (or any object exposing
        ``engine_service()`` / ``fleet_router()``) this scraper reads."""
        self._server = server

    def role(self) -> str:
        srv = self._server
        router = srv.fleet_router() if srv is not None else None
        return "router" if router is not None else "replica"

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def _loop() -> None:
            while not self._stop.wait(timeout=self.cfg.scrape_interval_s):
                self.scrape_once()

        self._thread = threading.Thread(
            target=_loop, name="signal-scraper", daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    # -- sampling --------------------------------------------------------

    def scrape_once(self) -> None:
        """One full sampling pass + anomaly evaluation.  Never raises —
        a scrape failure is a counter, not an outage."""
        srv = self._server
        if srv is None:
            return
        t = self._clock()
        try:
            svc_fn = getattr(srv, "engine_service", None)
            svc = svc_fn() if callable(svc_fn) else None
            if svc is not None:
                self._sample_engine(LOCAL_TARGET, svc, t)
            router_fn = getattr(srv, "fleet_router", None)
            router = router_fn() if callable(router_fn) else None
            if router is not None:
                sample = router.telemetry_sample()
                self._sample_fleet(sample["replicas"],
                                   sample["probe_interval_s"], t)
            with self._lock:
                self.scrapes_total += 1
        except Exception:  # noqa: BLE001 — the scrape loop must survive
            with self._lock:
                self.scrape_errors_total += 1
            logger.exception("signal scrape failed")
            return
        self._evaluate_anomalies(t)

    def _sample_engine(self, target: str, svc: Any, t: float) -> None:
        """Local-engine sample: the same signal set the fleet rows carry,
        read straight off the engine (the registry probe payload's
        source of truth)."""
        rec = self.store.record
        engine = svc.engine
        lab = {"replica": target}
        by_class = engine.queue_tokens_by_class()
        for c in SLO_CLASSES:
            rec("queue_tokens", by_class.get(c, 0),
                {"replica": target, "class": c}, t)
        rec("queue_tokens_total", engine.queue_tokens, lab, t)
        ttft = getattr(engine, "ttft_ema_by_class", {}) or {}
        for c in SLO_CLASSES:
            rec("ttft_ema_s", ttft.get(c, _NAN),
                {"replica": target, "class": c}, t)
        rec("brownout",
            engine.brownout() if engine.brownout is not None else 0, lab, t)
        headroom_fn = getattr(engine, "admission_headroom_tokens", None)
        rec("headroom_tokens",
            headroom_fn() if callable(headroom_fn) else _NAN, lab, t)
        tier_fn = getattr(engine, "kv_tier_stats", None)
        if callable(tier_fn):
            tier = tier_fn()
            has_host = getattr(engine, "host_kv_tier", None) is not None
            rec("kv_bytes", tier.get("device_bytes", _NAN),
                {"replica": target, "tier": "device"}, t)
            rec("kv_bytes",
                tier.get("host_bytes", 0) if has_host else _NAN,
                {"replica": target, "tier": "host"}, t)
            rec("kv_spills_total", tier.get("spills", 0), lab, t)
            rec("kv_restores_total", tier.get("restores", 0), lab, t)
        preempt = getattr(engine, "preemptions_by_class", {}) or {}
        sheds = getattr(svc, "shed_count_by_class", {}) or {}
        for c in SLO_CLASSES:
            rec("preemptions_total", preempt.get(c, 0),
                {"replica": target, "class": c}, t)
            rec("sheds_total", sheds.get(c, 0),
                {"replica": target, "class": c}, t)
        rec("busy_slots", engine.active_slots, lab, t)

    def _sample_fleet(self, rows: dict, probe_interval_s: float,
                      t: float) -> None:
        """Router-role sample from the registry's per-replica probe rows.
        Stale rows (probe age beyond ``stale_after_probes`` intervals, or
        never probed) record NaN markers, never frozen values."""
        interval = max(float(probe_interval_s), 1e-3)
        current = set(rows)
        with self._lock:
            self._probe_interval_s = interval
            departed = self._fleet_targets - current
            self._fleet_targets = current
        for rid in sorted(departed):
            self.evict_target(rid)
        stale_after = self.cfg.stale_after_probes * interval
        rec = self.store.record
        for rid, row in sorted(rows.items()):
            lab = {"replica": rid}
            age = row.get("probe_age_s")
            stale = age is None or float(age) > stale_after
            rec("scrape_age_s", _NAN if age is None else float(age), lab, t)
            if stale:
                for c in SLO_CLASSES:
                    rec("queue_tokens", _NAN,
                        {"replica": rid, "class": c}, t)
                    rec("ttft_ema_s", _NAN,
                        {"replica": rid, "class": c}, t)
                rec("queue_tokens_total", _NAN, lab, t)
                rec("brownout", _NAN, lab, t)
                rec("headroom_tokens", _NAN, lab, t)
                rec("busy_slots", _NAN, lab, t)
                continue
            by_class = row.get("queue_by_class") or {}
            ttft = row.get("ttft_ema_by_class") or {}
            preempt = row.get("preemptions_by_class") or {}
            sheds = row.get("shed_by_class") or {}
            for c in SLO_CLASSES:
                rec("queue_tokens", by_class.get(c, 0),
                    {"replica": rid, "class": c}, t)
                rec("ttft_ema_s", ttft.get(c, _NAN),
                    {"replica": rid, "class": c}, t)
                rec("preemptions_total", preempt.get(c, 0),
                    {"replica": rid, "class": c}, t)
                rec("sheds_total", sheds.get(c, 0),
                    {"replica": rid, "class": c}, t)
            rec("queue_tokens_total", row.get("queue_tokens", 0), lab, t)
            rec("brownout", row.get("brownout", 0), lab, t)
            headroom = row.get("headroom_tokens")
            rec("headroom_tokens",
                _NAN if headroom is None else headroom, lab, t)
            kv = row.get("kv_tier") or {}
            if kv:
                rec("kv_bytes", kv.get("device_bytes", _NAN),
                    {"replica": rid, "tier": "device"}, t)
                rec("kv_bytes", kv.get("host_bytes", _NAN),
                    {"replica": rid, "tier": "host"}, t)
                rec("kv_spills_total", kv.get("spills", 0), lab, t)
                rec("kv_restores_total", kv.get("restores", 0), lab, t)
            rec("busy_slots", row.get("busy_slots", 0), lab, t)

    def evict_target(self, target: str) -> int:
        """Membership GC for one departed fleet target: drop every
        ``{replica=target}`` series (so ``scrape_age_s`` and friends stop
        reading as stale alarms, and the cardinality cap isn't spent on
        dead replicas) and forget its anomaly cooldown keys.  Returns the
        number of series evicted.  Called automatically when a fleet
        scrape no longer lists the target; also safe to call directly
        from a registry on_remove hook."""
        if target == LOCAL_TARGET:
            return 0
        n = self.store.evict({"replica": target})
        prefix = f"{target}:"
        with self._lock:
            for key in [k for k in self._last_emit if k.startswith(prefix)]:
                del self._last_emit[key]
            if n:
                self.evicted_targets_total += 1
        if n:
            logger.info("evicted %d series for departed replica %s",
                        n, target)
        return n

    # -- derived signals -------------------------------------------------

    def _targets(self) -> list[str]:
        seen = set()
        for _, items in self.store.keys("queue_tokens_total"):
            seen.update(v for k, v in items if k == "replica")
        for _, items in self.store.keys("scrape_age_s"):
            seen.update(v for k, v in items if k == "replica")
        return sorted(seen)

    def _ttft_budget(self, slo_class: str) -> float:
        return {
            "interactive": self.cfg.ttft_budget_interactive_s,
            "standard": self.cfg.ttft_budget_standard_s,
            "batch": self.cfg.ttft_budget_batch_s,
        }.get(slo_class, self.cfg.ttft_budget_standard_s)

    def _derive(self, target: str, window_s: float,
                now: float) -> dict[str, Any]:
        """One target's autoscaler block: levels, trends, dwell, hint,
        anomaly flags.  All numbers JSON-safe (None = unmeasured)."""
        st = self.store
        cfg = self.cfg
        lab = {"replica": target}

        # Staleness: only fleet targets carry scrape_age_s; NaN there
        # means "never probed", which is as stale as it gets.
        stale = False
        if st.keys("scrape_age_s") and target != LOCAL_TARGET:
            age = st.last("scrape_age_s", lab, window_s, now=now)
            with self._lock:
                interval = self._probe_interval_s
            limit = cfg.stale_after_probes * max(interval, 1e-3)
            stale = (not math.isfinite(age)) or age > limit

        queue_last, queue_growth = {}, {}
        ttft_last, ttft_trend, ttft_breach = {}, {}, {}
        any_breach = False
        growth_up = False
        for c in SLO_CLASSES:
            cl = {"replica": target, "class": c}
            queue_last[c] = st.last("queue_tokens", cl, window_s, now=now)
            queue_growth[c] = st.rate("queue_tokens", cl, window_s, now=now)
            if (math.isfinite(queue_growth[c])
                    and queue_growth[c] > cfg.queue_growth_up_tok_s):
                growth_up = True
            ttft_last[c] = st.last("ttft_ema_s", cl, window_s, now=now)
            ttft_trend[c] = st.rate("ttft_ema_s", cl, window_s, now=now)
            # Sustained breach: over budget now AND not already falling.
            breach = (math.isfinite(ttft_last[c])
                      and ttft_last[c] > self._ttft_budget(c)
                      and not (math.isfinite(ttft_trend[c])
                               and ttft_trend[c] < 0.0))
            ttft_breach[c] = breach
            any_breach = any_breach or breach

        total_pts = [v for _, v in st.points(
            "queue_tokens_total", lab, window_s, now=now)
            if math.isfinite(v)]
        total_last = total_pts[-1] if total_pts else _NAN
        total_growth = st.rate("queue_tokens_total", lab, window_s, now=now)

        brown_pts = [v for _, v in st.points(
            "brownout", lab, window_s, now=now) if math.isfinite(v)]
        brownout_last = brown_pts[-1] if brown_pts else _NAN
        dwell = (sum(1 for v in brown_pts if v >= 1) / len(brown_pts)
                 if brown_pts else 0.0)

        headroom_last = st.last("headroom_tokens", lab, window_s, now=now)
        headroom_slope = st.rate("headroom_tokens", lab, window_s, now=now)

        # Monotonic queue growth: enough points, sustained positive rate,
        # and the newest point still at (within 5% of) the window max —
        # i.e. the backlog is climbing, not a spike already draining.
        mono_growth = (
            len(total_pts) >= 3
            and math.isfinite(total_growth)
            and total_growth > cfg.queue_growth_up_tok_s
            and total_pts[-1] >= 0.95 * max(total_pts)
            and total_pts[-1] > total_pts[0])

        if stale:
            hint = "steady"  # no fresh evidence: never scale on it
        elif (growth_up or mono_growth or any_breach
              or dwell > cfg.brownout_dwell_up):
            hint = "up"
        elif (total_pts and max(total_pts) == 0 and dwell == 0.0
              and not any_breach
              and (not math.isfinite(headroom_slope)
                   or headroom_slope >= 0.0)):
            # Idle for the whole window with headroom not shrinking.
            hint = "down"
        else:
            hint = "steady"

        flags = []
        if mono_growth:
            flags.append("queue_growth")
        if any_breach:
            flags.append("ttft_breach")
        if stale:
            flags.append("scrape_stale")

        return {
            "stale": stale,
            "scale_hint": hint,
            "anomalies": flags,
            "queue_tokens": {c: _num(queue_last[c], 1)
                             for c in SLO_CLASSES},
            "queue_growth_tok_per_s": {c: _num(queue_growth[c])
                                       for c in SLO_CLASSES},
            "queue_tokens_total": _num(total_last, 1),
            "queue_growth_total_tok_per_s": _num(total_growth),
            "ttft_ema_s": {c: _num(ttft_last[c], 6) for c in SLO_CLASSES},
            "ttft_trend_s_per_s": {c: _num(ttft_trend[c], 6)
                                   for c in SLO_CLASSES},
            "ttft_budget_breach": dict(ttft_breach),
            "brownout": _num(brownout_last, 1),
            "brownout_dwell": _num(dwell),
            "headroom_tokens": _num(headroom_last, 1),
            "headroom_slope_tok_per_s": _num(headroom_slope),
        }

    def signals(self, window_s: Optional[float] = None) -> dict[str, Any]:
        """The ``GET /api/v1/signals`` body: per-target derived blocks
        (fleet-merged on routers, just ``local`` on replicas) plus
        scraper self-accounting.  JSON-safe throughout."""
        w = float(window_s) if window_s else self.cfg.window_s
        now = self._clock()
        targets = {t: self._derive(t, w, now) for t in self._targets()}
        with self._lock:
            counters = {
                "scrapes": self.scrapes_total,
                "errors": self.scrape_errors_total,
                "anomalies": self.anomalies_total,
                "anomalies_by_flag": dict(self.anomalies_by_flag),
            }
            recent = list(self._recent_anomalies)
        counters["series"] = self.store.series_count()
        counters["interval_s"] = self.cfg.scrape_interval_s
        return {
            "role": self.role(),
            "window_s": w,
            "targets": targets,
            "recent_anomalies": recent,
            "scraper": counters,
        }

    def counters(self) -> dict:
        """Scraper self-accounting for the exporter (one lock hold)."""
        with self._lock:
            return {
                "scrapes_total": self.scrapes_total,
                "scrape_errors_total": self.scrape_errors_total,
                "anomalies_total": self.anomalies_total,
                "anomalies_by_flag": dict(self.anomalies_by_flag),
                "evicted_targets_total": self.evicted_targets_total,
            }

    # -- anomaly → diagnosis feed ---------------------------------------

    def _evaluate_anomalies(self, now: float) -> None:
        """Edge-trigger per (target, flag) with a cooldown, then inject
        synthetic Warning events into the diagnosis pipeline.  The
        pipeline call happens outside our lock — it takes its own."""
        from k8s_llm_monitor_tpu.monitor.models import EventInfo

        window = self.cfg.window_s
        emit: list[tuple[str, str, dict]] = []
        for target in self._targets():
            derived = self._derive(target, window, now)
            for flag in derived["anomalies"]:
                key = f"{target}:{flag}"
                with self._lock:
                    last = self._last_emit.get(key)
                    if (last is not None
                            and now - last < self.cfg.anomaly_cooldown_s):
                        continue
                    self._last_emit[key] = now
                    self.anomalies_total += 1
                    self.anomalies_by_flag[flag] = (
                        self.anomalies_by_flag.get(flag, 0) + 1)
                    self._recent_anomalies.append({
                        "t_mono": round(now, 3),
                        "target": target,
                        "flag": flag,
                        "scale_hint": derived["scale_hint"],
                    })
                emit.append((target, flag, derived))
        if not emit or self.pipeline is None or not self.cfg.feed_diagnosis:
            return
        for target, flag, derived in emit:
            detail = {
                "queue_growth": (
                    f"queue tokens growing at "
                    f"{derived['queue_growth_total_tok_per_s']} tok/s "
                    f"(total {derived['queue_tokens_total']})"),
                "ttft_breach": (
                    f"TTFT EMA over SLO budget, not falling: "
                    f"{derived['ttft_ema_s']}"),
                "scrape_stale": (
                    "stats probe stale beyond "
                    f"{self.cfg.stale_after_probes}x probe interval"),
            }.get(flag, flag)
            event = EventInfo(
                type="Warning",
                reason=f"SelfMonitor:{flag}",
                message=f"replica {target}: {detail}",
                source="self_monitor",
            )
            try:
                self.pipeline.offer(event)
            except Exception:  # noqa: BLE001 — feed is best-effort
                logger.exception("self_monitor event injection failed")
