"""Sampled, bounded-overhead request tracing (docs/observability.md).

A :class:`Tracer` records completed :class:`Span`\\ s into a fixed-size
per-process ring.  The ring is lock-free: slots are claimed with an
``itertools.count`` (``next()`` on a count is a single GIL-atomic C
call) and each slot write is one list-item assignment, so recording
from the step thread, HTTP handler threads, and router pump threads
never contends and never blocks — a full ring simply overwrites the
oldest spans.  Nothing here may run inside a traced (jitted) program.

Trace identity follows W3C Trace Context: 32-hex ``trace_id``, 16-hex
``span_id``, and a sampled flag carried in the ``traceparent`` header
flags byte.  The sampling decision is *deterministic in the trace id*
(a hash of the leading 8 hex digits against the configured rate), so
every process along a request's path agrees on whether to record
without coordination, and seeded tests are reproducible.

Cross-layer contract:

- HTTP servers parse ``traceparent`` into the handler thread's local
  context (:meth:`Tracer.use`); :class:`~..monitor.client.ApiClient`
  attaches the current context to every outbound hop, so hedge legs,
  failover replays, and ``/api/v1/kv/*`` migration calls all join the
  originating trace.
- ``EngineService.submit`` snapshots the current context onto the
  :class:`~..serving.engine.GenerationRequest` (host-side metadata
  only); the engine step thread records phase spans against it.
"""

from __future__ import annotations

import itertools
import os
import random
import re
import threading
import time
from typing import Any, NamedTuple, Optional

__all__ = [
    "Span",
    "TraceContext",
    "Tracer",
    "format_traceparent",
    "get_tracer",
    "parse_traceparent",
]

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")


class TraceContext(NamedTuple):
    """Immutable position inside a trace: which trace, which span is the
    current parent, whether the trace is recorded, and (for spans that
    are themselves recorded later, e.g. the per-request engine span)
    the span's own parent."""

    trace_id: str
    span_id: str
    sampled: bool
    parent_id: str = ""


class Span:
    """One completed (or in-flight) operation.  Mutable so handler code
    can attach attributes mid-flight; pushed to the ring only once, at
    end time."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name",
                 "start", "end", "start_unix", "attrs", "status")

    def __init__(self, trace_id: str, span_id: str, parent_id: str,
                 name: str, start: float, start_unix: float,
                 attrs: Optional[dict] = None) -> None:
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.start = start          # time.monotonic()
        self.end = start            # set at finish
        self.start_unix = start_unix  # wall clock, for cross-process merge
        self.attrs: dict[str, Any] = attrs or {}
        self.status = "ok"

    @property
    def duration_s(self) -> float:
        return max(0.0, self.end - self.start)

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start_unix": self.start_unix,
            "start_mono": self.start,
            "duration_s": self.duration_s,
            "status": self.status,
            "attrs": dict(self.attrs),
        }


def format_traceparent(ctx: TraceContext) -> str:
    return f"00-{ctx.trace_id}-{ctx.span_id}-{'01' if ctx.sampled else '00'}"


def parse_traceparent(header: str) -> Optional[TraceContext]:
    """Parse a W3C ``traceparent`` header; None on any malformation
    (an invalid header must never fail the request carrying it)."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if m is None:
        return None
    version, trace_id, span_id, flags = m.groups()
    if version == "ff" or trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return TraceContext(trace_id, span_id, bool(int(flags, 16) & 1))


class _SpanScope:
    """Context manager returned by :meth:`Tracer.span`: establishes the
    child context thread-locally for the with-block, then records the
    span (status ``error`` if the block raised)."""

    __slots__ = ("_tracer", "_ctx", "_prev", "span")

    def __init__(self, tracer: "Tracer", ctx: TraceContext, span: Span):
        self._tracer = tracer
        self._ctx = ctx
        self.span = span

    def __enter__(self) -> Span:
        self._prev = self._tracer._swap_local(self._ctx)
        return self.span

    def __exit__(self, exc_type, exc, tb) -> bool:
        self._tracer._swap_local(self._prev)
        sp = self.span
        sp.end = time.monotonic()
        if exc_type is not None and sp.status == "ok":
            sp.status = "error"
            sp.attrs.setdefault("error", exc_type.__name__)
        if self._ctx.sampled:
            self._tracer._push(sp)
        return False


class Tracer:
    """Per-process span recorder.  All methods are safe to call from any
    thread without locks (see module docstring); the only shared
    mutations are GIL-atomic container ops, and the worst race outcome
    is a slightly stale ring snapshot — never corruption, never a
    block on a hot path."""

    def __init__(self, ring_size: int = 4096,
                 sample: Optional[float] = None,
                 seed: Optional[int] = None) -> None:
        if sample is None:
            try:
                sample = float(os.environ.get("K8SLLM_TRACE_SAMPLE", "1.0"))
            except ValueError:
                sample = 1.0
        self.sample = min(1.0, max(0.0, sample))
        if seed is None:
            env_seed = os.environ.get("K8SLLM_TRACE_SEED", "")
            seed = int(env_seed) if env_seed.isdigit() else None
        self._rand = random.Random(seed)
        self._size = max(16, int(ring_size))
        self._ring: list[Optional[Span]] = [None] * self._size
        self._ring_idx = itertools.count()
        self._tls = threading.local()
        # request_id -> trace_id, bounded FIFO (endpoint lookup by either
        # id).  dict/deque ops are GIL-atomic; eviction races are benign.
        self._rid_index: dict[str, str] = {}
        self._rid_order: list[str] = []
        self._rid_cap = 1024
        self.recorded = 0   # spans pushed to the ring
        self.unsampled = 0  # record attempts on unsampled traces

    # -- identity --------------------------------------------------------

    def _new_trace_id(self) -> str:
        return f"{self._rand.getrandbits(128):032x}"

    def _new_span_id(self) -> str:
        return f"{self._rand.getrandbits(64):016x}"

    def sampled(self, trace_id: str) -> bool:
        """Deterministic head-sampling decision for ``trace_id``."""
        if self.sample >= 1.0:
            return True
        if self.sample <= 0.0:
            return False
        return int(trace_id[:8], 16) / 0x100000000 < self.sample

    def new_trace(self) -> Optional[TraceContext]:
        """Start a new root trace, or None when sampling is fully off
        (so untraced paths pay nothing, not even id generation's ring
        bookkeeping downstream)."""
        if self.sample <= 0.0:
            return None
        tid = self._new_trace_id()
        return TraceContext(tid, self._new_span_id(), self.sampled(tid))

    @staticmethod
    def child(ctx: TraceContext) -> TraceContext:
        """A child context under ``ctx``: same trace, fresh span id,
        parent recorded so the child span can be emitted later."""
        return TraceContext(ctx.trace_id, _GLOBAL_IDS.span_id(),
                            ctx.sampled, parent_id=ctx.span_id)

    # -- thread-local context -------------------------------------------

    def _swap_local(self, ctx: Optional[TraceContext]) -> Optional[TraceContext]:
        prev = getattr(self._tls, "ctx", None)
        self._tls.ctx = ctx
        return prev

    def current(self) -> Optional[TraceContext]:
        return getattr(self._tls, "ctx", None)

    def current_traceparent(self) -> str:
        ctx = self.current()
        return format_traceparent(ctx) if ctx is not None else ""

    def use(self, ctx: Optional[TraceContext]) -> "_UseScope":
        """Establish ``ctx`` as the thread's current context for a
        with-block (router pump/hedge threads re-entering a flight's
        trace before replica calls)."""
        return _UseScope(self, ctx)

    # -- span creation ---------------------------------------------------

    def span(self, name: str, *, parent: Optional[TraceContext] = None,
             attrs: Optional[dict] = None, root: bool = False) -> _SpanScope:
        """Open a span for a with-block.  Parent resolution: explicit
        ``parent``, else the thread's current context, else a new root
        trace (unless sampling is fully off, in which case the scope is
        inert)."""
        pctx = parent if parent is not None else (
            None if root else self.current())
        if pctx is None:
            ctx = self.new_trace()
            if ctx is None:  # sampling fully off: inert scope
                ctx = TraceContext("0" * 32, "0" * 16, False)
            sp_id, par = ctx.span_id, ""
        else:
            ctx = self.child(pctx)
            sp_id, par = ctx.span_id, ctx.parent_id
        sp = Span(ctx.trace_id, sp_id, par, name,
                  time.monotonic(), time.time(), attrs)
        return _SpanScope(self, ctx, sp)

    def record(self, name: str, t0: float, t1: float,
               ctx: Optional[TraceContext], *,
               attrs: Optional[dict] = None, status: str = "ok",
               span_id: str = "", parent_id: Optional[str] = None,
               t0_unix: Optional[float] = None) -> str:
        """Record an already-completed span under ``ctx`` (the engine
        path: dispatch/reconcile times are known after the fact).
        Parent defaults to ``ctx.span_id``; pass ``span_id=ctx.span_id,
        parent_id=ctx.parent_id`` to emit the context's own span (the
        per-request root).  Returns the span id, or "" unrecorded."""
        if ctx is None:
            return ""
        if not ctx.sampled:
            self.unsampled += 1
            return ""
        sid = span_id or self._new_span_id()
        pid = ctx.span_id if parent_id is None else parent_id
        if t0_unix is None:
            # Derive wall-clock start from the monotonic offset so merge
            # ordering is consistent with spans stamped at open time.
            t0_unix = time.time() - (time.monotonic() - t0)
        sp = Span(ctx.trace_id, sid, pid, name, t0, t0_unix, attrs)
        sp.end = t1
        sp.status = status
        self._push(sp)
        return sid

    def _push(self, span: Span) -> None:
        self._ring[next(self._ring_idx) % self._size] = span
        self.recorded += 1

    # -- request-id index ------------------------------------------------

    def bind(self, request_id: str, ctx: Optional[TraceContext]) -> None:
        """Associate a request id with its trace for endpoint lookup."""
        if ctx is None or not request_id:
            return
        if request_id not in self._rid_index:
            self._rid_order.append(request_id)
            while len(self._rid_order) > self._rid_cap:
                old = self._rid_order.pop(0)
                self._rid_index.pop(old, None)
        self._rid_index[request_id] = ctx.trace_id

    def lookup(self, request_or_trace_id: str) -> Optional[str]:
        """Resolve either a request id or a literal 32-hex trace id."""
        s = request_or_trace_id.strip()
        hit = self._rid_index.get(s)
        if hit is not None:
            return hit
        low = s.lower()
        if len(low) == 32 and all(c in "0123456789abcdef" for c in low):
            return low
        return None

    # -- inspection ------------------------------------------------------

    def _snapshot_spans(self) -> list[Span]:
        return [s for s in list(self._ring) if s is not None]

    def spans_for(self, trace_id: str) -> list[dict]:
        """All ring-resident spans of one trace, merge-ordered by wall
        clock start."""
        out = [s.to_dict() for s in self._snapshot_spans()
               if s.trace_id == trace_id]
        out.sort(key=lambda d: d["start_unix"])
        return out

    def recent(self, limit: int = 20) -> list[dict]:
        """Most recent traces in the ring: id, span count, root name."""
        by_trace: dict[str, list[Span]] = {}
        for s in self._snapshot_spans():
            by_trace.setdefault(s.trace_id, []).append(s)
        rows = []
        for tid, spans in by_trace.items():
            spans.sort(key=lambda s: s.start_unix)
            roots = [s for s in spans if not s.parent_id]
            rows.append({
                "trace_id": tid,
                "n_spans": len(spans),
                "root": (roots[0].name if roots else spans[0].name),
                "start_unix": spans[0].start_unix,
                "last_unix": max(s.start_unix + s.duration_s for s in spans),
            })
        rows.sort(key=lambda r: r["last_unix"], reverse=True)
        return rows[:max(1, int(limit))]

    def snapshot(self) -> list[dict]:
        """Every ring-resident span (flight-recorder dump payload)."""
        out = [s.to_dict() for s in self._snapshot_spans()]
        out.sort(key=lambda d: d["start_unix"])
        return out


class _UseScope:
    __slots__ = ("_tracer", "_ctx", "_prev")

    def __init__(self, tracer: Tracer, ctx: Optional[TraceContext]):
        self._tracer = tracer
        self._ctx = ctx

    def __enter__(self) -> Optional[TraceContext]:
        self._prev = self._tracer._swap_local(self._ctx)
        return self._ctx

    def __exit__(self, exc_type, exc, tb) -> bool:
        self._tracer._swap_local(self._prev)
        return False


class _Ids:
    """Process-wide span-id source for TraceContext.child (static method
    — cannot reach an instance's RNG; ids only need uniqueness)."""

    def __init__(self) -> None:
        self._rand = random.Random()

    def span_id(self) -> str:
        return f"{self._rand.getrandbits(64):016x}"


_GLOBAL_IDS = _Ids()
_TRACER: Optional[Tracer] = None


def get_tracer() -> Tracer:
    """The per-process tracer singleton (created on first use, env-
    configured: K8SLLM_TRACE_SAMPLE, K8SLLM_TRACE_SEED)."""
    global _TRACER
    if _TRACER is None:
        _TRACER = Tracer()
    return _TRACER


def set_tracer(tracer: Optional[Tracer]) -> None:
    """Swap the process tracer (tests)."""
    global _TRACER
    _TRACER = tracer
