"""Crash flight recorder (docs/observability.md).

A bounded in-memory event log (engine/service/supervisor milestones:
watchdog trips, shed decisions, preemptions, rebuilds) that, together
with the tracer's span ring, is dumped to a JSON artifact when the
process hits a failure edge: watchdog fire (``_reset_pipeline``),
``EngineService._fail_all``, a supervisor rebuild, or SIGTERM.  Every
crash gets a postmortem timeline alongside the WAL.

``note()`` is a single deque.append (GIL-atomic, lock-free, O(1));
``dump()`` does file I/O but only on failure edges, never on a hot
path, and swallows OSErrors — a full disk must not turn a recoverable
fault into a crash.
"""

from __future__ import annotations

import collections
import itertools
import json
import os
import re
import tempfile
import time
from typing import Any, Callable, Optional

from .tracing import get_tracer

__all__ = ["FlightRecorder", "get_flight_recorder"]

_REASON_RE = re.compile(r"[^A-Za-z0-9_.-]+")


def _default_dir() -> str:
    return (os.environ.get("K8SLLM_FLIGHT_DIR")
            or os.path.join(tempfile.gettempdir(), "k8sllm-flight"))


class FlightRecorder:
    """Bounded event ring + JSON dump-on-failure.  Artifact format
    (version 2): ``{"version", "reason", "ts_unix", "pid", "events":
    [{"t_unix", "t_mono", "kind", ...}], "spans": [span dicts],
    "signals": {...} | null, "extra": {...}}``.

    ``signal_source`` is an optional zero-arg callable returning a
    JSON-safe snapshot of the local telemetry window (wired to
    ``TimeSeriesStore.window_snapshot`` by the server builders) — the
    load trajectory *into* the crash, alongside the event/span timeline.
    A raising source never fails the dump."""

    def __init__(self, capacity: int = 512,
                 dirpath: Optional[str] = None) -> None:
        self._events: collections.deque[dict] = collections.deque(
            maxlen=max(16, int(capacity)))
        self._dir = dirpath or _default_dir()
        self._seq = itertools.count()
        self.dumps = 0
        self.dump_errors = 0
        self.last_dump_path = ""
        self.signal_source: Optional[Callable[[], Any]] = None

    def note(self, kind: str, **fields: Any) -> None:
        """Record one engine/service event (lock-free, bounded)."""
        ev = {"t_unix": time.time(), "t_mono": time.monotonic(),
              "kind": kind}
        ev.update(fields)
        self._events.append(ev)

    def events(self) -> list[dict]:
        return list(self._events)

    def dump(self, reason: str, extra: Optional[dict] = None) -> str:
        """Write the artifact; returns its path ("" on I/O failure)."""
        safe = _REASON_RE.sub("_", reason)[:64] or "unknown"
        signals = None
        if self.signal_source is not None:
            try:
                signals = self.signal_source()
            except Exception:  # noqa: BLE001 — snapshot is best-effort
                signals = {"error": "signal snapshot failed"}
        payload = {
            "version": 2,
            "reason": reason,
            "ts_unix": time.time(),
            "pid": os.getpid(),
            "events": self.events(),
            "spans": get_tracer().snapshot(),
            "signals": signals,
            "extra": extra or {},
        }
        try:
            os.makedirs(self._dir, exist_ok=True)
            path = os.path.join(
                self._dir,
                f"flight-{safe}-{os.getpid()}-{next(self._seq)}.json")
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(payload, f, default=str)
            os.replace(tmp, path)
        except OSError:
            self.dump_errors += 1
            return ""
        self.dumps += 1
        self.last_dump_path = path
        return path


_RECORDER: Optional[FlightRecorder] = None


def get_flight_recorder() -> FlightRecorder:
    """The per-process flight recorder singleton."""
    global _RECORDER
    if _RECORDER is None:
        _RECORDER = FlightRecorder()
    return _RECORDER


def set_flight_recorder(rec: Optional[FlightRecorder]) -> None:
    """Swap the process recorder (tests)."""
    global _RECORDER
    _RECORDER = rec
