"""Latency histograms with OpenMetrics exemplars (docs/observability.md).

:class:`ClassHistogram` keeps one Prometheus histogram per SLO class
(non-cumulative bucket counts internally; the exporter renders the
cumulative ``le`` series) plus the most recent exemplar per bucket —
``(trace_id, value, unix_ts)`` — so a bad p99 bucket on a dashboard
links straight to the trace that produced it.

All mutation happens via GIL-atomic ops on per-class state that is in
practice touched by a single thread (the engine step thread); there is
deliberately no lock on this path.
"""

from __future__ import annotations

import bisect
import time
from typing import Optional

__all__ = ["ClassHistogram"]


class _ClassState:
    __slots__ = ("counts", "sum", "count", "exemplars")

    def __init__(self, n_buckets: int) -> None:
        self.counts = [0] * (n_buckets + 1)  # +Inf last
        self.sum = 0.0
        self.count = 0
        # bucket index -> (trace_id, value, unix_ts); most recent wins
        self.exemplars: dict[int, tuple[str, float, float]] = {}


class ClassHistogram:
    """Per-class histogram over fixed ``buckets`` (upper bounds in the
    metric's native unit, usually seconds)."""

    def __init__(self, buckets: tuple[float, ...] | list[float]) -> None:
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs:
            raise ValueError("histogram needs at least one bucket")
        self.buckets = bs
        self._by_class: dict[str, _ClassState] = {}

    def observe(self, value: float, slo_class: str,
                trace_id: str = "") -> None:
        st = self._by_class.get(slo_class)
        if st is None:
            # Benign race: two threads may both build a state; one write
            # wins and at most one observation is lost at first touch.
            st = _ClassState(len(self.buckets))
            self._by_class[slo_class] = st
        i = bisect.bisect_left(self.buckets, value)
        st.counts[i] += 1
        st.sum += value
        st.count += 1
        if trace_id:
            st.exemplars[i] = (trace_id, float(value), time.time())

    # -- exporter surface ------------------------------------------------

    def classes(self) -> list[str]:
        return sorted(self._by_class)

    def total_count(self) -> int:
        return sum(st.count for st in self._by_class.values())

    def series(self, slo_class: str):
        """``(cumulative_counts, sum, count, exemplars)`` for one class;
        cumulative_counts has ``len(buckets)+1`` entries (last = +Inf ==
        count).  Exemplars keyed by the same bucket index."""
        st = self._by_class.get(slo_class)
        if st is None:
            n = len(self.buckets) + 1
            return [0] * n, 0.0, 0, {}
        cum, running = [], 0
        for c in st.counts:
            running += c
            cum.append(running)
        return cum, st.sum, st.count, dict(st.exemplars)

    def quantile(self, slo_class: str, q: float) -> Optional[float]:
        """Linear-interpolated quantile estimate from bucket counts
        (bench assertions; None with no data)."""
        cum, _, count, _ = self.series(slo_class)
        if count == 0:
            return None
        target = q * count
        lo = 0.0
        for i, b in enumerate(self.buckets):
            if cum[i] >= target:
                prev = cum[i - 1] if i else 0
                width = b - lo
                frac = (target - prev) / max(1, cum[i] - prev)
                return lo + width * frac
            lo = b
        return self.buckets[-1]
