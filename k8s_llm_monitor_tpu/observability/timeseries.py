"""Bounded in-process time-series store (docs/observability.md).

The telemetry plane's storage layer: a dict of fixed-capacity rings of
``(t, value)`` points keyed by ``(name, sorted-label-items)``.  Zero
dependencies, one lock, O(capacity) memory per series with a hard cap on
the number of series — the store can run inside every replica and the
router forever without growing.

Semantics the query layer is built on:

  * **NaN is a marker, not garbage.**  Scrapers record an explicit NaN
    when a signal exists but has no measurement (the PR 7 exposition
    rule: absent labels silently mix populations; NaN says "not measured
    *here*, *now*").  ``last()`` returns the newest raw point — NaN
    passes through, so staleness markers survive the query layer —
    while the windowed math (``rate``/``delta``/``ema``/``quantile``)
    skips non-finite points.
  * **Deterministic under a fake clock.**  Both ``record()`` and the
    window queries take the time axis from the injectable ``clock``
    (overridable per call via ``t=``/``now=``), so tests drive the
    exact same point sequence to the exact same answers.
"""

from __future__ import annotations

import math
import time
from collections import deque
from typing import Callable, Iterable, Optional

from k8s_llm_monitor_tpu.devtools.lockcheck import guarded_by, make_lock

__all__ = ["TimeSeriesStore"]

LabelItems = tuple[tuple[str, str], ...]
SeriesKey = tuple[str, LabelItems]


def _label_items(labels: Optional[dict]) -> LabelItems:
    return tuple(sorted((str(k), str(v)) for k, v in (labels or {}).items()))


def _finite(points: Iterable[tuple[float, float]]) -> list[tuple[float, float]]:
    return [(t, v) for t, v in points if math.isfinite(v)]


@guarded_by("_lock", "_series", "points_total", "dropped_series_total")
class TimeSeriesStore:
    """Fixed-capacity ring buffer per ``(name, labels)`` series.

    ``capacity`` bounds points per series; ``max_series`` bounds the
    label-cardinality blast radius — a scraper bug that mints unbounded
    label values drops new series (counted) instead of eating the heap.
    """

    def __init__(self, capacity: int = 512, max_series: int = 2048,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.capacity = max(2, int(capacity))
        self.max_series = max(1, int(max_series))
        self._clock = clock
        self.points_total = 0
        self.dropped_series_total = 0
        self._series: dict[SeriesKey, deque[tuple[float, float]]] = {}
        # Created last (lockcheck: __init__ writes are construction).
        self._lock = make_lock("observability.timeseries")

    # -- writing ---------------------------------------------------------

    def record(self, name: str, value: float,
               labels: Optional[dict] = None,
               t: Optional[float] = None) -> None:
        """Append one point.  ``value`` may be NaN (explicit "unmeasured"
        marker); ``t`` defaults to the store clock.  Never raises on a
        bad value — a telemetry write must not take down its caller."""
        try:
            v = float(value)
        except (TypeError, ValueError):
            v = float("nan")
        key = (str(name), _label_items(labels))
        stamp = self._clock() if t is None else float(t)
        with self._lock:
            ring = self._series.get(key)
            if ring is None:
                if len(self._series) >= self.max_series:
                    self.dropped_series_total += 1
                    return
                ring = deque(maxlen=self.capacity)
                self._series[key] = ring
            ring.append((stamp, v))
            self.points_total += 1

    def evict(self, labels: dict) -> int:
        """Drop every series whose labels contain ``labels`` as a subset;
        returns the number of series removed.  Fleet-membership GC: when a
        replica leaves, its ``{replica=...}`` series must not linger as
        permanently-stale signal targets (or crowd new replicas out of the
        ``max_series`` cap)."""
        want = _label_items(labels)
        if not want:
            return 0
        with self._lock:
            doomed = [key for key in self._series
                      if set(want) <= set(key[1])]
            for key in doomed:
                del self._series[key]
        return len(doomed)

    # -- reading ---------------------------------------------------------

    def series_count(self) -> int:
        with self._lock:
            return len(self._series)

    def totals(self) -> dict:
        """Store self-accounting for the exporter: live series, points
        ever recorded, series refused at the cardinality cap."""
        with self._lock:
            return {
                "series": len(self._series),
                "points_total": self.points_total,
                "dropped_series_total": self.dropped_series_total,
            }

    def names(self) -> list[str]:
        with self._lock:
            return sorted({name for name, _ in self._series})

    def keys(self, name: Optional[str] = None) -> list[SeriesKey]:
        with self._lock:
            return sorted(k for k in self._series
                          if name is None or k[0] == name)

    def points(self, name: str, labels: Optional[dict] = None,
               window_s: Optional[float] = None,
               now: Optional[float] = None) -> list[tuple[float, float]]:
        """Chronological points of one exact series, optionally clipped
        to the trailing ``window_s``.  Empty list when the series does
        not exist — queries turn that into NaN, not an error."""
        key = (str(name), _label_items(labels))
        with self._lock:
            ring = self._series.get(key)
            pts = list(ring) if ring is not None else []
        if window_s is not None and pts:
            anchor = (self._clock() if now is None else now) - float(window_s)
            pts = [(t, v) for t, v in pts if t >= anchor]
        return pts

    def last(self, name: str, labels: Optional[dict] = None,
             window_s: Optional[float] = None,
             now: Optional[float] = None) -> float:
        """Newest raw value in the window (NaN markers pass through);
        NaN when the series is absent or the window is empty."""
        pts = self.points(name, labels, window_s, now)
        return pts[-1][1] if pts else float("nan")

    def delta(self, name: str, labels: Optional[dict] = None,
              window_s: Optional[float] = None,
              now: Optional[float] = None) -> float:
        """last - first over the finite points in the window; NaN with
        fewer than two finite points."""
        pts = _finite(self.points(name, labels, window_s, now))
        if len(pts) < 2:
            return float("nan")
        return pts[-1][1] - pts[0][1]

    def rate(self, name: str, labels: Optional[dict] = None,
             window_s: Optional[float] = None,
             now: Optional[float] = None) -> float:
        """(last - first) / (t_last - t_first) over the finite points in
        the window — per-second growth; NaN with fewer than two finite
        points or a zero time span."""
        pts = _finite(self.points(name, labels, window_s, now))
        if len(pts) < 2:
            return float("nan")
        dt = pts[-1][0] - pts[0][0]
        if dt <= 0.0:
            return float("nan")
        return (pts[-1][1] - pts[0][1]) / dt

    def ema(self, name: str, labels: Optional[dict] = None,
            window_s: Optional[float] = None,
            half_life_s: float = 10.0,
            now: Optional[float] = None) -> float:
        """Irregular-interval exponential moving average over the finite
        points in the window: each step decays the running value by
        ``0.5 ** (dt / half_life_s)``.  Deterministic — same points, same
        answer.  NaN when no finite point is in the window."""
        pts = _finite(self.points(name, labels, window_s, now))
        if not pts:
            return float("nan")
        hl = max(1e-9, float(half_life_s))
        value = pts[0][1]
        for (t_prev, _), (t_cur, v) in zip(pts, pts[1:]):
            w = 0.5 ** (max(0.0, t_cur - t_prev) / hl)
            value = w * value + (1.0 - w) * v
        return value

    def quantile(self, name: str, q: float,
                 labels: Optional[dict] = None,
                 window_s: Optional[float] = None,
                 now: Optional[float] = None) -> float:
        """Linear-interpolated quantile (q in [0, 1]; p50 = 0.5, p99 =
        0.99) of the finite values in the window; NaN when empty."""
        vals = sorted(v for _, v in _finite(
            self.points(name, labels, window_s, now)))
        if not vals:
            return float("nan")
        qq = min(1.0, max(0.0, float(q)))
        pos = qq * (len(vals) - 1)
        lo = int(math.floor(pos))
        hi = int(math.ceil(pos))
        if lo == hi:
            return vals[lo]
        frac = pos - lo
        return vals[lo] * (1.0 - frac) + vals[hi] * frac

    # -- export ----------------------------------------------------------

    def export(self, name: str, window_s: Optional[float] = None,
               label_filter: Optional[dict] = None) -> list[dict]:
        """JSON-safe dump of every series under ``name`` whose labels
        contain ``label_filter`` as a subset.  Non-finite values become
        ``None`` — strict-JSON clients must not choke on NaN markers."""
        out = []
        for key in self.keys(name):
            labels = dict(key[1])
            if label_filter and any(labels.get(k) != str(v)
                                    for k, v in label_filter.items()):
                continue
            pts = self.points(key[0], labels, window_s)
            out.append({
                "name": key[0],
                "labels": labels,
                "points": [
                    [round(t, 4), round(v, 6) if math.isfinite(v) else None]
                    for t, v in pts],
            })
        return out

    def window_snapshot(self, window_s: float) -> dict:
        """The flight-recorder artifact block: every series clipped to
        the trailing window (the load trajectory into a crash)."""
        series = []
        for name in self.names():
            series.extend(self.export(name, window_s=window_s))
        return {"window_s": float(window_s),
                "t_mono": self._clock(),
                "series": series}
