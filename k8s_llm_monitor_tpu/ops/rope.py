"""Rotary position embeddings.

Uses the "split-half" rotation convention (rotate_half), matching the
HuggingFace Llama/Qwen2 implementations so checkpoints load without permuting
Q/K projection rows.  Angles are computed in float32 and applied in float32,
then cast back to the activation dtype — bf16 cos/sin tables measurably hurt
long-context quality.
"""

from __future__ import annotations

from typing import Mapping, Optional

import jax.numpy as jnp
import numpy as np


def _llama3_scale_inv_freq(
    inv_freq: np.ndarray, scaling: Mapping[str, float]
) -> np.ndarray:
    """Llama-3.1 ``rope_scaling`` (``rope_type: "llama3"``) frequency warp.

    Low frequencies (wavelength > low_freq_wavelen) are divided by ``factor``;
    high frequencies pass through; the band between interpolates smoothly.
    Computed host-side in numpy — the result is a compile-time constant.
    """
    factor = float(scaling.get("factor", 8.0))
    low_freq_factor = float(scaling.get("low_freq_factor", 1.0))
    high_freq_factor = float(scaling.get("high_freq_factor", 4.0))
    old_ctx = float(scaling.get("original_max_position_embeddings", 8192))

    wavelen = 2.0 * np.pi / inv_freq
    low_freq_wavelen = old_ctx / low_freq_factor
    high_freq_wavelen = old_ctx / high_freq_factor

    smooth = (old_ctx / wavelen - low_freq_factor) / (
        high_freq_factor - low_freq_factor
    )
    smoothed = (1.0 - smooth) * inv_freq / factor + smooth * inv_freq
    out = np.where(wavelen > low_freq_wavelen, inv_freq / factor, inv_freq)
    mid = (wavelen <= low_freq_wavelen) & (wavelen >= high_freq_wavelen)
    return np.where(mid, smoothed, out).astype(np.float32)


def rope_angles(
    positions: jnp.ndarray,
    head_dim: int,
    theta: float,
    scaling: Optional[Mapping[str, float]] = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """cos/sin tables for the given absolute positions.

    Args:
      positions: int32 array of any shape ``[...]``.
      head_dim: per-head dimension (even).
      theta: RoPE base (5e5 for Llama-3, 1e6 for Qwen2-72B).
      scaling: optional HF ``rope_scaling`` dict.  ``rope_type``/``type`` of
        ``"llama3"`` applies the Llama-3.1 frequency warp; ``"linear"``
        divides positions by ``factor``; None/``"default"`` is identity.

    Returns:
      (cos, sin), each float32 of shape ``[..., head_dim]`` — the half-dim
      frequency table tiled twice along the last axis (rotate_half convention).
    """
    half = head_dim // 2
    inv_freq = 1.0 / (
        theta ** (np.arange(0, half, dtype=np.float32) / half)
    )
    pos = positions.astype(jnp.float32)
    if scaling:
        kind = scaling.get("rope_type", scaling.get("type", "default"))
        if kind == "llama3":
            inv_freq = _llama3_scale_inv_freq(inv_freq, scaling)
        elif kind == "linear":
            pos = pos / float(scaling.get("factor", 1.0))
        elif kind not in ("default", None):
            raise NotImplementedError(f"rope_scaling type {kind!r}")
    ang = pos[..., None] * jnp.asarray(inv_freq)  # [..., half]
    ang = jnp.concatenate([ang, ang], axis=-1)  # [..., head_dim]
    return jnp.cos(ang), jnp.sin(ang)


def _rotate_half(x: jnp.ndarray) -> jnp.ndarray:
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([-x2, x1], axis=-1)


def apply_rope(
    x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray
) -> jnp.ndarray:
    """Rotate ``x`` of shape ``[..., seq, heads, head_dim]``.

    cos/sin have shape ``[..., seq, head_dim]`` and broadcast over the heads
    axis.
    """
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    cos = cos[..., :, None, :]
    sin = sin[..., :, None, :]
    out = x32 * cos + _rotate_half(x32) * sin
    return out.astype(dtype)
