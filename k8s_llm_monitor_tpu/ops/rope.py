"""Rotary position embeddings.

Uses the "split-half" rotation convention (rotate_half), matching the
HuggingFace Llama/Qwen2 implementations so checkpoints load without permuting
Q/K projection rows.  Angles are computed in float32 and applied in float32,
then cast back to the activation dtype — bf16 cos/sin tables measurably hurt
long-context quality.
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_angles(positions: jnp.ndarray, head_dim: int, theta: float) -> tuple[jnp.ndarray, jnp.ndarray]:
    """cos/sin tables for the given absolute positions.

    Args:
      positions: int32 array of any shape ``[...]``.
      head_dim: per-head dimension (even).
      theta: RoPE base (5e5 for Llama-3, 1e6 for Qwen2-72B).

    Returns:
      (cos, sin), each float32 of shape ``[..., head_dim]`` — the half-dim
      frequency table tiled twice along the last axis (rotate_half convention).
    """
    half = head_dim // 2
    inv_freq = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    ang = positions.astype(jnp.float32)[..., None] * inv_freq  # [..., half]
    ang = jnp.concatenate([ang, ang], axis=-1)  # [..., head_dim]
    return jnp.cos(ang), jnp.sin(ang)


def _rotate_half(x: jnp.ndarray) -> jnp.ndarray:
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([-x2, x1], axis=-1)


def apply_rope(
    x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray
) -> jnp.ndarray:
    """Rotate ``x`` of shape ``[..., seq, heads, head_dim]``.

    cos/sin have shape ``[..., seq, head_dim]`` and broadcast over the heads
    axis.
    """
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    cos = cos[..., :, None, :]
    sin = sin[..., :, None, :]
    out = x32 * cos + _rotate_half(x32) * sin
    return out.astype(dtype)
