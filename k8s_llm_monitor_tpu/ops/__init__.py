"""TPU compute primitives: norms, RoPE, attention, sampling."""

from k8s_llm_monitor_tpu.ops.norms import rms_norm
from k8s_llm_monitor_tpu.ops.rope import apply_rope, rope_angles
from k8s_llm_monitor_tpu.ops.attention import (
    causal_attention,
    decode_attention,
    paged_decode_attention,
)
from k8s_llm_monitor_tpu.ops.sampling import sample_tokens

__all__ = [
    "rms_norm",
    "apply_rope",
    "rope_angles",
    "causal_attention",
    "decode_attention",
    "paged_decode_attention",
    "sample_tokens",
]
