"""Attention primitives: dense causal prefill, contiguous-cache decode, and
paged-KV decode.

All variants are GQA-aware (``num_heads`` query heads grouped over
``num_kv_heads`` KV heads) and run the softmax in float32.

Layout conventions (chosen for TPU):
  activations  [batch, seq, heads, head_dim]
  paged KV     [num_blocks, block_size, kv_heads * head_dim] — the fused
               lane layout of models/llama.py:KVPages (128-lane-aligned
               page rows the Pallas kernel DMAs directly)
  block table  [batch, max_blocks_per_seq] int32 (block ids; entries past a
               sequence's pages are 0, the reserved null block)

The pure-XLA paged path here is the reference implementation and the CPU/test
fallback; the Pallas TPU kernel lives in ops/pallas_attention.py and is
selected by ``select_attn_impl`` (used by serving/engine.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)


def _repeat_kv(x: jnp.ndarray, q_per_kv: int) -> jnp.ndarray:
    """[..., kv_heads, d] -> [..., kv_heads * q_per_kv, d]."""
    if q_per_kv == 1:
        return x
    return jnp.repeat(x, q_per_kv, axis=-2)


def causal_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    q_positions: jnp.ndarray | None = None,
    kv_len: jnp.ndarray | None = None,
    scale: float | None = None,
    logit_softcap: float = 0.0,
    window: int = 0,
) -> jnp.ndarray:
    """Dense causal attention for prefill.

    Args:
      q: [B, S, H, D].
      k, v: [B, T, KVH, D] with T >= S (T may include a cached prefix).
      q_positions: [B, S] absolute position of each query token; defaults to
        arange(S) + (T - S) (i.e. queries are the last S positions of kv).
      kv_len: [B] valid kv length per sequence (keys at index >= kv_len are
        masked out).  Defaults to T.
      scale: query scale; defaults to D**-0.5 (Gemma-2 uses
        query_pre_attn_scalar**-0.5 instead).
      logit_softcap: tanh soft cap on attention logits (Gemma-2; 0 = off).
      window: sliding-window size — queries attend only to keys within the
        last ``window`` positions (0 = global).  Static per call/layer.

    Returns:
      [B, S, H, D] in q.dtype.
    """
    B, S, H, D = q.shape
    T, KVH = k.shape[1], k.shape[2]
    q_per_kv = H // KVH

    k = _repeat_kv(k, q_per_kv)
    v = _repeat_kv(v, q_per_kv)

    if scale is None:
        scale = 1.0 / (D ** 0.5)
    logits = jnp.einsum("bshd,bthd->bhst", q.astype(jnp.float32), k.astype(jnp.float32))
    logits *= scale
    if logit_softcap:
        logits = logit_softcap * jnp.tanh(logits / logit_softcap)

    if q_positions is None:
        q_positions = jnp.arange(S, dtype=jnp.int32)[None, :] + (T - S)
        q_positions = jnp.broadcast_to(q_positions, (B, S))
    kv_positions = jnp.arange(T, dtype=jnp.int32)
    causal = q_positions[:, :, None] >= kv_positions[None, None, :]  # [B, S, T]
    if kv_len is not None:
        causal = causal & (kv_positions[None, None, :] < kv_len[:, None, None])
    if window > 0:
        causal = causal & (kv_positions[None, None, :]
                           > q_positions[:, :, None] - window)
    logits = jnp.where(causal[:, None, :, :], logits, NEG_INF)

    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhst,bthd->bshd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def decode_attention(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    scale: float | None = None,
    logit_softcap: float = 0.0,
    window: int = 0,
) -> jnp.ndarray:
    """Single-token decode against a contiguous KV cache.

    Args:
      q: [B, 1, H, D].
      k_cache, v_cache: [B, T, KVH, D].
      lengths: [B] int32 — number of valid KV entries per sequence (the new
        token's K/V must already be written at index lengths-1).
      scale / logit_softcap / window: as in ``causal_attention`` (the
      query position is lengths-1, so the window keeps keys in
      ``(lengths-1-window, lengths)``).
    """
    B, _, H, D = q.shape
    T, KVH = k_cache.shape[1], k_cache.shape[2]
    q_per_kv = H // KVH

    k = _repeat_kv(k_cache, q_per_kv)
    v = _repeat_kv(v_cache, q_per_kv)

    if scale is None:
        scale = 1.0 / (D ** 0.5)
    logits = jnp.einsum("bshd,bthd->bhst", q.astype(jnp.float32), k.astype(jnp.float32))
    logits *= scale
    if logit_softcap:
        logits = logit_softcap * jnp.tanh(logits / logit_softcap)
    kv_positions = jnp.arange(T, dtype=jnp.int32)[None, :]
    valid = kv_positions < lengths[:, None]                          # [B, T]
    if window > 0:
        valid = valid & (kv_positions > (lengths - 1)[:, None] - window)
    logits = jnp.where(valid[:, None, None, :], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhst,bthd->bshd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def gather_pages(
    pages: jnp.ndarray, block_table: jnp.ndarray
) -> jnp.ndarray:
    """Gather a sequence's KV pages into a contiguous view.

    Args:
      pages: [num_blocks, block_size, KVH*D] (fused lane layout — see
        models/llama.py:KVPages).
      block_table: [B, max_blocks] int32 (entries may be -1 / garbage past the
        sequence's length — callers mask by length).

    Returns:
      [B, max_blocks * block_size, KVH*D].
    """
    B, max_blocks = block_table.shape
    bs = pages.shape[1]
    safe = jnp.maximum(block_table, 0)
    g = pages[safe]  # [B, max_blocks, bs, KVH*D]
    return g.reshape(B, max_blocks * bs, g.shape[3])


def paged_decode_attention(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    block_table: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    scale: float | None = None,
    logit_softcap: float = 0.0,
    window: int = 0,
) -> jnp.ndarray:
    """Single-token decode against a paged (block) KV cache — XLA reference.

    Gathers each sequence's blocks into a contiguous [B, max_blocks*bs, F]
    view then runs masked decode attention (unfusing F -> [KVH, D] on the
    gathered activation only).  The Pallas kernel avoids the gather by
    streaming pages HBM->VMEM per block; this version is the semantics
    reference, the CPU fallback, and the only impl carrying the Gemma-2
    extras (custom scale / logit softcap / sliding window).
    """
    B = q.shape[0]
    D = q.shape[-1]
    k = gather_pages(k_pages, block_table).reshape(B, -1, k_pages.shape[2] // D, D)
    v = gather_pages(v_pages, block_table).reshape(B, -1, v_pages.shape[2] // D, D)
    return decode_attention(q, k, v, lengths, scale=scale,
                            logit_softcap=logit_softcap, window=window)


def paged_decode_attention_quant(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    k_scale: jnp.ndarray,
    v_scale: jnp.ndarray,
    block_table: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    scale: float | None = None,
    logit_softcap: float = 0.0,
    window: int = 0,
) -> jnp.ndarray:
    """Quantized-KV twin of ``paged_decode_attention`` — XLA reference.

    ``k_pages``/``v_pages`` hold int8/fp8 rows; ``k_scale``/``v_scale``
    are the per-(token, head) float32 scales [num_blocks, bs, KVH]
    (models/llama.py:KVPages).  Scales are gathered alongside the pages
    and applied on the small gathered activation — dequantize-on-read,
    so the resident pool never materializes in float.  Under a GSPMD
    mesh this partitions automatically when pages and scales both shard
    their kv-head axis (parallel/sharding.py emits matching specs), which
    is why the mesh path needs no quant-aware shard_map kernel.
    """
    B = q.shape[0]
    D = q.shape[-1]
    KVH = k_pages.shape[2] // D
    ks = gather_pages(k_scale, block_table)            # [B, T, KVH]
    vs = gather_pages(v_scale, block_table)
    k = (gather_pages(k_pages, block_table).astype(jnp.float32)
         .reshape(B, -1, KVH, D) * ks[..., None])
    v = (gather_pages(v_pages, block_table).astype(jnp.float32)
         .reshape(B, -1, KVH, D) * vs[..., None])
    return decode_attention(q, k.astype(q.dtype), v.astype(q.dtype),
                            lengths, scale=scale,
                            logit_softcap=logit_softcap, window=window)


def paged_verify_attention(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    block_table: jnp.ndarray,
    start: jnp.ndarray,
    lengths: jnp.ndarray,
) -> jnp.ndarray:
    """Multi-query paged attention — XLA gather reference for the Pallas
    verify kernel (speculative decode's k+1-token scoring pass).

    Query token ``i`` sits at absolute position ``start[b] + i`` and
    attends causally through itself over the gathered pages.  ``lengths``
    counts valid query tokens (0 = inactive lane; output rows garbage,
    discarded by the caller).  A thin wrapper over gather_pages +
    causal_attention so the serving path and this Pallas-parity reference
    can never drift apart.
    """
    B, S, H, D = q.shape
    KVH = k_pages.shape[2] // D
    kk = gather_pages(k_pages, block_table).reshape(B, -1, KVH, D)
    vv = gather_pages(v_pages, block_table).reshape(B, -1, KVH, D)
    positions = start[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]
    return causal_attention(q, kk, vv, q_positions=positions,
                            kv_len=start + lengths)


# Table width (tokens) above which the Pallas verify kernel beats the XLA
# gather for the spec verify pass.  The gather reads the FULL static table
# width per lane per layer, so its cost is O(max_blocks*bs) regardless of
# live context; the kernel streams only real pages but serializes its
# batch tile per program.  Measured on v5e-1 / 8B int8 spec decode:
# 336-token tables gather wins (235 vs 175 tok/s); 2048-token tables the
# kernel wins (76 vs 72 tok/s) and its margin grows with table width.
VERIFY_KERNEL_MIN_TABLE_TOKENS = 2048


def select_verify_impl(platform: str | None = None, cfg=None, mesh=None,
                       max_table_tokens: int | None = None):
    """Pick the verify (multi-query paged) attention implementation.

    Mirrors ``select_attn_impl``: single-chip TPU with kernel-compatible
    geometry gets the Pallas verify kernel; meshes and CPU get the XLA
    gather reference (which partitions under GSPMD automatically).
    ``max_table_tokens`` (the engine's per-seq capacity) gates the kernel
    to long-table configs where its O(real ctx) streaming beats the
    gather's O(table width) reads.
    Returns a callable (q, k_pages, v_pages, table, start, lengths).
    """
    import logging

    logger = logging.getLogger("k8s_llm_monitor_tpu.ops")
    if platform is None:
        platform = jax.default_backend()
    if cfg is not None and getattr(cfg, "has_attn_extras", False):
        # Extras models use _prefill_impl's own gather branch, which
        # threads the per-layer parameters (models/llama.py).
        return None
    if mesh is not None or platform != "tpu":
        return paged_verify_attention
    if (max_table_tokens is not None
            and max_table_tokens < VERIFY_KERNEL_MIN_TABLE_TOKENS):
        return paged_verify_attention
    if cfg is not None and not _pallas_geometry_ok(cfg, 1):
        logger.warning(
            "Pallas verify kernel unavailable for %s (geometry gate); "
            "speculative verify uses the XLA gather fallback",
            getattr(cfg, "name", "model"))
        return paged_verify_attention
    try:
        from k8s_llm_monitor_tpu.ops.pallas_attention import (
            paged_verify_attention_pallas,
        )

        return paged_verify_attention_pallas
    except Exception as exc:  # pragma: no cover - import/lowering unavailable
        logger.warning(
            "Pallas verify kernel failed to import (%s); speculative "
            "verify uses the XLA gather fallback", exc)
        return paged_verify_attention


def make_tp_paged_attention(mesh, cfg, interpret: bool = False):
    """Pallas paged decode attention under a GSPMD mesh, via ``shard_map``.

    Paged decode attention is embarrassingly tensor-parallel when the KV
    pages shard on kv-head boundaries (parallel/sharding.py): every query
    head's output depends only on its own kv group's pages, so each device
    runs the kernel on its local head/page shard and NO collective is
    needed — the sharded outputs are exactly the sharded o-projection
    inputs.  Requires ``tp | num_kv_heads`` (the same condition under which
    the pages shard at all); the block-diagonal GQA trick is per-kv-group
    and group boundaries align with the shard cuts.

    ``interpret`` runs the kernel in the Pallas interpreter per shard — the
    CPU-mesh path used by tests and the driver's virtual-device dryrun.
    """
    import functools

    from jax.sharding import PartitionSpec as P

    from k8s_llm_monitor_tpu.ops.pallas_attention import (
        paged_decode_attention_pallas,
    )
    from k8s_llm_monitor_tpu.parallel.mesh import shard_map_compat

    qspec = P(None, None, "model", None)       # query heads over TP
    pspec = P(None, None, "model")             # fused kv lanes over TP

    @functools.partial(
        shard_map_compat, mesh=mesh,
        in_specs=(qspec, pspec, pspec, P(None, None), P(None)),
        out_specs=qspec, check_replication=False)
    def attn(q, k_pages, v_pages, block_table, lengths):
        return paged_decode_attention_pallas(
            q, k_pages, v_pages, block_table, lengths, interpret=interpret)

    return attn


def _pallas_geometry_ok(cfg, tp: int) -> bool:
    """Mosaic lane-alignment gate for the (per-shard) fused page rows."""
    fused_local = cfg.num_kv_heads * cfg.head_dim_ // tp
    return fused_local % 128 == 0 and cfg.head_dim_ <= 128


def select_attn_impl(platform: str | None = None, cfg=None, mesh=None):
    """Pick the paged-decode attention implementation for the backend.

    Single device on TPU gets the Pallas kernel (block-table-driven
    HBM->VMEM streaming, ops/pallas_attention.py); a GSPMD ``mesh`` gets
    the kernel wrapped in ``shard_map`` over the ``model`` axis (compiled
    on TPU, interpreter on the CPU-mesh test/dryrun path); everything else
    gets the XLA gather fallback above.

    ``cfg`` (a ModelConfig) gates on kernel geometry: the kernel DMAs pages
    as [block_size, kv_heads*head_dim] rows, and Mosaic requires that fused
    lane dim to be 128-aligned (and head_dim <= 128).  Models that fail the
    gate (tiny test configs) get the XLA path with a logged warning — never
    a silent compile-time crash or a quiet performance cliff.
    """
    import logging

    logger = logging.getLogger("k8s_llm_monitor_tpu.ops")
    if platform is None:
        platform = jax.default_backend()

    if cfg is not None and getattr(cfg, "has_attn_extras", False):
        # Gemma-2-style extras (query scale / softcap / sliding window)
        # live only in the gather reference; the Pallas kernel has no
        # cap/window support (Gemma's head_dim=256 fails its geometry
        # gate anyway).
        return paged_decode_attention

    if mesh is not None:
        tp = mesh.shape.get("model", 1)
        if cfg is None or tp < 1 or cfg.num_kv_heads % tp != 0:
            # Pages replicate in this regime (see kv_pages_partition_specs);
            # the gather fallback partitions under GSPMD automatically.
            if cfg is not None:
                logger.warning(
                    "TP=%d does not divide %d KV heads; paged attention "
                    "uses the XLA gather fallback with replicated pages",
                    tp, cfg.num_kv_heads)
            return paged_decode_attention
        interpret = platform != "tpu"
        if not interpret and not _pallas_geometry_ok(cfg, tp):
            logger.warning(
                "Pallas kernel geometry gate failed for %s at TP=%d "
                "(per-shard fused lanes not 128-aligned); using the XLA "
                "gather fallback", getattr(cfg, "name", "model"), tp)
            return paged_decode_attention
        try:
            return make_tp_paged_attention(mesh, cfg, interpret=interpret)
        except Exception as exc:  # pragma: no cover
            logger.warning(
                "TP Pallas paged attention unavailable (%s); using the XLA "
                "gather fallback", exc)
            return paged_decode_attention

    if platform != "tpu":
        return paged_decode_attention
    if cfg is not None and not _pallas_geometry_ok(cfg, 1):
        logger.warning(
            "Pallas paged-attention kernel unavailable for %s "
            "(kv_heads*head_dim=%d not 128-aligned or head_dim>128); "
            "using the XLA gather fallback — O(B*max_ctx) HBM traffic "
            "per decode step", getattr(cfg, "name", "model"),
            cfg.num_kv_heads * cfg.head_dim_)
        return paged_decode_attention
    try:
        from k8s_llm_monitor_tpu.ops.pallas_attention import (
            paged_decode_attention_pallas,
        )

        return paged_decode_attention_pallas
    except Exception as exc:  # pragma: no cover - import/lowering unavailable
        logger.warning(
            "Pallas paged-attention kernel failed to import (%s); using the "
            "XLA gather fallback — O(B*max_ctx) HBM traffic per decode "
            "step", exc)
        return paged_decode_attention


def select_decode_impl(platform: str | None = None, cfg=None, mesh=None,
                       mode: str = "auto", kv_quant: str = ""):
    """Pick the decode-step attention path, including the fused fast-path.

    ``mode`` (EngineConfig.decode_path / K8SLLM_DECODE_PATH env):
      * ``"auto"``   — the fused RoPE+append+attention kernel
        (ops/pallas_attention.py:paged_decode_attention_fused) on a
        single TPU chip when the model passes the geometry gate;
        otherwise whatever ``select_attn_impl`` picks.
      * ``"fused"``  — force the fused kernel (interpreter off-TPU; used
        by parity tests and the bench's fused leg).  Raises if the model
        can't take it (extras models, odd head_dim) rather than silently
        falling back — the caller asked for a specific path.
      * ``"gather"`` — force the XLA gather fallback (the numerics
        oracle; also what the fused path is diffed against in tests).
      * ``"pallas"`` — force the split kernel pipeline (Pallas attention
        with the XLA rope/scatter around it).

    ``kv_quant`` ("int8"/"fp8", EngineConfig.kv_dtype) selects the
    quantized-KV tier: the fused fast-path becomes the quantized fused
    kernel (quantize-on-append + dequantize-in-kernel, marked
    ``is_fused_quant_decode_impl``); the split "pallas" pipeline has no
    scale support and degrades to the gather/dequant reference with a
    warning.  Non-fused returns are sentinels only — decode_step routes a
    quantized pool through its own gather/dequant branch.

    Returns an attention impl for models/llama.py:decode_step; fused
    impls are marked (``is_fused_decode_impl``) and use the extended
    calling convention (raw q/k/v + angles in, pages out).
    """
    import functools
    import logging

    logger = logging.getLogger("k8s_llm_monitor_tpu.ops")
    if platform is None:
        platform = jax.default_backend()

    def _fused_ok():
        return (mesh is None
                and cfg is not None
                and not getattr(cfg, "has_attn_extras", False)
                and cfg.head_dim_ % 2 == 0
                and _pallas_geometry_ok(cfg, 1))

    def _fused_quant():
        from k8s_llm_monitor_tpu.ops.pallas_attention import (
            paged_decode_attention_fused_quant,
        )

        if platform != "tpu":
            return functools.partial(paged_decode_attention_fused_quant,
                                     interpret=True)
        return paged_decode_attention_fused_quant

    if mode == "gather":
        return paged_decode_attention
    if mode == "pallas":
        if kv_quant:
            logger.warning(
                "decode_path='pallas' has no quantized-KV support; the "
                "split kernel is bypassed for the gather/dequant reference")
            return paged_decode_attention
        return select_attn_impl(platform, cfg=cfg, mesh=mesh)
    if mode == "fused":
        if not _fused_ok():
            raise ValueError(
                "decode_path='fused' but the model/mesh can't take the "
                "fused kernel (mesh, attn extras, odd head_dim, or lane "
                "alignment); use decode_path='auto' for gated selection")
        if kv_quant:
            return _fused_quant()
        from k8s_llm_monitor_tpu.ops.pallas_attention import (
            paged_decode_attention_fused,
        )

        if platform != "tpu":
            return functools.partial(paged_decode_attention_fused,
                                     interpret=True)
        return paged_decode_attention_fused
    if mode != "auto":
        raise ValueError(f"unknown decode_path {mode!r}; expected "
                         "'auto', 'fused', 'gather', or 'pallas'")

    if platform == "tpu" and _fused_ok():
        try:
            if kv_quant:
                return _fused_quant()
            from k8s_llm_monitor_tpu.ops.pallas_attention import (
                paged_decode_attention_fused,
            )

            return paged_decode_attention_fused
        except Exception as exc:  # pragma: no cover - import unavailable
            logger.warning(
                "fused decode kernel failed to import (%s); using the "
                "split path", exc)
    if kv_quant:
        # Mesh or gather regime: decode_step's quant branch gathers pages
        # AND scales (paged_decode_attention_quant) — GSPMD partitions it
        # when both shard their kv-head axis.
        return paged_decode_attention
    return select_attn_impl(platform, cfg=cfg, mesh=mesh)


def make_tp_flash_prefill(mesh, cfg, interpret: bool = False,
                          kv_quant: str = ""):
    """Flash paged prefill under a GSPMD mesh, via ``shard_map``.

    Same TP story as ``make_tp_paged_attention``: the pages shard on
    kv-head boundaries, page ids stay GLOBAL (every chip reads the same
    block-table rows and its own head-slice of each page), queries shard
    their head axis, and no collective is needed — each shard's kernel
    output is exactly its o-projection input.  The per-shard kernel sees
    KVH/tp groups and H/tp heads, so the heads-per-group ratio (and the
    group-major q reshape) is invariant under the split.

    ``kv_quant`` adds the scale planes, sharded exactly with the pages
    (SpecLayout.kv_scales: the kv-heads axis splits when the fused lane
    dim does).
    """
    import functools

    from jax.sharding import PartitionSpec as P

    from k8s_llm_monitor_tpu.ops.pallas_attention import (
        flash_prefill_attention,
    )
    from k8s_llm_monitor_tpu.parallel.mesh import shard_map_compat

    qspec = P(None, None, "model", None)       # query heads over TP
    pspec = P(None, None, "model")             # fused kv lanes / scale heads
    tspec = P(None, None)                      # block tables: global ids

    if kv_quant:
        @functools.partial(
            shard_map_compat, mesh=mesh,
            in_specs=(qspec, pspec, pspec, pspec, pspec, tspec, P(None),
                      P(None)),
            out_specs=qspec, check_replication=False)
        def _attn_sharded(q, k_pages, v_pages, k_scale, v_scale, table,
                          start, lengths):
            return flash_prefill_attention(
                q, k_pages, v_pages, table, start, lengths,
                k_scale=k_scale, v_scale=v_scale, interpret=interpret)

        def attn(q, k_pages, v_pages, table, start, lengths, *,
                 k_scale, v_scale):
            return _attn_sharded(q, k_pages, v_pages, k_scale, v_scale,
                                 table, start, lengths)
    else:
        @functools.partial(
            shard_map_compat, mesh=mesh,
            in_specs=(qspec, pspec, pspec, tspec, P(None), P(None)),
            out_specs=qspec, check_replication=False)
        def _attn_sharded(q, k_pages, v_pages, table, start, lengths):
            return flash_prefill_attention(
                q, k_pages, v_pages, table, start, lengths,
                interpret=interpret)

        def attn(q, k_pages, v_pages, table, start, lengths):
            return _attn_sharded(q, k_pages, v_pages, table, start, lengths)

    attn.flash_prefill = True
    return attn


def select_prefill_impl(platform: str | None = None, cfg=None, mesh=None,
                        mode: str = "auto", kv_quant: str = ""):
    """Pick the prefill-family attention path (fresh / chunk / verify).

    ``mode`` (EngineConfig.prefill_path / K8SLLM_PREFILL_PATH env):
      * ``"auto"``  — the flash paged-prefill kernel
        (ops/pallas_attention.py:flash_prefill_attention) on TPU when the
        geometry passes; the dense XLA path everywhere else.
      * ``"flash"`` — force the kernel (interpreter off-TPU; parity tests,
        traceguard, and the bench's flash legs).  Raises when the model or
        mesh can't take it rather than silently falling back.
      * ``"dense"`` — force the dense XLA oracle: in-flight
        ``causal_attention`` for fresh prefill, ``gather_pages`` + dense
        attention for chunks and verify.

    ``kv_quant`` ("int8"/"fp8", EngineConfig.kv_dtype) only changes the
    mesh wrapper's signature — the kernel itself keys on the scale planes
    it is handed and dequantizes in-kernel, so the quantized pool never
    widens in HBM (the dense chunk path dequantizes the full gathered
    prefix instead).

    Returns ``None`` for the dense path (models/llama.py keeps its
    existing branches — the correctness oracle every flash output is
    tested against) or an impl marked ``is_flash_prefill_impl`` with the
    ``flash_prefill_attention`` calling convention.
    """
    import functools
    import logging

    logger = logging.getLogger("k8s_llm_monitor_tpu.ops")
    if platform is None:
        platform = jax.default_backend()

    if mode == "dense":
        return None
    if mode not in ("auto", "flash"):
        raise ValueError(f"unknown prefill_path {mode!r}; expected "
                         "'auto', 'flash', or 'dense'")

    tp = mesh.shape.get("model", 1) if mesh is not None else 1

    def _flash_ok():
        if cfg is None or getattr(cfg, "has_attn_extras", False):
            return False   # softcap / sliding window live only in dense
        if mesh is not None and (tp < 1 or cfg.num_kv_heads % tp != 0):
            return False   # pages replicate; dense partitions automatically
        if platform != "tpu":
            return True    # interpreter has no lane-alignment constraints
        # Hardware: the kernel DMAs each kv group's own D-lane slice of
        # the fused page rows, so the slice offset g*D must itself be
        # lane-aligned — head_dim must be exactly 128 on top of the
        # fused-row gate (the decode kernels avoid this by copying whole
        # [bs, F] rows, which prefill can't afford at KVH x the traffic).
        return _pallas_geometry_ok(cfg, tp) and cfg.head_dim_ == 128

    def _build():
        from k8s_llm_monitor_tpu.ops.pallas_attention import (
            flash_prefill_attention,
        )

        if mesh is not None:
            return make_tp_flash_prefill(
                mesh, cfg, interpret=platform != "tpu", kv_quant=kv_quant)
        if platform != "tpu":
            return functools.partial(flash_prefill_attention, interpret=True)
        return flash_prefill_attention

    if mode == "flash":
        if not _flash_ok():
            raise ValueError(
                "prefill_path='flash' but the model/mesh can't take the "
                "flash kernel (attn extras, head_dim != 128 on TPU, or a "
                "TP degree that doesn't divide the KV heads); use "
                "prefill_path='auto' for gated selection")
        return _build()

    # auto: flash on TPU when the geometry allows; CPU always stays dense
    # (the interpreter would be a de-optimization, not a fast path) and
    # remains the oracle the flash path is diffed against in tests.
    if platform != "tpu" or not _flash_ok():
        return None
    try:
        return _build()
    except Exception as exc:  # pragma: no cover - import unavailable
        logger.warning(
            "flash prefill kernel unavailable (%s); prefill stays on the "
            "dense XLA path", exc)
        return None
