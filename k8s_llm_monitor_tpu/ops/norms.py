"""Normalization primitives.

RMSNorm runs in float32 regardless of activation dtype — bf16 accumulation of
the mean-square loses enough precision to visibly perturb logits, and XLA fuses
the up/down casts into the surrounding elementwise ops anyway.
"""

from __future__ import annotations

import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-5,
             unit_offset: bool = False) -> jnp.ndarray:
    """Root-mean-square layer norm (no mean-centering, no bias).

    Llama/Qwen convention: normalize in fp32, scale by ``weight``, cast
    back.  ``unit_offset`` selects the Gemma convention: the stored weight
    is a zero-centered delta and the effective scale is ``1 + weight``.
    """
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    x32 = x32 * (1.0 / jnp.sqrt(var + eps))
    w32 = weight.astype(jnp.float32)
    if unit_offset:
        w32 = 1.0 + w32
    return (x32 * w32).astype(dtype)


def layer_norm(
    x: jnp.ndarray, weight: jnp.ndarray, bias: jnp.ndarray, eps: float = 1e-12
) -> jnp.ndarray:
    """Standard LayerNorm (BERT/BGE encoder path)."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mean) / jnp.sqrt(var + eps)
    y = y * weight.astype(jnp.float32) + bias.astype(jnp.float32)
    return y.astype(dtype)
