"""Pallas TPU kernel: paged decode attention over a block KV cache.

The XLA fallback (ops/attention.py:paged_decode_attention) materializes every
sequence's pages into a contiguous ``[B, max_blocks*bs, KVH, D]`` gather per
layer per step — O(B * max_ctx) HBM traffic regardless of actual context
lengths.  This kernel instead streams exactly the pages named in the block
table through VMEM with online (flash-style) softmax accumulation:

  * grid = (batch, max_blocks_per_seq); the block-table entry for grid cell
    (b, j) drives the k/v page BlockSpec index map (scalar-prefetched, so the
    DMA for page j+1 is issued while page j computes — Pallas double-buffers
    revisited specs automatically).
  * pages past a sequence's length map to the null block 0 and are skipped
    with ``pl.when`` (consecutive identical indices skip the re-copy).
  * GQA: each kv head's page slice serves its ``H // KVH`` query heads; the
    online-softmax state (m, l, acc) lives in VMEM scratch across grid steps.

Selected by ops/attention.py:select_attn_impl on TPU (single-chip engine);
CPU tests run it in interpreter mode for parity with the XLA reference.
Capability context: the reference has no kernels of any kind (pure Go control
plane); this is part of the new TPU serving obligation (SURVEY.md §7 hard
part #1).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)


def _decode_kernel(
    # scalar prefetch
    tables_ref,            # [B, NB] int32 block ids
    lens_ref,              # [B] int32 valid kv length per sequence
    # blocks
    q_ref,                 # [1, H, D]
    k_ref,                 # [1, bs, KVH, D] — page tables_ref[b, j]
    v_ref,                 # [1, bs, KVH, D]
    # out
    o_ref,                 # [1, H, D]
    # scratch (persists across the j grid dimension)
    m_ref,                 # [H, 128] f32 running max
    l_ref,                 # [H, 128] f32 running denominator
    acc_ref,               # [H, D] f32 running numerator
    *,
    kv_heads: int,
    q_per_kv: int,
):
    b = pl.program_id(0)
    j = pl.program_id(1)
    bs = k_ref.shape[1]
    D = q_ref.shape[2]

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    length = lens_ref[b]
    start = j * bs

    @pl.when(start < length)
    def _block():
        scale = D ** -0.5
        # Positions covered by this page, masked against the true length.
        pos = start + jax.lax.broadcasted_iota(jnp.int32, (1, bs), 1)
        valid = pos < length                                   # [1, bs]
        for h in range(kv_heads):
            sl = slice(h * q_per_kv, (h + 1) * q_per_kv)
            qh = q_ref[0, sl, :].astype(jnp.float32) * scale   # [qpk, D]
            kh = k_ref[0, :, h, :].astype(jnp.float32)         # [bs, D]
            s = jax.lax.dot_general(
                qh, kh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )                                                   # [qpk, bs]
            s = jnp.where(valid, s, NEG_INF)

            m_prev = m_ref[sl, :]                               # [qpk, 128]
            l_prev = l_ref[sl, :]
            m_cur = jnp.max(s, axis=-1, keepdims=True)          # [qpk, 1]
            m_new = jnp.maximum(m_prev, m_cur)                  # [qpk, 128]
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new[:, :1])                       # [qpk, bs]
            l_ref[sl, :] = alpha * l_prev + jnp.sum(
                p, axis=-1, keepdims=True)
            m_ref[sl, :] = m_new

            vh = v_ref[0, :, h, :].astype(jnp.float32)          # [bs, D]
            pv = jax.lax.dot_general(
                p, vh, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )                                                   # [qpk, D]
            acc_ref[sl, :] = alpha[:, :D] * acc_ref[sl, :] + pv

    @pl.when(j == pl.num_programs(1) - 1)
    def _finish():
        o_ref[0] = (acc_ref[:] / l_ref[:, :D]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_decode_attention_pallas(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    block_table: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    interpret: bool = False,
) -> jnp.ndarray:
    """Single-token paged decode attention (drop-in for the XLA fallback).

    Args:
      q: [B, 1, H, D].
      k_pages, v_pages: [num_blocks, bs, KVH, D].
      block_table: [B, max_blocks_per_seq] int32 (entries past the sequence's
        pages must be 0, the null block — serving/kv_cache.py guarantees it).
      lengths: [B] int32 valid kv length (>= 1 for active lanes; the new
        token's K/V must already be written at index lengths-1).
      interpret: run in the Pallas interpreter (CPU parity tests).

    Returns:
      [B, 1, H, D] in q.dtype.
    """
    B, S, H, D = q.shape
    assert S == 1, f"decode kernel expects one query token, got {S}"
    _, bs, KVH, Dk = k_pages.shape
    assert D == Dk and D <= 128, (D, Dk)
    NB = block_table.shape[1]
    q_per_kv = H // KVH

    kernel = functools.partial(
        _decode_kernel, kv_heads=KVH, q_per_kv=q_per_kv)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, NB),
        in_specs=[
            pl.BlockSpec((1, H, D), lambda b, j, tbl, lens: (b, 0, 0)),
            pl.BlockSpec(
                (1, bs, KVH, D),
                lambda b, j, tbl, lens: (tbl[b, j], 0, 0, 0),
            ),
            pl.BlockSpec(
                (1, bs, KVH, D),
                lambda b, j, tbl, lens: (tbl[b, j], 0, 0, 0),
            ),
        ],
        out_specs=pl.BlockSpec((1, H, D), lambda b, j, tbl, lens: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, 128), jnp.float32),
            pltpu.VMEM((H, 128), jnp.float32),
            pltpu.VMEM((H, D), jnp.float32),
        ],
    )

    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, D), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(block_table, lengths, q[:, 0], k_pages, v_pages)
    return out[:, None]
