"""Pallas TPU kernel: paged decode attention over a block KV cache.

The XLA fallback (ops/attention.py:paged_decode_attention) materializes every
sequence's pages into a contiguous ``[B, max_blocks*bs, KVH, D]`` gather per
layer per step — O(B * max_ctx) HBM traffic regardless of actual context
lengths.  This kernel instead streams exactly the pages a sequence actually
uses through VMEM with online (flash-style) softmax accumulation:

  * grid = (batch,): one program per sequence.  K/V page arrays stay in HBM
    (``memory_space=ANY``); the program walks its block table in
    double-buffered *windows* of ``_WINDOW`` pages, issuing all of a
    window's ``make_async_copy`` bursts together and waiting once — per-copy
    HBM latency overlaps within the burst instead of serializing (the
    page-at-a-time variant spent ~n_pages x DMA latency per program, which
    at B=128 x 32 layers dominated the decode step).  The loop is bounded
    by the sequence's real page count (``cdiv(length, bs)``), so unused
    table slots cost nothing.
  * the window w+1 burst is started before window w's math, hiding HBM
    latency behind the compute.
  * GQA without any in-kernel head splitting: pages are DMA'd as
    ``[bs, KVH*D]`` rows (the fused lane dim keeps HBM slices 128-aligned
    for D < 128), queries enter **block-diagonal** — q head h occupies its
    kv-group's D-slice of a ``[H, KVH*D]`` matrix and zeros elsewhere — so
    ``scores = q_bd @ page.T`` and ``acc += p @ page`` are single MXU dots
    whose cross-head terms vanish; the per-head output slice is extracted
    by XLA after the kernel.  The online-softmax state (m, l, acc) is a
    ``fori_loop`` carry.

Selected by ops/attention.py:select_attn_impl on TPU (single-chip engine);
CPU tests run it in interpreter mode for parity with the XLA reference.
Capability context: the reference has no kernels of any kind (pure Go control
plane); this is part of the new TPU serving obligation (SURVEY.md §7 hard
part #1).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)

# jax renamed TPUCompilerParams -> CompilerParams; support both.
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams")


# Pages DMA'd per burst: W pages' copies are issued together and waited
# once, so per-copy HBM latency overlaps within the burst instead of
# serializing (a serial page-at-a-time loop costs ~n_pages x DMA latency of
# pure wait per program — measured ~3x the whole step budget at B=128).
_WINDOW = 8


def _paged_attn_kernel(
    QS,                    # static: query tokens per sequence (1 = decode)
    H,                     # static: query heads per token
    # scalar prefetch
    tables_ref,            # [B, NB] int32 block ids
    starts_ref,            # [B] int32 cached tokens before this chunk
    qlens_ref,             # [B] int32 query tokens this call (<= QS)
    # inputs
    q_ref,                 # [TB, QS*H, F] block-diagonal queries (VMEM)
    k_hbm,                 # [num_blocks, bs, KVH*D] (ANY/HBM, whole array)
    v_hbm,                 # same
    # out
    o_ref,                 # [TB, QS*H, F]
):
    """One program handles TB sequences; each sequence streams its pages
    ONCE for all QS query tokens.  Query token i (rows i*H..i*H+H-1)
    attends causally through absolute position ``starts[b] + i`` — the
    verify/chunk semantics; QS=1 with starts = lengths-1 is exactly the
    decode case.  K/V for the chunk's own tokens must already be written
    into the pages (models/llama.py scatters before attention)."""
    TB = q_ref.shape[0]                                    # seqs per program
    b0 = pl.program_id(0) * TB
    bs = k_hbm.shape[1]
    F = q_ref.shape[2]                                     # KVH * D
    NB = tables_ref.shape[1]
    W = min(_WINDOW, NB)
    # Row r of the [QS*H, F] tile belongs to query token r // H.
    row_q = jax.lax.broadcasted_iota(jnp.int32, (QS * H, 1), 0) // H

    def scoped(k_buf, v_buf, sem):
        # k_buf/v_buf: [2, W*bs, F] double-buffered page slabs, reused
        # across the program's TB sequences; sem: [2, W, 2] one DMA
        # semaphore pair per page slot.
        def start_window(slot, b, w):
            # Issue all W page copies of window ``w`` back-to-back; table
            # indices past the sequence's pages clamp to a duplicate id
            # (rows are masked by position later), so the burst shape is
            # static and every wait has a matching start.
            for i in range(W):
                j = jnp.minimum(w * W + i, NB - 1)
                blk = tables_ref[b, j]
                pltpu.make_async_copy(
                    k_hbm.at[blk], k_buf.at[slot, pl.ds(i * bs, bs)],
                    sem.at[slot, i, 0]).start()
                pltpu.make_async_copy(
                    v_hbm.at[blk], v_buf.at[slot, pl.ds(i * bs, bs)],
                    sem.at[slot, i, 1]).start()

        def wait_window(slot, b, w):
            for i in range(W):
                j = jnp.minimum(w * W + i, NB - 1)
                blk = tables_ref[b, j]
                pltpu.make_async_copy(
                    k_hbm.at[blk], k_buf.at[slot, pl.ds(i * bs, bs)],
                    sem.at[slot, i, 0]).wait()
                pltpu.make_async_copy(
                    v_hbm.at[blk], v_buf.at[slot, pl.ds(i * bs, bs)],
                    sem.at[slot, i, 1]).wait()

        # Static unroll over the tile's sequences: one program amortizes
        # grid startup over TB sequences' attention.  Each sequence still
        # pays its own window-0 DMA stall (the shared double buffers make
        # cross-sequence prefetch non-trivial; measured immaterial on v5e).
        for t in range(TB):
            b = b0 + t
            start = starts_ref[b]
            # Stream every page the chunk's last token can see.  Inactive
            # lanes (qlen 0) stream ONE masked window, not their whole dead
            # context: a lane that finished in round 1 of a multi-round
            # spec call would otherwise re-stream ctx pages per layer per
            # remaining round just to produce discarded rows.
            length = jnp.where(qlens_ref[b] > 0, start + qlens_ref[b], 1)
            n_blocks = (length + bs - 1) // bs             # >= 1
            n_windows = (n_blocks + W - 1) // W
            start_window(0, b, 0)
            q = q_ref[t].astype(jnp.float32)           # [QS*H, F] block-diag

            def body(w, carry, b=b, start=start, n_windows=n_windows):
                m, l, acc = carry          # [QS*H, 1], [QS*H, 1], [QS*H, F]
                slot = jax.lax.rem(w, 2)

                @pl.when(w + 1 < n_windows)
                def _prefetch():
                    start_window(1 - slot, b, w + 1)

                wait_window(slot, b, w)
                pos = (w * (W * bs)
                       + jax.lax.broadcasted_iota(jnp.int32, (1, W * bs), 1))
                # Per-row causal bound: query token i sits at absolute
                # position start + i, attending through itself.
                valid = pos < start + 1 + row_q             # [QS*H, W*bs]
                kblk = k_buf[slot].astype(jnp.float32)      # [W*bs, F]
                vblk = v_buf[slot].astype(jnp.float32)

                # Block-diagonal q makes this one dot per window: head h
                # only overlaps its own kv group's D-slice, so cross-head
                # products are zero.
                s = jax.lax.dot_general(
                    q, kblk, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )                                           # [QS*H, W*bs]
                s = jnp.where(valid, s, NEG_INF)

                m_cur = jnp.max(s, axis=-1, keepdims=True)
                m_new = jnp.maximum(m, m_cur)
                alpha = jnp.exp(m - m_new)
                p = jnp.exp(s - m_new)                      # [QS*H, W*bs]
                l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
                pv = jax.lax.dot_general(
                    p, vblk, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )                                           # [QS*H, F]
                return m_new, l_new, alpha * acc + pv

            m0 = jnp.full((QS * H, 1), NEG_INF, jnp.float32)
            l0 = jnp.zeros((QS * H, 1), jnp.float32)
            acc0 = jnp.zeros((QS * H, F), jnp.float32)
            _, l, acc = jax.lax.fori_loop(0, n_windows, body, (m0, l0, acc0))
            # acc rows carry the head's output in its kv-group slice (plus
            # group-mates' contributions in other slices, sliced away by
            # the caller).
            o_ref[t] = (acc / l).astype(o_ref.dtype)

    pl.run_scoped(
        scoped,
        k_buf=pltpu.VMEM((2, W * bs, F), k_hbm.dtype),
        v_buf=pltpu.VMEM((2, W * bs, F), v_hbm.dtype),
        sem=pltpu.SemaphoreType.DMA((2, W, 2)),
    )


def _run_paged_attn(q, k_pages, v_pages, block_table, starts, qlens,
                    interpret):
    """Shared wrapper: block-diagonalize queries, tile the batch, run the
    unified kernel, extract each head's kv-group slice.

    q: [B, QS, H, D] — QS query tokens per sequence at absolute positions
    ``starts[b] + i``; returns [B, QS, H, D].
    """
    B, QS, H, D = q.shape
    nblk, bs, F = k_pages.shape
    assert F % D == 0 and D <= 128, (F, D)
    KVH = F // D
    q_per_kv = H // KVH

    # Block-diagonal queries (scaled): head h lives in its kv group's
    # D-slice of the F lane dim, zeros elsewhere — see _paged_attn_kernel.
    group = jnp.arange(H, dtype=jnp.int32) // q_per_kv            # [H]
    onehot = jax.nn.one_hot(group, KVH, dtype=q.dtype)            # [H, KVH]
    q_bd = (q[:, :, :, None, :] * (D ** -0.5)
            * onehot[None, None, :, :, None]).reshape(B, QS * H, F)

    # Batch-tile: TB sequences per program amortize per-program grid
    # startup — at B=128 this is 16 programs instead of 128, 8 per
    # megacore half.  (Measured neutral vs grid=(B,) on v5e at B=128; the
    # decode-attention cost there is dependency-serialization against the
    # surrounding matmuls, not program count.)  Keep at least 2 programs
    # so both megacore halves stay busy at small B, and bound the q/o VMEM
    # tiles to ~4 MiB for multi-query (verify) calls.
    budget = 4 * 2**20 // max(QS * H * F * q.dtype.itemsize, 1)
    TB = next(tb for tb in (8, 4, 2, 1)
              if B % tb == 0 and (B // tb >= 2 or B == 1)
              and (tb <= budget or tb == 1))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B // TB,),
        in_specs=[
            pl.BlockSpec((TB, QS * H, F), lambda p, tbl, st, ql: (p, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),   # K pages stay in HBM
            pl.BlockSpec(memory_space=pl.ANY),   # V pages stay in HBM
        ],
        out_specs=pl.BlockSpec((TB, QS * H, F),
                               lambda p, tbl, st, ql: (p, 0, 0)),
    )

    out_full = pl.pallas_call(
        functools.partial(_paged_attn_kernel, QS, H),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, QS * H, F), q.dtype),
        compiler_params=_CompilerParams(
            # Programs touch disjoint q/o tiles and only read pages: the
            # tile grid is safely parallel (megacore splits it).
            dimension_semantics=("parallel",),
        ),
        interpret=interpret,
    )(block_table, starts, qlens, q_bd, k_pages, v_pages)

    # Extract each head's own kv-group slice.
    out = jnp.take_along_axis(
        out_full.reshape(B, QS, H, KVH, D),
        group[None, None, :, None, None], axis=3)[:, :, :, 0, :]
    return out


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_decode_attention_pallas(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    block_table: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    interpret: bool = False,
) -> jnp.ndarray:
    """Single-token paged decode attention (drop-in for the XLA fallback).

    Args:
      q: [B, 1, H, D].
      k_pages, v_pages: [num_blocks, bs, KVH*D] — the resident fused-lane
        layout (models/llama.py:KVPages), consumed directly with no
        per-step relayout.
      block_table: [B, max_blocks_per_seq] int32 (entries past the sequence's
        pages must be 0, the null block — serving/kv_cache.py guarantees it).
      lengths: [B] int32 valid kv length (>= 1 for active lanes; the new
        token's K/V must already be written at index lengths-1).
      interpret: run in the Pallas interpreter (CPU parity tests).

    Returns:
      [B, 1, H, D] in q.dtype.
    """
    B, S, H, D = q.shape
    assert S == 1, f"decode kernel expects one query token, got {S}"
    starts = jnp.maximum(lengths - 1, 0).astype(jnp.int32)
    qlens = jnp.minimum(lengths, 1).astype(jnp.int32)
    return _run_paged_attn(q, k_pages, v_pages, block_table, starts, qlens,
                           interpret)


# ---------------------------------------------------------------------------
# Fused decode fast-path: RoPE + KV append + paged attention in one kernel
# ---------------------------------------------------------------------------


def _rotate_half_fused(x, D):
    """rotate_half within each D-slice of a fused-lane [..., KVH*D] array.

    For lane j with r = j mod D: first half (r < D/2) takes -x[j + D/2],
    second half takes x[j - D/2].  Both reads stay inside j's D-slice, so
    two full-axis rolls + a half-mask select implement the per-slice
    rotate without any lane-offset slicing (which Mosaic restricts).
    """
    F = x.shape[-1]
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, F), 1)
    first_half = jax.lax.rem(lane, D) < (D // 2)
    fwd = pltpu.roll(x, D // 2, 1)          # x[j - D/2]
    bwd = pltpu.roll(x, (F - D // 2) % F, 1)  # x[j + D/2]
    return jnp.where(first_half, -bwd, fwd)


def _fused_decode_kernel(
    H,                     # static: query heads per token
    D,                     # static: head dim
    # scalar prefetch
    tables_ref,            # [B, NB] int32 block ids
    pos_ref,               # [B] int32 new-token position (0 = inactive lane)
    # inputs
    q_ref,                 # [TB, H, F] raw (unroped) block-diagonal queries
    kn_ref,                # [TB, 1, F] raw fused-lane new-token k
    vn_ref,                # [TB, 1, F] fused-lane new-token v
    cos_ref,               # [TB, 1, F] rope cos, tiled per kv group
    sin_ref,               # [TB, 1, F]
    k_hbm,                 # [num_blocks, bs, F] (ANY/HBM; aliased to k_out)
    v_hbm,
    # outputs
    o_ref,                 # [TB, H, F]
    k_out,                 # aliased page arrays (ANY/HBM)
    v_out,
):
    """Decode step for TB sequences: RoPE the query and the new token's k
    in-kernel, DMA the roped k / raw v row into its page (overlapped with
    the attention math), stream the CACHED pages (positions < pos) with
    online softmax, and fold the current token in as one extra softmax
    update from VMEM — so the appended row is never read back from HBM
    and the append DMA can land any time before the program ends.

    Inactive lanes (pos == 0) stream nothing and write their row to the
    null block 0, matching models/llama.py:_scatter_pages; their output is
    finite garbage (only the current-token term) that the engine discards.
    """
    TB = q_ref.shape[0]
    b0 = pl.program_id(0) * TB
    bs = k_hbm.shape[1]
    F = q_ref.shape[2]
    NB = tables_ref.shape[1]
    W = min(_WINDOW, NB)

    def scoped(k_buf, v_buf, k_row, v_row, sem, append_sem):
        def start_window(slot, b, w):
            for i in range(W):
                j = jnp.minimum(w * W + i, NB - 1)
                blk = tables_ref[b, j]
                pltpu.make_async_copy(
                    k_hbm.at[blk], k_buf.at[slot, pl.ds(i * bs, bs)],
                    sem.at[slot, i, 0]).start()
                pltpu.make_async_copy(
                    v_hbm.at[blk], v_buf.at[slot, pl.ds(i * bs, bs)],
                    sem.at[slot, i, 1]).start()

        def wait_window(slot, b, w):
            for i in range(W):
                j = jnp.minimum(w * W + i, NB - 1)
                blk = tables_ref[b, j]
                pltpu.make_async_copy(
                    k_hbm.at[blk], k_buf.at[slot, pl.ds(i * bs, bs)],
                    sem.at[slot, i, 0]).wait()
                pltpu.make_async_copy(
                    v_hbm.at[blk], v_buf.at[slot, pl.ds(i * bs, bs)],
                    sem.at[slot, i, 1]).wait()

        for t in range(TB):
            b = b0 + t
            pos = pos_ref[b]                 # tokens cached before this one
            active = pos > 0

            # --- in-kernel RoPE (f32, like ops/rope.py) -------------------
            cos = cos_ref[t].astype(jnp.float32)          # [1, F]
            sin = sin_ref[t].astype(jnp.float32)
            q = q_ref[t].astype(jnp.float32)              # [H, F] block-diag
            # Per-D-slice rotate: a head's slice-g support stays in slice
            # g and zeros rope to zeros, so roping the block-diagonal
            # matrix equals block-diagonalizing the roped heads.
            qf = q * cos + _rotate_half_fused(q, D) * sin
            kn = kn_ref[t].astype(jnp.float32)            # [1, F]
            kf = kn * cos + _rotate_half_fused(kn, D) * sin

            # --- KV append: start the DMA, overlap with attention ---------
            raw_blk = pos // bs
            in_table = raw_blk < NB
            blk = jnp.where(active & in_table,
                            tables_ref[b, jnp.minimum(raw_blk, NB - 1)], 0)
            off = jax.lax.rem(pos, bs)
            k_row[...] = kf.astype(k_row.dtype)
            v_row[...] = vn_ref[t].astype(v_row.dtype)
            k_copy = pltpu.make_async_copy(
                k_row, k_out.at[blk, pl.ds(off, 1)], append_sem.at[0])
            v_copy = pltpu.make_async_copy(
                v_row, v_out.at[blk, pl.ds(off, 1)], append_sem.at[1])
            k_copy.start()
            v_copy.start()

            # --- stream the cached pages (positions < pos) ----------------
            n_blocks = (pos + bs - 1) // bs              # 0 for inactive
            n_windows = (n_blocks + W - 1) // W

            @pl.when(n_windows > 0)
            def _first():
                start_window(0, b, 0)

            def body(w, carry, b=b, pos=pos, n_windows=n_windows):
                m, l, acc = carry
                slot = jax.lax.rem(w, 2)

                @pl.when(w + 1 < n_windows)
                def _prefetch():
                    start_window(1 - slot, b, w + 1)

                wait_window(slot, b, w)
                p_idx = (w * (W * bs)
                         + jax.lax.broadcasted_iota(jnp.int32, (1, W * bs), 1))
                # The row being appended (p_idx == pos) is masked, so the
                # in-flight append DMA can never race a row we consume.
                valid = p_idx < pos
                kblk = k_buf[slot].astype(jnp.float32)
                vblk = v_buf[slot].astype(jnp.float32)
                s = jax.lax.dot_general(
                    qf, kblk, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)
                s = jnp.where(valid, s, NEG_INF)
                m_cur = jnp.max(s, axis=-1, keepdims=True)
                m_new = jnp.maximum(m, m_cur)
                alpha = jnp.exp(m - m_new)
                p = jnp.exp(s - m_new)
                l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
                pv = jax.lax.dot_general(
                    p, vblk, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                return m_new, l_new, alpha * acc + pv

            m0 = jnp.full((H, 1), NEG_INF, jnp.float32)
            l0 = jnp.zeros((H, 1), jnp.float32)
            acc0 = jnp.zeros((H, F), jnp.float32)
            m, l, acc = jax.lax.fori_loop(0, n_windows, body, (m0, l0, acc0))

            # --- current token: one more online-softmax update from VMEM --
            # Always included (even for inactive lanes) so l > 0 and the
            # output stays finite without the cached window the old
            # gather path borrowed from the null block.
            s_cur = jax.lax.dot_general(
                qf, kf, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)       # [H, 1]
            m_new = jnp.maximum(m, s_cur)
            alpha = jnp.exp(m - m_new)
            p_cur = jnp.exp(s_cur - m_new)
            l = alpha * l + p_cur
            vf = vn_ref[t].astype(jnp.float32)            # [1, F]
            acc = alpha * acc + p_cur * vf

            k_copy.wait()
            v_copy.wait()
            o_ref[t] = (acc / l).astype(o_ref.dtype)

    pl.run_scoped(
        scoped,
        k_buf=pltpu.VMEM((2, W * bs, F), k_hbm.dtype),
        v_buf=pltpu.VMEM((2, W * bs, F), v_hbm.dtype),
        k_row=pltpu.VMEM((1, F), k_hbm.dtype),
        v_row=pltpu.VMEM((1, F), v_hbm.dtype),
        sem=pltpu.SemaphoreType.DMA((2, W, 2)),
        append_sem=pltpu.SemaphoreType.DMA((2,)),
    )


def paged_decode_attention_fused(
    q: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    block_table: jnp.ndarray,
    positions: jnp.ndarray,
    *,
    interpret: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Fused decode step: RoPE + KV append + paged attention in one call.

    Replaces the decode-path sequence apply_rope -> _scatter_pages ->
    paged_decode_attention (models/llama.py) with a single Pallas kernel:
    the query/new-k rotary embedding runs in-kernel, the new token's K/V
    row is DMA'd into its page from VMEM (no XLA scatter over the full
    page arrays), and attention streams only the CACHED pages, folding
    the current token in from registers.  The page outputs alias the
    inputs (in-place update) so the engine's donated KV buffers are
    never copied.

    Args:
      q: [B, 1, H, D] raw (unroped) queries.
      k_new, v_new: [B, 1, KVH, D] raw new-token projections (k unroped).
      cos, sin: [B, 1, D] rope angle tables at each lane's position
        (ops/rope.py:rope_angles of ``positions``).
      k_pages, v_pages: [num_blocks, bs, KVH*D] resident page arrays.
      block_table: [B, max_blocks_per_seq] int32 (0 = null block).
      positions: [B] int32 — tokens already cached per lane, i.e. the new
        token's absolute position; 0 marks an inactive lane whose write
        is redirected to the null block (same as _scatter_pages).
      interpret: run in the Pallas interpreter (CPU parity tests).

    Returns:
      (attn [B, 1, H, D], updated k_pages, updated v_pages).
    """
    B, S, H, D = q.shape
    assert S == 1, f"fused decode kernel expects one query token, got {S}"
    nblk, bs, F = k_pages.shape
    assert F % D == 0 and D % 2 == 0 and D <= 128, (F, D)
    KVH = F // D
    q_per_kv = H // KVH

    group = jnp.arange(H, dtype=jnp.int32) // q_per_kv
    onehot = jax.nn.one_hot(group, KVH, dtype=q.dtype)
    # Raw block-diagonal queries; RoPE commutes with the D**-0.5 scale and
    # acts within each D-slice, so roping this matrix in-kernel is exact.
    q_bd = (q[:, 0, :, None, :] * (D ** -0.5)
            * onehot[None, :, :, None]).reshape(B, H, F)
    kn = k_new.reshape(B, 1, F)
    vn = v_new.reshape(B, 1, F)
    cos_f = jnp.tile(cos.astype(jnp.float32), (1, 1, KVH))     # [B, 1, F]
    sin_f = jnp.tile(sin.astype(jnp.float32), (1, 1, KVH))

    budget = 4 * 2**20 // max(H * F * q.dtype.itemsize, 1)
    TB = next(tb for tb in (8, 4, 2, 1)
              if B % tb == 0 and (B // tb >= 2 or B == 1)
              and (tb <= budget or tb == 1))
    lane_spec = lambda p, tbl, pos: (p, 0, 0)  # noqa: E731
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B // TB,),
        in_specs=[
            pl.BlockSpec((TB, H, F), lane_spec),
            pl.BlockSpec((TB, 1, F), lane_spec),
            pl.BlockSpec((TB, 1, F), lane_spec),
            pl.BlockSpec((TB, 1, F), lane_spec),
            pl.BlockSpec((TB, 1, F), lane_spec),
            pl.BlockSpec(memory_space=pl.ANY),   # K pages stay in HBM
            pl.BlockSpec(memory_space=pl.ANY),   # V pages stay in HBM
        ],
        out_specs=[
            pl.BlockSpec((TB, H, F), lane_spec),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
    )

    out_full, k_out, v_out = pl.pallas_call(
        functools.partial(_fused_decode_kernel, H, D),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, H, F), q.dtype),
            jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype),
            jax.ShapeDtypeStruct(v_pages.shape, v_pages.dtype),
        ],
        # Page arrays update in place: inputs 7/8 (after the 2 scalar-
        # prefetch operands) alias outputs 1/2.
        input_output_aliases={7: 1, 8: 2},
        compiler_params=_CompilerParams(
            # Lanes append to blocks they own (the allocator hands out
            # distinct tail blocks; only never-read null-block rows race),
            # so the tile grid stays megacore-parallel like the decode
            # kernel.
            dimension_semantics=("parallel",),
        ),
        interpret=interpret,
    )(block_table, positions.astype(jnp.int32), q_bd, kn, vn, cos_f, sin_f,
      k_pages, v_pages)

    out = jnp.take_along_axis(
        out_full.reshape(B, 1, H, KVH, D),
        group[None, None, :, None, None], axis=3)[:, :, :, 0, :]
    return out, k_out, v_out


# Marker consumed by models/llama.py:decode_step to select the fused
# calling convention (raw q/k/v + angles in, pages out).
paged_decode_attention_fused.fused_decode = True


# ---------------------------------------------------------------------------
# Quantized-KV fused decode: quantize-on-append + dequantize-in-kernel
# ---------------------------------------------------------------------------


def _fused_decode_quant_kernel(
    H,                     # static: query heads per token
    D,                     # static: head dim
    KVH,                   # static: kv heads (= F // D)
    qmax,                  # static: quant range (127 int8 / 448 fp8)
    is_int8,               # static: round+clip vs saturating fp8 cast
    # scalar prefetch
    tables_ref,            # [B, NB] int32 block ids
    pos_ref,               # [B] int32 new-token position (0 = inactive lane)
    # inputs
    q_ref,                 # [TB, H, F] raw (unroped) block-diagonal queries
    kn_ref,                # [TB, 1, F] raw fused-lane new-token k
    vn_ref,                # [TB, 1, F]
    cos_ref,               # [TB, 1, F]
    sin_ref,               # [TB, 1, F]
    k_hbm,                 # [num_blocks, bs, F] quantized (aliased to k_out)
    v_hbm,
    ks_hbm,                # [num_blocks, bs, KVH] f32 scales (aliased)
    vs_hbm,
    # outputs
    o_ref,                 # [TB, H, F]
    k_out,
    v_out,
    ks_out,
    vs_out,
):
    """Quantized twin of ``_fused_decode_kernel``.

    Dequantization never expands scales to the F lane dim for the cached
    pages: per-(token, head) K scales factor out of ``q @ k^T`` (the
    block-diagonal q restricts head h to its own kv group's lanes), so the
    score matrix is rescaled by ``scale_bd[h, j] = ks[j, group(h)]`` — one
    small MXU dot (``onehot_h @ ks_win^T``) per window.  V scales fold into
    the probabilities the same way: ``acc += (p * vs_bd) @ v_q`` is exact
    for each head's own group slice (other slices carry garbage the caller
    slices away) while the softmax denominator uses the unscaled ``p``.

    The appended token is quantized in-kernel (per-head amax over its
    D-slice) and folded into the softmax as dequantize(quantize(k)) — bit
    parity with the gather path, which reads the row back dequantized.
    """
    TB = q_ref.shape[0]
    b0 = pl.program_id(0) * TB
    bs = k_hbm.shape[1]
    F = q_ref.shape[2]
    NB = tables_ref.shape[1]
    W = min(_WINDOW, NB)
    # Constant index maps: lane j belongs to kv group j // D; head h reads
    # group h // (H // KVH).
    lane_group = jax.lax.broadcasted_iota(jnp.int32, (KVH, F), 1) // D
    grp_row = jax.lax.broadcasted_iota(jnp.int32, (KVH, F), 0)
    onehot_lane = (lane_group == grp_row).astype(jnp.float32)   # [KVH, F]
    head_grp = (jax.lax.broadcasted_iota(jnp.int32, (H, KVH), 0)
                // max(H // KVH, 1))
    kvh_col = jax.lax.broadcasted_iota(jnp.int32, (H, KVH), 1)
    onehot_h = (kvh_col == head_grp).astype(jnp.float32)        # [H, KVH]

    def _quantize_row(xf):
        """xf [1, F] float -> (store [1, F] float pre-cast, scale [1, KVH],
        dequantized [1, F] f32)."""
        masked = jnp.where(onehot_lane > 0, jnp.abs(xf), 0.0)   # [KVH, F]
        amax = jnp.max(masked, axis=1, keepdims=True)           # [KVH, 1]
        scale = jnp.maximum(amax / qmax, 1e-8)
        # Lane-expand via one small dot: scale_lane[0, j] = scale[g(j)].
        scale_lane = jax.lax.dot_general(
            scale.reshape(1, KVH), onehot_lane, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                 # [1, F]
        xq = xf / scale_lane
        if is_int8:
            xq = jnp.clip(jnp.round(xq), -qmax, qmax)
        deq = xq * scale_lane
        return xq, scale.reshape(1, KVH), deq

    def scoped(k_buf, v_buf, ks_buf, vs_buf, k_row, v_row, ks_row, vs_row,
               sem, ssem, append_sem):
        def start_window(slot, b, w):
            for i in range(W):
                j = jnp.minimum(w * W + i, NB - 1)
                blk = tables_ref[b, j]
                pltpu.make_async_copy(
                    k_hbm.at[blk], k_buf.at[slot, pl.ds(i * bs, bs)],
                    sem.at[slot, i, 0]).start()
                pltpu.make_async_copy(
                    v_hbm.at[blk], v_buf.at[slot, pl.ds(i * bs, bs)],
                    sem.at[slot, i, 1]).start()
                pltpu.make_async_copy(
                    ks_hbm.at[blk], ks_buf.at[slot, pl.ds(i * bs, bs)],
                    ssem.at[slot, i, 0]).start()
                pltpu.make_async_copy(
                    vs_hbm.at[blk], vs_buf.at[slot, pl.ds(i * bs, bs)],
                    ssem.at[slot, i, 1]).start()

        def wait_window(slot, b, w):
            for i in range(W):
                j = jnp.minimum(w * W + i, NB - 1)
                blk = tables_ref[b, j]
                pltpu.make_async_copy(
                    k_hbm.at[blk], k_buf.at[slot, pl.ds(i * bs, bs)],
                    sem.at[slot, i, 0]).wait()
                pltpu.make_async_copy(
                    v_hbm.at[blk], v_buf.at[slot, pl.ds(i * bs, bs)],
                    sem.at[slot, i, 1]).wait()
                pltpu.make_async_copy(
                    ks_hbm.at[blk], ks_buf.at[slot, pl.ds(i * bs, bs)],
                    ssem.at[slot, i, 0]).wait()
                pltpu.make_async_copy(
                    vs_hbm.at[blk], vs_buf.at[slot, pl.ds(i * bs, bs)],
                    ssem.at[slot, i, 1]).wait()

        for t in range(TB):
            b = b0 + t
            pos = pos_ref[b]
            active = pos > 0

            cos = cos_ref[t].astype(jnp.float32)
            sin = sin_ref[t].astype(jnp.float32)
            q = q_ref[t].astype(jnp.float32)
            qf = q * cos + _rotate_half_fused(q, D) * sin
            kn = kn_ref[t].astype(jnp.float32)
            kf = kn * cos + _rotate_half_fused(kn, D) * sin
            vf = vn_ref[t].astype(jnp.float32)

            # --- quantize-on-append (per-head amax over the D-slice) ------
            kq, k_scale, kdeq = _quantize_row(kf)
            vq, v_scale, vdeq = _quantize_row(vf)

            raw_blk = pos // bs
            in_table = raw_blk < NB
            blk = jnp.where(active & in_table,
                            tables_ref[b, jnp.minimum(raw_blk, NB - 1)], 0)
            off = jax.lax.rem(pos, bs)
            k_row[...] = kq.astype(k_row.dtype)
            v_row[...] = vq.astype(v_row.dtype)
            ks_row[...] = k_scale
            vs_row[...] = v_scale
            copies = [
                pltpu.make_async_copy(
                    k_row, k_out.at[blk, pl.ds(off, 1)], append_sem.at[0]),
                pltpu.make_async_copy(
                    v_row, v_out.at[blk, pl.ds(off, 1)], append_sem.at[1]),
                pltpu.make_async_copy(
                    ks_row, ks_out.at[blk, pl.ds(off, 1)], append_sem.at[2]),
                pltpu.make_async_copy(
                    vs_row, vs_out.at[blk, pl.ds(off, 1)], append_sem.at[3]),
            ]
            for c in copies:
                c.start()

            n_blocks = (pos + bs - 1) // bs
            n_windows = (n_blocks + W - 1) // W

            @pl.when(n_windows > 0)
            def _first():
                start_window(0, b, 0)

            def body(w, carry, b=b, pos=pos, n_windows=n_windows):
                m, l, acc = carry
                slot = jax.lax.rem(w, 2)

                @pl.when(w + 1 < n_windows)
                def _prefetch():
                    start_window(1 - slot, b, w + 1)

                wait_window(slot, b, w)
                p_idx = (w * (W * bs)
                         + jax.lax.broadcasted_iota(jnp.int32, (1, W * bs), 1))
                valid = p_idx < pos
                kblk = k_buf[slot].astype(jnp.float32)      # quantized ints
                vblk = v_buf[slot].astype(jnp.float32)
                # K scales factor out of the contraction: scale_bd[h, j] =
                # ks[j, group(h)], built as one [H, KVH] x [KVH, W*bs] dot.
                ks_bd = jax.lax.dot_general(
                    onehot_h, ks_buf[slot], (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)     # [H, W*bs]
                vs_bd = jax.lax.dot_general(
                    onehot_h, vs_buf[slot], (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)
                s = jax.lax.dot_general(
                    qf, kblk, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32) * ks_bd
                s = jnp.where(valid, s, NEG_INF)
                m_cur = jnp.max(s, axis=-1, keepdims=True)
                m_new = jnp.maximum(m, m_cur)
                alpha = jnp.exp(m - m_new)
                p = jnp.exp(s - m_new)
                l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
                # V scales fold into p; exact on each head's own group
                # slice, garbage elsewhere (sliced away by the caller).
                pv = jax.lax.dot_general(
                    p * vs_bd, vblk, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                return m_new, l_new, alpha * acc + pv

            m0 = jnp.full((H, 1), NEG_INF, jnp.float32)
            l0 = jnp.zeros((H, 1), jnp.float32)
            acc0 = jnp.zeros((H, F), jnp.float32)
            m, l, acc = jax.lax.fori_loop(0, n_windows, body, (m0, l0, acc0))

            # Current token folded as dequant(quant(.)) — parity with the
            # gather path reading the row back.
            s_cur = jax.lax.dot_general(
                qf, kdeq, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)         # [H, 1]
            m_new = jnp.maximum(m, s_cur)
            alpha = jnp.exp(m - m_new)
            p_cur = jnp.exp(s_cur - m_new)
            l = alpha * l + p_cur
            acc = alpha * acc + p_cur * vdeq

            for c in copies:
                c.wait()
            o_ref[t] = (acc / l).astype(o_ref.dtype)

    pl.run_scoped(
        scoped,
        k_buf=pltpu.VMEM((2, W * bs, F), k_hbm.dtype),
        v_buf=pltpu.VMEM((2, W * bs, F), v_hbm.dtype),
        # Scale slabs keep the KVH lane dim (sub-128 lanes: Mosaic pads;
        # the bytes are 1/(2*D) of the page slabs so the padding waste is
        # bounded and the VMEM cost is noise).
        ks_buf=pltpu.VMEM((2, W * bs, KVH), jnp.float32),
        vs_buf=pltpu.VMEM((2, W * bs, KVH), jnp.float32),
        k_row=pltpu.VMEM((1, F), k_hbm.dtype),
        v_row=pltpu.VMEM((1, F), v_hbm.dtype),
        ks_row=pltpu.VMEM((1, KVH), jnp.float32),
        vs_row=pltpu.VMEM((1, KVH), jnp.float32),
        sem=pltpu.SemaphoreType.DMA((2, W, 2)),
        ssem=pltpu.SemaphoreType.DMA((2, W, 2)),
        append_sem=pltpu.SemaphoreType.DMA((4,)),
    )


def paged_decode_attention_fused_quant(
    q: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    k_scale: jnp.ndarray,
    v_scale: jnp.ndarray,
    block_table: jnp.ndarray,
    positions: jnp.ndarray,
    *,
    interpret: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Quantized-KV fused decode step (see ``paged_decode_attention_fused``).

    Identical calling convention plus the per-(token, head) float32 scale
    arrays ``k_scale``/``v_scale`` [num_blocks, bs, KVH], which — like the
    pages — alias their outputs and update in place.  The engine's donated
    quantized pool (pages + scales) is therefore never copied; traceguard
    asserts the rebinding exactly as for the fp16 pool.

    Returns:
      (attn [B, 1, H, D], k_pages, v_pages, k_scale, v_scale) — the four
      pool arrays updated in place.
    """
    B, S, H, D = q.shape
    assert S == 1, f"fused decode kernel expects one query token, got {S}"
    nblk, bs, F = k_pages.shape
    assert F % D == 0 and D % 2 == 0 and D <= 128, (F, D)
    KVH = F // D
    q_per_kv = H // KVH
    qmax = 127.0 if jnp.dtype(k_pages.dtype) == jnp.int8 else 448.0
    is_int8 = jnp.dtype(k_pages.dtype) == jnp.int8

    group = jnp.arange(H, dtype=jnp.int32) // q_per_kv
    onehot = jax.nn.one_hot(group, KVH, dtype=q.dtype)
    q_bd = (q[:, 0, :, None, :] * (D ** -0.5)
            * onehot[None, :, :, None]).reshape(B, H, F)
    kn = k_new.reshape(B, 1, F)
    vn = v_new.reshape(B, 1, F)
    cos_f = jnp.tile(cos.astype(jnp.float32), (1, 1, KVH))
    sin_f = jnp.tile(sin.astype(jnp.float32), (1, 1, KVH))

    budget = 4 * 2**20 // max(H * F * q.dtype.itemsize, 1)
    TB = next(tb for tb in (8, 4, 2, 1)
              if B % tb == 0 and (B // tb >= 2 or B == 1)
              and (tb <= budget or tb == 1))
    lane_spec = lambda p, tbl, pos: (p, 0, 0)  # noqa: E731
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B // TB,),
        in_specs=[
            pl.BlockSpec((TB, H, F), lane_spec),
            pl.BlockSpec((TB, 1, F), lane_spec),
            pl.BlockSpec((TB, 1, F), lane_spec),
            pl.BlockSpec((TB, 1, F), lane_spec),
            pl.BlockSpec((TB, 1, F), lane_spec),
            pl.BlockSpec(memory_space=pl.ANY),   # K pages stay in HBM
            pl.BlockSpec(memory_space=pl.ANY),   # V pages stay in HBM
            pl.BlockSpec(memory_space=pl.ANY),   # K scales stay in HBM
            pl.BlockSpec(memory_space=pl.ANY),   # V scales stay in HBM
        ],
        out_specs=[
            pl.BlockSpec((TB, H, F), lane_spec),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
    )

    out_full, k_out, v_out, ks_out, vs_out = pl.pallas_call(
        functools.partial(_fused_decode_quant_kernel, H, D, KVH, qmax,
                          is_int8),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, H, F), q.dtype),
            jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype),
            jax.ShapeDtypeStruct(v_pages.shape, v_pages.dtype),
            jax.ShapeDtypeStruct(k_scale.shape, k_scale.dtype),
            jax.ShapeDtypeStruct(v_scale.shape, v_scale.dtype),
        ],
        # Pool arrays update in place: inputs 7..10 (after the 2 scalar-
        # prefetch operands) alias outputs 1..4.
        input_output_aliases={7: 1, 8: 2, 9: 3, 10: 4},
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel",),
        ),
        interpret=interpret,
    )(block_table, positions.astype(jnp.int32), q_bd, kn, vn, cos_f, sin_f,
      k_pages, v_pages, k_scale, v_scale)

    out = jnp.take_along_axis(
        out_full.reshape(B, 1, H, KVH, D),
        group[None, None, :, None, None], axis=3)[:, :, :, 0, :]
    return out, k_out, v_out, ks_out, vs_out


# Markers: fused calling convention + quantized-pool variant
# (models/llama.py:is_fused_decode_impl / is_fused_quant_decode_impl).
paged_decode_attention_fused_quant.fused_decode = True
paged_decode_attention_fused_quant.quant_kv = True


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_verify_attention_pallas(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    block_table: jnp.ndarray,
    start: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    interpret: bool = False,
) -> jnp.ndarray:
    """Multi-query paged attention for speculative verify / small chunks.

    Query token ``i`` of sequence ``b`` sits at absolute position
    ``start[b] + i`` and attends causally through itself; the chunk's K/V
    must already be scattered into the pages.  Streams each sequence's
    pages ONCE for all S queries — vs the XLA gather fallback's
    O(B * max_blocks * bs) traffic, and vs S separate decode-kernel calls'
    S-fold re-streaming.

    Args:
      q: [B, S, H, D] (S small — the spec draft length + 1).
      start: [B] int32 tokens already cached before this chunk.
      lengths: [B] int32 valid query tokens (0 = inactive lane; its rows
        compute against the null block and are discarded by the caller).

    Returns:
      [B, S, H, D] in q.dtype.
    """
    return _run_paged_attn(q, k_pages, v_pages, block_table,
                           start.astype(jnp.int32),
                           lengths.astype(jnp.int32), interpret)


# ---------------------------------------------------------------------------
# Flash paged prefill: tiled online softmax straight off the paged pool
# ---------------------------------------------------------------------------


def _flash_prefill_kernel(
    TQ,                    # static: query tokens per tile
    D,                     # static: head dim
    KVH,                   # static: kv heads (= F // D)
    qpk,                   # static: query heads per kv group
    quant,                 # static: dequantize-in-kernel from scale planes
    # scalar prefetch
    tables_ref,            # [B, NB] int32 block ids
    starts_ref,            # [B] int32 cached tokens before this chunk
    qlens_ref,             # [B] int32 valid query tokens (0 = inactive lane)
    # inputs
    q_ref,                 # [1, TQ, 1, qpk*D] this (seq, tile, group) q slab
    k_hbm,                 # [num_blocks, bs, KVH*D] (ANY/HBM, whole array)
    v_hbm,                 # same
    *rest,                 # (ks_hbm, vs_hbm,) o_ref
):
    """One program: one query tile of one sequence for one kv group.

    Unlike the decode/verify kernels, whose [QS*H, F] block-diagonal query
    costs KVH x redundant MXU work per extra query row, prefill has TQ up
    to 128 query rows live at once — so the grid splits the kv-head axis
    instead (grid = (B, KVH, n_tiles)) and each program DMAs only its own
    group's D-lane slice of every page row.  The group's qpk query heads
    stack on the sublane axis ([qpk*TQ, D]), giving dense MXU dots with no
    cross-head waste at any GQA ratio.

    Scores for a [TQ, W*bs] window tile are reduced into running
    (max, sum, acc) online-softmax carries — the [S, T] score matrix is
    never materialized, which is what lets 8k/32k buckets fit where the
    dense path's [B, H, S, T] float32 logits cannot.

    ``quant``: pages hold int8/fp8 codes; the per-(token, head) scale rows
    are DMA'd whole ([bs, KVH]) and this group's column is extracted with a
    one-hot dot (a [1, KVH] x [KVH, W*bs] contraction — never a sub-lane
    sliced DMA).  K scales factor out of ``q @ k^T`` onto the score tile; V
    scales fold into the probabilities, exactly the
    ``_fused_decode_quant_kernel`` convention.
    """
    if quant:
        ks_hbm, vs_hbm, o_ref = rest
    else:
        (o_ref,) = rest
    b = pl.program_id(0)
    g = pl.program_id(1)                         # kv group this program owns
    t = pl.program_id(2)                         # query tile index
    bs = k_hbm.shape[1]
    NB = tables_ref.shape[1]
    W = min(_WINDOW, NB)
    R = qpk * TQ                                 # stacked query rows
    start = starts_ref[b]
    qlen = qlens_ref[b]

    # Row r of the stacked [R, D] query tile is head r // TQ at tile-local
    # offset r % TQ; its causal horizon is the absolute query position.
    row_off = jax.lax.rem(
        jax.lax.broadcasted_iota(jnp.int32, (R, 1), 0), TQ)
    q_bound = start + t * TQ + row_off

    # Pages to stream: everything through this tile's last valid query
    # position.  Dead tiles (inactive lane, or wholly past qlen) stream
    # exactly one page so every wait has a matching start; their rows are
    # garbage the caller never reads.
    live = (qlen > 0) & (t * TQ < qlen)
    ctx = jnp.where(live, start + jnp.minimum((t + 1) * TQ, qlen), 1)
    n_blocks = (ctx + bs - 1) // bs
    n_windows = (n_blocks + W - 1) // W

    if quant:
        onehot_g = (jax.lax.broadcasted_iota(jnp.int32, (1, KVH), 1)
                    == g).astype(jnp.float32)    # picks this group's scales

    qt = q_ref[0, :, 0, :].astype(jnp.float32)   # [TQ, qpk*D]
    q2 = jnp.concatenate(
        [qt[:, j * D:(j + 1) * D] for j in range(qpk)], axis=0)  # [R, D]

    def scoped(k_buf, v_buf, sem, ks_buf=None, vs_buf=None, ssem=None):
        # k_buf/v_buf: [2, W*bs, D] double-buffered page-slice slabs —
        # only this group's D lanes ever leave HBM.
        def start_window(slot, w):
            for i in range(W):
                j = jnp.minimum(w * W + i, NB - 1)
                blk = tables_ref[b, j]
                pltpu.make_async_copy(
                    k_hbm.at[blk, :, pl.ds(g * D, D)],
                    k_buf.at[slot, pl.ds(i * bs, bs)],
                    sem.at[slot, i, 0]).start()
                pltpu.make_async_copy(
                    v_hbm.at[blk, :, pl.ds(g * D, D)],
                    v_buf.at[slot, pl.ds(i * bs, bs)],
                    sem.at[slot, i, 1]).start()
                if quant:
                    pltpu.make_async_copy(
                        ks_hbm.at[blk], ks_buf.at[slot, pl.ds(i * bs, bs)],
                        ssem.at[slot, i, 0]).start()
                    pltpu.make_async_copy(
                        vs_hbm.at[blk], vs_buf.at[slot, pl.ds(i * bs, bs)],
                        ssem.at[slot, i, 1]).start()

        def wait_window(slot, w):
            for i in range(W):
                j = jnp.minimum(w * W + i, NB - 1)
                blk = tables_ref[b, j]
                pltpu.make_async_copy(
                    k_hbm.at[blk, :, pl.ds(g * D, D)],
                    k_buf.at[slot, pl.ds(i * bs, bs)],
                    sem.at[slot, i, 0]).wait()
                pltpu.make_async_copy(
                    v_hbm.at[blk, :, pl.ds(g * D, D)],
                    v_buf.at[slot, pl.ds(i * bs, bs)],
                    sem.at[slot, i, 1]).wait()
                if quant:
                    pltpu.make_async_copy(
                        ks_hbm.at[blk], ks_buf.at[slot, pl.ds(i * bs, bs)],
                        ssem.at[slot, i, 0]).wait()
                    pltpu.make_async_copy(
                        vs_hbm.at[blk], vs_buf.at[slot, pl.ds(i * bs, bs)],
                        ssem.at[slot, i, 1]).wait()

        start_window(0, 0)                       # n_windows >= 1 always

        def body(w, carry):
            m, l, acc = carry
            slot = jax.lax.rem(w, 2)

            @pl.when(w + 1 < n_windows)
            def _prefetch():
                start_window(1 - slot, w + 1)

            wait_window(slot, w)
            p_idx = (w * (W * bs)
                     + jax.lax.broadcasted_iota(jnp.int32, (1, W * bs), 1))
            valid = p_idx <= q_bound             # causal, absolute positions
            kblk = k_buf[slot].astype(jnp.float32)          # [W*bs, D]
            vblk = v_buf[slot].astype(jnp.float32)
            s = jax.lax.dot_general(
                q2, kblk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)         # [R, W*bs]
            if quant:
                ks_g = jax.lax.dot_general(
                    onehot_g, ks_buf[slot], (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)     # [1, W*bs]
                vs_g = jax.lax.dot_general(
                    onehot_g, vs_buf[slot], (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)
                s = s * ks_g
            s = jnp.where(valid, s, NEG_INF)
            m_cur = jnp.max(s, axis=-1, keepdims=True)
            m_new = jnp.maximum(m, m_cur)
            alpha = jnp.exp(m - m_new)
            p = jnp.exp(s - m_new)
            l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
            if quant:
                p = p * vs_g
            pv = jax.lax.dot_general(
                p, vblk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)         # [R, D]
            return m_new, l_new, alpha * acc + pv

        m0 = jnp.full((R, 1), NEG_INF, jnp.float32)
        l0 = jnp.zeros((R, 1), jnp.float32)
        acc0 = jnp.zeros((R, D), jnp.float32)
        m, l, acc = jax.lax.fori_loop(0, n_windows, body, (m0, l0, acc0))
        # Position 0 is always causally visible, so l > 0 on every row;
        # the guard only hardens against a fully-degenerate table.
        out = acc / jnp.where(l > 0.0, l, 1.0)
        for j in range(qpk):
            o_ref[0, :, 0, j * D:(j + 1) * D] = out[
                j * TQ:(j + 1) * TQ].astype(o_ref.dtype)

    scope = dict(
        k_buf=pltpu.VMEM((2, W * bs, D), k_hbm.dtype),
        v_buf=pltpu.VMEM((2, W * bs, D), v_hbm.dtype),
        sem=pltpu.SemaphoreType.DMA((2, W, 2)),
    )
    if quant:
        scope.update(
            ks_buf=pltpu.VMEM((2, W * bs, KVH), jnp.float32),
            vs_buf=pltpu.VMEM((2, W * bs, KVH), jnp.float32),
            ssem=pltpu.SemaphoreType.DMA((2, W, 2)),
        )
    pl.run_scoped(scoped, **scope)


def flash_prefill_attention(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    block_table: jnp.ndarray,
    start: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    k_scale: jnp.ndarray | None = None,
    v_scale: jnp.ndarray | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Causal prefill attention reading K/V straight from the paged pool.

    Query token ``i`` of sequence ``b`` sits at absolute position
    ``start[b] + i`` and attends causally through itself — the same
    geometry contract as ``paged_verify_attention_pallas``, but tiled for
    bucket-sized S: queries split into TQ-token tiles (largest power of two
    <= 128 dividing S), scores reduce through online-softmax carries, and
    the ``[S, T]`` score matrix is never materialized.  The chunk's own K/V
    must already be scattered into the pages (models/llama.py scatters
    before attention), which is what collapses fresh prefill
    (``start = 0``), continuation chunks, and spec verify into one kernel.

    ``k_scale``/``v_scale`` ([num_blocks, bs, KVH] float32) switch on
    in-kernel dequantization of int8/fp8 pages — the quantized pool never
    widens in HBM.

    Args:
      q: [B, S, H, D] (S = prefill bucket).
      start: [B] int32 tokens already cached before this chunk (0 = fresh).
      lengths: [B] int32 valid query tokens (0 = inactive lane; its rows
        compute against the null block and are discarded by the caller).

    Returns:
      [B, S, H, D] in q.dtype.
    """
    B, S, H, D = q.shape
    nblk, bs, F = k_pages.shape
    assert F % D == 0 and D <= 128, (F, D)
    KVH = F // D
    assert H % KVH == 0, (H, KVH)
    qpk = H // KVH
    quant = k_scale is not None
    TQ = next(tt for tt in (128, 64, 32, 16, 8, 4, 2, 1) if S % tt == 0)
    NQ = S // TQ

    # Head order is group-major (head h serves kv group h // qpk), so a
    # plain reshape lands each group's qpk heads on contiguous D-lane
    # slices of its [B, S, KVH, qpk*D] slab.
    qg = (q * (D ** -0.5)).reshape(B, S, KVH, qpk * D)

    def qmap(b, g, t, *_):
        return (b, t, g, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, KVH, NQ),
        in_specs=[
            pl.BlockSpec((1, TQ, 1, qpk * D), qmap),
            pl.BlockSpec(memory_space=pl.ANY),   # K pages stay in HBM
            pl.BlockSpec(memory_space=pl.ANY),   # V pages stay in HBM
        ] + ([pl.BlockSpec(memory_space=pl.ANY)] * 2 if quant else []),
        out_specs=pl.BlockSpec((1, TQ, 1, qpk * D), qmap),
    )

    operands = [block_table, start.astype(jnp.int32),
                lengths.astype(jnp.int32), qg, k_pages, v_pages]
    if quant:
        operands += [k_scale, v_scale]
    out = pl.pallas_call(
        functools.partial(_flash_prefill_kernel, TQ, D, KVH, qpk, quant),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, S, KVH, qpk * D), q.dtype),
        compiler_params=_CompilerParams(
            # Programs are fully independent (read-only pages, disjoint
            # output tiles): megacore may split any grid axis.
            dimension_semantics=("parallel", "parallel", "parallel"),
        ),
        interpret=interpret,
    )(*operands)
    return out.reshape(B, S, H, D)


# Marker consumed by models/llama.py:is_flash_prefill_impl — the prefill
# family routes all three geometries (fresh/chunk/verify) through this
# calling convention, passing scale planes for quantized pools.
flash_prefill_attention.flash_prefill = True
