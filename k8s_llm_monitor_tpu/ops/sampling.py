"""Token sampling: greedy / temperature / top-k / top-p, jit-friendly.

All parameters are per-sequence arrays so a continuously-batched decode step
can mix greedy and sampled requests in one compiled program (no recompilation
per sampling config — shapes and dtypes are static).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sample_tokens(
    rng: jax.Array,
    logits: jnp.ndarray,
    *,
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
) -> jnp.ndarray:
    """Sample next tokens from final-position logits.

    Args:
      rng: PRNG key.
      logits: [B, V] float.
      temperature: [B] float; <= 0 means greedy (argmax).
      top_k: [B] int32; <= 0 disables top-k.
      top_p: [B] float; >= 1.0 disables nucleus filtering.

    Returns:
      [B] int32 token ids.
    """
    B, V = logits.shape
    logits = logits.astype(jnp.float32)

    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    # --- temperature ---
    temp = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = logits / temp

    # --- top-k: mask everything below the k-th largest logit ---
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]  # [B, V] descending
    k = jnp.clip(top_k, 1, V)
    kth = jnp.take_along_axis(sorted_desc, (k - 1)[:, None], axis=-1)  # [B, 1]
    use_topk = (top_k > 0)[:, None]
    scaled = jnp.where(use_topk & (scaled < kth), -jnp.inf, scaled)

    # --- top-p (nucleus): keep smallest prefix of the sorted distribution with
    # cumulative prob >= top_p; implemented via the sorted cumulative mass ---
    sorted_desc2 = jnp.sort(scaled, axis=-1)[:, ::-1]
    probs_sorted = jax.nn.softmax(sorted_desc2, axis=-1)
    cum = jnp.cumsum(probs_sorted, axis=-1)
    # Keep entries where the cumulative mass *before* them is < top_p.
    keep_sorted = (cum - probs_sorted) < top_p[:, None]
    # Threshold logit = smallest kept sorted logit.
    thresh = jnp.min(jnp.where(keep_sorted, sorted_desc2, jnp.inf), axis=-1)
    use_topp = (top_p < 1.0)[:, None]
    scaled = jnp.where(use_topp & (scaled < thresh[:, None]), -jnp.inf, scaled)

    sampled = jax.random.categorical(rng, scaled, axis=-1).astype(jnp.int32)
    return jnp.where(temperature <= 0.0, greedy, sampled)
