"""Token sampling: greedy / temperature / top-k / top-p, jit-friendly.

All parameters are per-sequence arrays so a continuously-batched decode step
can mix greedy and sampled requests in one compiled program (no recompilation
per sampling config — shapes and dtypes are static).

Top-k and top-p are both expressed as *rank* cutoffs over one descending
argsort: ranks are unique even when logits tie, so a tied distribution can
never defeat the nucleus mask (a strict value-threshold comparison would keep
every tied token and make ``top_p=0.1`` a no-op on uniform logits).

``greedy_tokens`` is the sort-free fast path — serving/engine.py dispatches
to it when every active lane in a decode step is greedy (a pure argmax, no
[B, V] sort traffic).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def greedy_tokens(logits: jnp.ndarray) -> jnp.ndarray:
    """Argmax sampling, [B, V] -> [B] int32. No sorting, no rng."""
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def filtered_scaled_logits(
    logits: jnp.ndarray,
    *,
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
) -> jnp.ndarray:
    """Temperature-scale then top-k/top-p-mask logits: the SINGLE
    definition of the sampling distribution, shared by ``sample_tokens``
    and the speculative-decode acceptance (serving/spec.py) so the
    speculated and sequential chains target the identical distribution.

    Args: logits [B, V]; temperature/top_k/top_p [B] (semantics as in
    ``sample_tokens``).  Returns [B, V] f32, filtered entries -inf.
    """
    B, V = logits.shape
    logits = logits.astype(jnp.float32)
    temp = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = logits / temp

    # One descending argsort serves both filters.  order[b, r] = token id with
    # rank r; rank[b, t] = rank of token t.
    order = jnp.argsort(-scaled, axis=-1)
    sorted_vals = jnp.take_along_axis(scaled, order, axis=-1)
    rows = jnp.arange(B, dtype=jnp.int32)[:, None]
    rank = jnp.zeros((B, V), jnp.int32).at[rows, order].set(
        jnp.broadcast_to(jnp.arange(V, dtype=jnp.int32)[None, :], (B, V))
    )

    # top-k: keep ranks < k (k <= 0 disables).
    k = jnp.where(top_k > 0, jnp.clip(top_k, 1, V), V)[:, None]

    # top-p over the top-k-filtered distribution: keep the smallest rank
    # prefix whose cumulative mass reaches top_p (always >= 1 token).
    sorted_masked = jnp.where(
        jnp.arange(V, dtype=jnp.int32)[None, :] < k, sorted_vals, -jnp.inf
    )
    probs_sorted = jax.nn.softmax(sorted_masked, axis=-1)
    cum_before = jnp.cumsum(probs_sorted, axis=-1) - probs_sorted
    n_keep = jnp.sum(cum_before < top_p[:, None], axis=-1, dtype=jnp.int32)
    n_keep = jnp.where(top_p < 1.0, jnp.maximum(n_keep, 1), V)[:, None]

    keep = rank < jnp.minimum(k, n_keep)
    return jnp.where(keep, scaled, -jnp.inf)


def sample_tokens_bounded(
    rng: jax.Array,
    logits: jnp.ndarray,
    *,
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
    k_cap: int,
) -> jnp.ndarray:
    """``sample_tokens`` restricted to the top ``k_cap`` logits per lane.

    Samples the EXACT ``filtered_scaled_logits`` distribution whenever every
    sampling lane has ``0 < top_k <= k_cap`` (the dispatcher checks this
    before selecting the bounded program): top-k keeps at most ``k_cap``
    tokens, and top-p here filters *within* the top-k distribution, so no
    token outside the top ``k_cap`` can ever carry probability.  The win is
    replacing the full-vocab descending argsort (V is 128k on the 8B
    target — the sort dominates the on-device sampling cost inside the
    fused decode scan) with one ``lax.top_k`` over ``k_cap`` lanes.

    Ties resolve identically to the full path (lowest token id first, both
    via stable ordering), but the categorical draw uses a [B, k_cap] gumbel
    shape instead of [B, V] — same distribution, different stream for a
    given key.  Greedy lanes (temperature <= 0) take the argmax exactly as
    in ``sample_tokens``.
    """
    B, V = logits.shape
    logits = logits.astype(jnp.float32)
    greedy = greedy_tokens(logits)
    temp = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = logits / temp

    vals, idx = jax.lax.top_k(scaled, k_cap)            # [B, k_cap], sorted
    ranks = jnp.arange(k_cap, dtype=jnp.int32)[None, :]
    k = jnp.clip(top_k, 1, k_cap)[:, None]
    masked = jnp.where(ranks < k, vals, -jnp.inf)
    probs = jax.nn.softmax(masked, axis=-1)
    cum_before = jnp.cumsum(probs, axis=-1) - probs
    n_keep = jnp.sum(cum_before < top_p[:, None], axis=-1, dtype=jnp.int32)
    n_keep = jnp.where(top_p < 1.0,
                       jnp.maximum(n_keep, 1), k_cap)[:, None]
    keep = ranks < jnp.minimum(k, n_keep)
    filtered = jnp.where(keep, masked, -jnp.inf)

    choice = jax.random.categorical(rng, filtered, axis=-1)   # [B] < k_cap
    sampled = jnp.take_along_axis(idx, choice[:, None], axis=-1)[:, 0]
    return jnp.where(temperature <= 0.0, greedy,
                     sampled.astype(jnp.int32))


# Large-negative instead of -inf for FSM-disallowed entries: a fully
# finite row keeps softmax/categorical NaN-free even before the grammar's
# >=1-allowed-token guarantee kicks in, and survives the /temperature
# scaling in both samplers without overflow (1e9 / 1e-6 = 1e15 << f32 max).
_FSM_NEG = -1e9


def fsm_allowed_mask(fsm_state: jnp.ndarray, fsm_trans: jnp.ndarray,
                     vocab: int) -> jnp.ndarray:
    """Per-lane allowed-token mask from a grammar FSM.

    Args:
      fsm_state: [B] int32 — per-lane state; 0 is the FREE state (lane is
        unconstrained, everything allowed).
      fsm_trans: [S, Vg] int32 — dense transition table (diagnosis.grammar
        ``TokenFSM.trans``); entry >= 0 allowed, -1 disallowed.
      vocab: model vocab size V (>= Vg); tokens past the grammar vocab are
        disallowed for constrained lanes.

    Returns: [B, V] bool.
    """
    rows = fsm_trans[jnp.clip(fsm_state, 0, fsm_trans.shape[0] - 1)]
    allowed = rows >= 0
    if vocab > fsm_trans.shape[1]:
        pad = jnp.zeros(
            (allowed.shape[0], vocab - fsm_trans.shape[1]), dtype=bool)
        allowed = jnp.concatenate([allowed, pad], axis=-1)
    return allowed | (fsm_state <= 0)[:, None]


def fsm_mask_logits(logits: jnp.ndarray, fsm_state: jnp.ndarray,
                    fsm_trans: jnp.ndarray) -> jnp.ndarray:
    """Mask grammar-disallowed tokens to a large negative BEFORE sampling.

    Masking ahead of ``sample_tokens``/``sample_tokens_bounded`` (rather
    than inside them) keeps one distribution definition: greedy lanes
    (temperature <= 0) take the argmax of the *masked* logits, so a
    constrained-greedy lane is exact too.
    """
    allowed = fsm_allowed_mask(fsm_state, fsm_trans, logits.shape[-1])
    return jnp.where(allowed, logits.astype(jnp.float32), _FSM_NEG)


def fsm_advance(fsm_state: jnp.ndarray, fsm_trans: jnp.ndarray,
                tokens: jnp.ndarray) -> jnp.ndarray:
    """Next per-lane FSM state after ``tokens`` ([B] int32).

    FREE lanes stay at 0 by table construction (row 0 is all-zero); token
    ids beyond the grammar vocab are clipped — a constrained lane can never
    sample one (they are masked), and for free lanes any index reads row
    entries that all map to 0.
    """
    state = jnp.clip(fsm_state, 0, fsm_trans.shape[0] - 1)
    tok = jnp.clip(tokens, 0, fsm_trans.shape[1] - 1)
    return fsm_trans[state, tok].astype(jnp.int32)


def sample_tokens(
    rng: jax.Array,
    logits: jnp.ndarray,
    *,
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
) -> jnp.ndarray:
    """Sample next tokens from final-position logits.

    Args:
      rng: PRNG key.
      logits: [B, V] float.
      temperature: [B] float; <= 0 means greedy (argmax).
      top_k: [B] int32; <= 0 disables top-k.
      top_p: [B] float; >= 1.0 disables nucleus filtering.

    Returns:
      [B] int32 token ids.
    """
    greedy = greedy_tokens(logits)
    filtered = filtered_scaled_logits(
        logits, temperature=temperature, top_k=top_k, top_p=top_p)
    sampled = jax.random.categorical(rng, filtered, axis=-1).astype(jnp.int32)
    return jnp.where(temperature <= 0.0, greedy, sampled)
