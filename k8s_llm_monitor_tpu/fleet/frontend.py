"""Router-role frontend: the fleet behind the standard monitor HTTP API.

``FleetAnalysis`` duck-types the slice of ``AnalysisEngine`` the HTTP
handlers call (``query`` / ``query_stream`` / ``analyze``), delegating to a
``FleetRouter`` over HTTP replicas instead of a local engine.  A router
process therefore serves the *same* ``/api/v1/query`` and
``/api/v1/analyze`` contract as a replica — clients and dashboards don't
know which tier they're talking to.

It deliberately has no ``backend`` attribute: ``MonitorServer`` discovers a
local engine through ``analysis.backend``, and a router has none — its
health comes from the registry (``analysis.router``), wired into
``health_snapshot`` and the exporter's fleet gauges.
"""

from __future__ import annotations

import logging

from k8s_llm_monitor_tpu.fleet.registry import ReplicaRegistry
from k8s_llm_monitor_tpu.fleet.replica import HTTPReplica
from k8s_llm_monitor_tpu.fleet.router import FleetRouter, HedgeConfig
from k8s_llm_monitor_tpu.monitor.models import (AnalysisRequest,
                                                AnalysisResponse)
from k8s_llm_monitor_tpu.observability.tracing import get_tracer

logger = logging.getLogger("fleet.frontend")


class FleetAnalysis:
    """AnalysisEngine-shaped facade over a ``FleetRouter``."""

    def __init__(self, router: FleetRouter):
        self.router = router

    @staticmethod
    def _to_response(payload: dict) -> AnalysisResponse:
        """Rehydrate a replica's JSON reply; the timestamp is re-stamped
        locally (the wire value is a string, and callers only log it)."""
        payload = payload or {}
        return AnalysisResponse(
            request_id=str(payload.get("request_id", "")),
            status=str(payload.get("status", "error")),
            result=payload.get("result") or {},
            error=str(payload.get("error", "")),
            error_kind=str(payload.get("error_kind", "")),
        )

    def query(self, question: str, slo_class: str = "interactive",
              tenant: str = "") -> AnalysisResponse:
        # Root (or joined) span for the text path: the replica's HTTP hop
        # inherits this context via the ApiClient traceparent header.
        with get_tracer().span("router.query", attrs={"class": slo_class}):
            return self._to_response(
                self.router.query(question, slo_class=slo_class,
                                  tenant=tenant))

    def query_stream(self, question: str, slo_class: str = "interactive",
                     tenant: str = ""):
        # The span covers dispatch (replica choice + SSE open); streaming
        # itself is consumed by the HTTP handler after this returns.
        with get_tracer().span("router.query_stream",
                               attrs={"class": slo_class}):
            return self.router.query_stream(question, slo_class=slo_class,
                                            tenant=tenant)

    def analyze(self, request: AnalysisRequest,
                tenant: str = "") -> AnalysisResponse:
        return self._to_response(self.router.analyze({
            "type": request.type,
            "parameters": request.parameters,
            "context": request.context,
        }, tenant=tenant))

    def diagnoses(self, limit: int = 0) -> dict:
        """Raw replica payload for GET /api/v1/diagnoses — the handler
        serves it verbatim, so router and replica answer the same shape
        (plus the ``replica`` field saying who answered)."""
        return self.router.diagnoses(limit)

    def close(self) -> None:
        self.router.registry.stop_probes()
        for rid in self.router.registry.ids():
            entry = self.router.registry.get(rid)
            if entry is not None:
                entry.replica.close()


def build_router_server(config, web_dir=None):
    """Wire a router-role ``MonitorServer``: HTTP replica adapters from
    ``config.fleet.replicas`` → registry (+ background probes) → router →
    ``FleetAnalysis`` behind the standard HTTP API.  No cluster client and
    no metrics manager — a router routes; replicas analyze."""
    from k8s_llm_monitor_tpu.monitor.server import MonitorServer

    fcfg = config.fleet
    if not fcfg.replicas:
        raise ValueError(
            "router role needs fleet.replicas (comma-separated URLs via "
            "FLEET_REPLICAS or the fleet: config block)")
    registry = ReplicaRegistry(
        breaker_failures=fcfg.breaker_failures,
        breaker_cooldown_s=fcfg.breaker_cooldown_s)
    for i, url in enumerate(fcfg.replicas):
        registry.add(HTTPReplica(
            f"replica-{i}", url,
            connect_timeout_s=fcfg.connect_timeout_s,
            read_timeout_s=fcfg.read_timeout_s))
    governor = None
    tcfg = getattr(config, "tenancy", None)
    if tcfg is not None and tcfg.enabled:
        from k8s_llm_monitor_tpu.resilience.tenancy import TenantGovernor

        # Fleet tenancy: the router owns the ONE governor for the whole
        # fleet — it admits per logical request before any replica
        # dispatch, so hedges and failover replays can never double-charge
        # (replicas behind this router run with governor=None).
        governor = TenantGovernor(
            requests_per_s=tcfg.requests_per_s,
            request_burst=tcfg.request_burst,
            tokens_per_s=tcfg.tokens_per_s,
            token_burst=tcfg.token_burst,
            enforce=tcfg.enforce,
            max_tenants=tcfg.max_tenants)
    router = FleetRouter(
        registry, policy=fcfg.policy,
        hedge=HedgeConfig(enabled=fcfg.hedge_enabled,
                          min_delay_s=fcfg.hedge_min_delay_s,
                          fixed_delay_s=fcfg.hedge_fixed_delay_s),
        max_failovers=fcfg.max_failovers,
        affinity_prefix_tokens=fcfg.affinity_prefix_tokens,
        batch_spill_threshold=fcfg.batch_spill_threshold,
        drain_sweep_budget=fcfg.drain_sweep_budget,
        governor=governor)
    registry.refresh()
    registry.start_probes(interval_s=fcfg.probe_interval_s)
    logger.info("router fronting %d replica(s), policy=%s, hedging=%s",
                len(registry), fcfg.policy,
                "on" if fcfg.hedge_enabled else "off")
    signals = None
    if config.telemetry.enabled:
        from k8s_llm_monitor_tpu.observability.flight import (
            get_flight_recorder,
        )
        from k8s_llm_monitor_tpu.observability.signals import SignalScraper

        # Router-role telemetry: fleet-merged series fed by the registry
        # probes (telemetry_sample()), behind GET /api/v1/signals.  A
        # router has no diagnosis pipeline by default — anomalies are
        # still derived and reported; callers wanting self-diagnosis
        # attach a pipeline to both srv.diagnosis and srv.signals.
        signals = SignalScraper(cfg=config.telemetry)
        get_flight_recorder().signal_source = (
            lambda: signals.store.window_snapshot(
                config.telemetry.flight_window_s))
    srv = MonitorServer(
        config=config, analysis=FleetAnalysis(router), web_dir=web_dir,
        signals=signals)
    srv.governor = governor
    if signals is not None:
        signals.attach(srv)
    if config.autoscale.enabled and signals is not None:
        srv.autoscaler = _build_autoscaler(config, registry, signals)
    return srv


def _build_autoscaler(config, registry, signals):
    """Controller over the kube scale executor (StatefulSet /scale through
    the hardened client).  Returns None — autoscaling disabled, router
    unaffected — when no in-cluster credentials exist (dev/bench fleets
    drive a ``LocalPoolExecutor`` directly instead)."""
    from k8s_llm_monitor_tpu.fleet.autoscaler import (AutoscaleController,
                                                      KubeScaleExecutor)
    from k8s_llm_monitor_tpu.monitor.kube_rest import KubeRestBackend

    try:
        backend = KubeRestBackend.in_cluster()
    except Exception as exc:  # noqa: BLE001 — no cluster: no autoscaler
        logger.warning("autoscale.enabled but no cluster credentials "
                       "(%s); elasticity controller disabled", exc)
        return None
    controller = AutoscaleController(
        signals, KubeScaleExecutor(backend, config.autoscale),
        config.autoscale, registry=registry)
    logger.info("elasticity controller armed (interval=%.1fs, dwell=%.0fs, "
                "cooldown=%.0fs)", config.autoscale.interval_s,
                config.autoscale.scale_down_dwell_s,
                config.autoscale.cooldown_s)
    return controller
