"""Elasticity controller: close the telemetry plane's sense→decide→act loop.

``AutoscaleController`` consumes the ``SignalScraper``'s per-target
``scale_hint``s (plus the ``queue_growth`` / ``ttft_breach`` anomaly
flags), folds them into one desired direction per fleet *role*
(prefill / decode / unified — each target's role comes from the registry's
probe rows), and executes scale decisions through a pluggable executor:

* ``KubeScaleExecutor`` — per-role StatefulSet ``/scale`` subresources
  through the hardened kube client (retry budget + breaker, PR 2
  semantics), dry-run-first so a malformed patch never hits the fleet.
* ``LocalPoolExecutor`` — an in-process ``LocalReplica`` pool for tests,
  bench, and the single-binary dev mode: scale-up spawns a replica via a
  factory and registers it; scale-down *drains* the newest replica of the
  role (the router stops dispatching to it, in-flight streams finish) and
  ``reap()`` removes it once idle.  The whole loop is chaos-testable
  without a cluster.

Hysteresis — the controller's entire job is to NOT act most of the time:

* **cooldown** after any executed action (no thrash on its own wake),
* **dwell-gated scale-down**: hints must agree "down" continuously for
  ``scale_down_dwell_s`` before a replica is removed (scale-up stays
  fast — under-capacity hurts users, over-capacity hurts the bill),
* **min/max replicas per role** (a runaway signal can never scale to
  zero or to infinity),
* **flap damping**: more than ``flap_max_flips`` desire-direction changes
  inside ``flap_window_s`` freezes the role until hints settle,
* a **per-verb circuit breaker** around the executor: a broken API
  server opens the breaker and the controller refuses (counted) instead
  of hammering.

Every action AND every refusal lands in
``autoscale_actions_total{role,direction,outcome}`` and — when a
diagnosis pipeline is wired — as a synthetic ``source="autoscaler"``
event, so the monitor can diagnose its own elasticity decisions.

All time comes from an injectable clock; the gate proofs in
``tests/test_elasticity.py`` drive ``tick()`` with a fake clock.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from collections import deque
from typing import Any, Callable, Optional

from k8s_llm_monitor_tpu.devtools.lockcheck import guarded_by, make_lock
from k8s_llm_monitor_tpu.resilience.retry import CircuitBreaker, CircuitOpen

logger = logging.getLogger("fleet.autoscaler")

__all__ = ["AutoscaleController", "KubeScaleExecutor", "LocalPoolExecutor"]

ROLES = ("prefill", "decode", "unified")

# Anomaly flags that read as "scale up now" regardless of the folded hint.
_UP_FLAGS = ("queue_growth", "ttft_breach")


class KubeScaleExecutor:
    """Scale per-role StatefulSets through the hardened kube backend.

    The backend (``monitor/kube_rest.py``) already owns retries, backoff,
    fault injection, and its own cluster breaker; this adapter only maps
    role → StatefulSet name and role → scale verb."""

    def __init__(self, backend, cfg) -> None:
        self.backend = backend
        self.cfg = cfg

    def _name(self, role: str) -> str:
        return {
            "prefill": self.cfg.statefulset_prefill,
            "decode": self.cfg.statefulset_decode,
        }.get(role, self.cfg.statefulset_unified)

    def current_replicas(self, role: str) -> int:
        scale = self.backend.get_statefulset_scale(
            self.cfg.namespace, self._name(role))
        spec = scale.get("spec") or {}
        return int(spec.get("replicas", 0))

    def scale(self, role: str, replicas: int, dry_run: bool = False) -> None:
        self.backend.scale_statefulset(
            self.cfg.namespace, self._name(role), replicas, dry_run=dry_run)

    def reap(self) -> list[str]:
        return []  # kube terminates drained pods itself (preStop + grace)


class LocalPoolExecutor:
    """In-process replica pool behind the executor interface.

    ``factory(role, replica_id)`` builds a ready-to-serve replica
    (typically a ``LocalReplica`` over a fresh ``EngineService``); the
    executor registers it with the fleet registry and probes it once so
    the router can dispatch immediately.  Scale-down drains the newest
    replica of the role — removal happens later in ``reap()``, once the
    router-side inflight count hits zero, so no stream is cut."""

    def __init__(self, registry, factory: Callable[[str, str], Any]) -> None:
        self.registry = registry
        self.factory = factory
        self._seq = itertools.count()
        self._pools: dict[str, list] = {r: [] for r in ROLES}
        self._lock = make_lock("fleet.autoscaler.localpool")

    def adopt(self, role: str, replica) -> None:
        """Track a replica that was built outside the executor (the
        initial fleet) so current_replicas()/scale() see it."""
        with self._lock:
            self._pools.setdefault(role, []).append(replica)

    def _live(self, role: str) -> list:
        with self._lock:
            pool = list(self._pools.get(role, ()))
        return [r for r in pool if not getattr(r, "draining", False)]

    def current_replicas(self, role: str) -> int:
        return len(self._live(role))

    def scale(self, role: str, replicas: int, dry_run: bool = False) -> None:
        live = self._live(role)
        want = max(0, int(replicas))
        if dry_run:
            if want > len(live) and self.factory is None:
                raise RuntimeError("no replica factory for scale-up")
            return
        while len(live) < want:
            rid = f"{role}-auto-{next(self._seq)}"
            replica = self.factory(role, rid)
            with self._lock:
                self._pools.setdefault(role, []).append(replica)
            self.registry.add(replica)
            self.registry.refresh(rid)
            logger.info("local pool: spawned %s", rid)
            live.append(replica)
        while len(live) > want:
            victim = live.pop()  # newest first: oldest keep their caches
            drain = getattr(victim, "drain", None)
            if callable(drain):
                drain()
                # Probe now so the draining flag is visible to the router
                # (and the drain sweep fires) before the next cycle.
                self.registry.refresh(victim.replica_id)
                logger.info("local pool: draining %s", victim.replica_id)
            else:
                self.registry.remove(victim.replica_id)
                victim.close()

    def reap(self) -> list[str]:
        """Remove drained replicas whose router-side inflight hit zero.
        Returns the removed replica ids."""
        removed: list[str] = []
        with self._lock:
            draining = [(role, r) for role, pool in self._pools.items()
                        for r in pool if getattr(r, "draining", False)]
        for role, replica in draining:
            rid = replica.replica_id
            entry = self.registry.get(rid)
            if entry is not None and entry.inflight > 0:
                continue  # streams still finishing: not yet
            self.registry.remove(rid)
            with self._lock:
                pool = self._pools.get(role, [])
                if replica in pool:
                    pool.remove(replica)
            try:
                replica.close()
            except Exception:  # noqa: BLE001 — teardown is best-effort
                logger.exception("closing drained replica %s failed", rid)
            removed.append(rid)
            logger.info("local pool: reaped drained %s", rid)
        return removed


@guarded_by("_lock", "actions_total", "_last_action_t", "_down_since",
            "_flips", "_last_desire")
class AutoscaleController:
    """Sense (signals) → decide (hysteresis gates) → act (executor).

    ``tick()`` is the synchronous seam tests drive with a fake clock;
    ``start()`` runs it on a daemon thread every ``cfg.interval_s``.
    The controller acts only when every gate agrees — the acceptance
    criterion is literally "never acts while dwell/cooldown gates are
    closed or the breaker is open"."""

    def __init__(self, signals, executor, cfg=None, *,
                 registry=None, pipeline: Any = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        from k8s_llm_monitor_tpu.monitor.config import AutoscaleConfig

        self.cfg = cfg or AutoscaleConfig()
        self.signals = signals
        self.executor = executor
        self.registry = registry
        self.pipeline = pipeline
        self._clock = clock
        self.breaker = CircuitBreaker(
            failure_threshold=self.cfg.breaker_failures,
            cooldown_s=self.cfg.breaker_cooldown_s)
        # {(role, direction, outcome): count} — the exporter renders this
        # as autoscale_actions_total{role,direction,outcome}.
        self.actions_total: dict[tuple[str, str, str], int] = {}
        self.events: deque[dict] = deque(maxlen=64)
        self._last_action_t: Optional[float] = None
        self._down_since: dict[str, float] = {}
        self._flips: dict[str, deque] = {}
        self._last_desire: dict[str, str] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Created last (lockcheck construction rule).
        self._lock = make_lock("fleet.autoscaler")

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def _loop() -> None:
            while not self._stop.wait(timeout=self.cfg.interval_s):
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 — loop must survive
                    logger.exception("autoscale tick failed")

        self._thread = threading.Thread(
            target=_loop, name="fleet-autoscaler", daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    # -- sensing ---------------------------------------------------------

    def _role_of(self, target: str) -> str:
        if self.registry is not None:
            entry = self.registry.get(target)
            if entry is not None:
                role = entry.stats.role
                return role if role in ROLES else "unified"
        return "unified"

    def desired_directions(self) -> dict[str, str]:
        """Fold per-target hints/anomalies into one direction per role.
        Any target screaming "up" (hint or anomaly) wins for its role;
        "down" requires EVERY fresh target of the role to agree; stale
        targets are no evidence in either direction."""
        body = self.signals.signals()
        targets = body.get("targets") or {}
        votes: dict[str, list[str]] = {}
        for target, derived in targets.items():
            role = self._role_of(target)
            if derived.get("stale"):
                votes.setdefault(role, []).append("steady")
                continue
            hint = derived.get("scale_hint", "steady")
            if hint != "up" and any(f in _UP_FLAGS
                                    for f in derived.get("anomalies", ())):
                hint = "up"
            votes.setdefault(role, []).append(hint)
        out = {}
        for role, hints in votes.items():
            if "up" in hints:
                out[role] = "up"
            elif hints and all(h == "down" for h in hints):
                out[role] = "down"
            else:
                out[role] = "steady"
        return out

    # -- deciding --------------------------------------------------------

    def _bounds(self, role: str) -> tuple[int, int]:
        return {
            "prefill": (self.cfg.min_prefill, self.cfg.max_prefill),
            "decode": (self.cfg.min_decode, self.cfg.max_decode),
        }.get(role, (self.cfg.min_unified, self.cfg.max_unified))

    def _note(self, role: str, direction: str, outcome: str,
              detail: str = "") -> None:
        """Count + remember + (optionally) feed the diagnosis pipeline.
        Refusals are first-class outcomes: a controller that silently
        does nothing is undiagnosable."""
        now = self._clock()
        key = (role, direction, outcome)
        with self._lock:
            self.actions_total[key] = self.actions_total.get(key, 0) + 1
            self.events.append({
                "t_mono": round(now, 3), "role": role,
                "direction": direction, "outcome": outcome,
                "detail": detail,
            })
        if self.pipeline is None:
            return
        from k8s_llm_monitor_tpu.monitor.models import EventInfo

        event = EventInfo(
            type="Normal" if outcome == "applied" else "Warning",
            reason=f"Autoscale:{direction}:{outcome}",
            message=f"role {role}: {direction} -> {outcome}"
                    + (f" ({detail})" if detail else ""),
            source="autoscaler",
        )
        try:
            self.pipeline.offer(event)
        except Exception:  # noqa: BLE001 — feed is best-effort
            logger.exception("autoscaler event injection failed")

    def _flap_count(self, role: str, now: float) -> int:
        with self._lock:
            ring = self._flips.get(role)
            if ring is None:
                return 0
            while ring and now - ring[0] > self.cfg.flap_window_s:
                ring.popleft()
            return len(ring)

    def _track_desire(self, role: str, desire: str, now: float) -> None:
        with self._lock:
            prev = self._last_desire.get(role)
            if (prev is not None and desire != prev
                    and "steady" not in (prev, desire)):
                self._flips.setdefault(role, deque()).append(now)
            self._last_desire[role] = desire

    def _cooldown_left(self, now: float) -> float:
        with self._lock:
            last = self._last_action_t
        if last is None:
            return 0.0
        return max(0.0, self.cfg.cooldown_s - (now - last))

    # -- acting ----------------------------------------------------------

    def _execute(self, role: str, target: int, direction: str) -> str:
        """One gated executor call: breaker slot, dry-run first, then the
        real scale.  Returns the outcome string."""
        try:
            self.breaker.before_call()
        except CircuitOpen:
            return "refused_breaker"
        try:
            if self.cfg.dry_run_first:
                self.executor.scale(role, target, dry_run=True)
            self.executor.scale(role, target, dry_run=False)
        except Exception as exc:  # noqa: BLE001 — executor fault
            self.breaker.record_failure()
            logger.warning("scale %s -> %d failed: %s", role, target, exc)
            return "error"
        self.breaker.record_success()
        return "applied"

    def tick(self) -> list[dict]:
        """One decision cycle.  Returns the events recorded this cycle
        (actions and refusals both)."""
        now = self._clock()
        before = len(self.events)
        reap = getattr(self.executor, "reap", None)
        if callable(reap):
            reap()
        desires = self.desired_directions()
        # Opposing desires are a rebalance opportunity: move capacity
        # between roles instead of growing the fleet.
        ups = [r for r, d in desires.items() if d == "up"]
        downs = [r for r, d in desires.items() if d == "down"]
        for role, desire in sorted(desires.items()):
            self._track_desire(role, desire, now)
        if ups and downs:
            self.rebalance(downs[0], ups[0], now=now)
        else:
            for role, desire in sorted(desires.items()):
                if desire == "steady":
                    with self._lock:
                        self._down_since.pop(role, None)
                    continue
                self._step(role, desire, now)
        with self._lock:
            return list(self.events)[before:]

    def _gates(self, role: str, direction: str, now: float,
               dwell_gated: bool = True) -> Optional[str]:
        """Shared refusal ladder; returns the refusal outcome or None when
        every gate is open.  Order matters: the breaker is checked first
        (an unusable executor makes every other question moot), then
        cooldown, then the down-dwell, then flap damping."""
        if self.breaker.state == "open":
            return "refused_breaker"
        if self._cooldown_left(now) > 0.0:
            return "refused_cooldown"
        if direction == "down" and dwell_gated:
            with self._lock:
                since = self._down_since.setdefault(role, now)
            if now - since < self.cfg.scale_down_dwell_s:
                return "refused_dwell"
        if self._flap_count(role, now) > self.cfg.flap_max_flips:
            return "refused_flap"
        return None

    def _step(self, role: str, direction: str, now: float) -> None:
        refusal = self._gates(role, direction, now)
        if refusal is not None:
            self._note(role, direction, refusal)
            return
        try:
            current = int(self.executor.current_replicas(role))
        except Exception as exc:  # noqa: BLE001 — executor fault
            self.breaker.record_failure()
            self._note(role, direction, "error", f"read: {exc}")
            return
        lo, hi = self._bounds(role)
        target = min(hi, current + 1) if direction == "up" \
            else max(lo, current - 1)
        if target == current:
            self._note(role, direction, "refused_minmax",
                       f"at bound {current} in [{lo},{hi}]")
            return
        outcome = self._execute(role, target, direction)
        self._note(role, direction, outcome, f"{current}->{target}")
        if outcome == "applied":
            with self._lock:
                self._last_action_t = now
                self._down_since.pop(role, None)
            logger.info("autoscale %s: %s %d -> %d",
                        direction, role, current, target)

    def rebalance(self, from_role: str, to_role: str,
                  now: Optional[float] = None) -> bool:
        """Move one replica of capacity between roles (scale ``from_role``
        down and ``to_role`` up) under the same gates as a plain action;
        the scale-down half keeps its dwell gate — a rebalance must not
        be a back door around the down hysteresis.  Returns True when
        both halves applied."""
        now = self._clock() if now is None else now
        refusal = self._gates(from_role, "down", now) \
            or self._gates(to_role, "up", now)
        if refusal is not None:
            self._note(to_role, "rebalance", refusal,
                       f"{from_role}->{to_role}")
            return False
        try:
            cur_from = int(self.executor.current_replicas(from_role))
            cur_to = int(self.executor.current_replicas(to_role))
        except Exception as exc:  # noqa: BLE001 — executor fault
            self.breaker.record_failure()
            self._note(to_role, "rebalance", "error", f"read: {exc}")
            return False
        lo_f, _ = self._bounds(from_role)
        _, hi_t = self._bounds(to_role)
        if cur_from - 1 < lo_f or cur_to + 1 > hi_t:
            self._note(to_role, "rebalance", "refused_minmax",
                       f"{from_role}@{cur_from} -> {to_role}@{cur_to}")
            return False
        out_up = self._execute(to_role, cur_to + 1, "up")
        if out_up != "applied":
            self._note(to_role, "rebalance", out_up)
            return False
        out_down = self._execute(from_role, cur_from - 1, "down")
        self._note(from_role, "rebalance", out_down,
                   f"{from_role} {cur_from}->{cur_from - 1}")
        self._note(to_role, "rebalance", "applied",
                   f"{to_role} {cur_to}->{cur_to + 1}")
        with self._lock:
            self._last_action_t = now
            self._down_since.pop(from_role, None)
        logger.info("autoscale rebalance: %s -> %s", from_role, to_role)
        return out_down == "applied"

    # -- observability ---------------------------------------------------

    def counters(self) -> dict:
        with self._lock:
            return {
                "actions_total": dict(self.actions_total),
                "recent": list(self.events),
            }

    def snapshot(self) -> dict:
        """JSON-safe block for /api/v1/stats on the router role."""
        with self._lock:
            actions = {
                f"{role}/{direction}/{outcome}": n
                for (role, direction, outcome), n
                in sorted(self.actions_total.items())}
            recent = list(self.events)[-8:]
            last = self._last_action_t
        return {
            "enabled": bool(self.cfg.enabled),
            "breaker_state": self.breaker.state,
            "cooldown_left_s": round(self._cooldown_left(self._clock()), 3),
            "last_action_t_mono": last,
            "actions_total": actions,
            "recent": recent,
        }
