"""Fleet tier: route queries across N engine replicas.

The single-replica stack (PRs 1–4) made one engine fast, crash-safe, and
observable; this package scales it *out*: a ``ReplicaRegistry`` tracks
replica health and load, replica adapters put in-process engines and remote
HTTP replicas behind one interface, and the ``FleetRouter`` dispatches with
pluggable policies (least-loaded, prefix-affinity rendezvous hashing),
per-replica circuit breakers, hedged dispatch, and mid-stream failover with
the supervisor's idempotent-replay contract.  See docs/fleet.md.
"""

from k8s_llm_monitor_tpu.fleet.autoscaler import (AutoscaleController,
                                                  KubeScaleExecutor,
                                                  LocalPoolExecutor)
from k8s_llm_monitor_tpu.fleet.registry import (Candidate, ReplicaRegistry,
                                                ReplicaStats)
from k8s_llm_monitor_tpu.fleet.replica import (HTTPReplica, LocalReplica,
                                               Replica, ReplicaUnavailable)
from k8s_llm_monitor_tpu.fleet.router import (POLICIES, FleetRouter,
                                              HedgeConfig, LeastLoadedPolicy,
                                              PrefixAffinityPolicy,
                                              RoundRobinPolicy, RoutingPolicy)

__all__ = [
    "Candidate",
    "ReplicaRegistry",
    "ReplicaStats",
    "Replica",
    "ReplicaUnavailable",
    "LocalReplica",
    "HTTPReplica",
    "FleetRouter",
    "HedgeConfig",
    "RoutingPolicy",
    "RoundRobinPolicy",
    "LeastLoadedPolicy",
    "PrefixAffinityPolicy",
    "POLICIES",
    "AutoscaleController",
    "KubeScaleExecutor",
    "LocalPoolExecutor",
]
