"""Replica membership, health, and load-signal tracking for the fleet tier.

The ``ReplicaRegistry`` is the router's single source of truth about the
fleet: which replicas exist, which are ready (probed through each replica's
``/readyz`` / ``HealthMonitor`` semantics), what their last stats snapshot
said (queue tokens, busy slots, prefix-cache hit rate — the weighted
least-loaded signal), how many router-side requests are in flight on each,
and each replica's ``CircuitBreaker`` state.

Probing is pull-based: ``refresh()`` polls every replica once (tests and
the bench call it synchronously); ``start_probes()`` runs the same poll on
a background thread for the server role.  A probe failure marks the
replica unready and records a breaker failure — the breaker, not the probe
loop, decides when to start trusting the replica again (half-open trial on
the next dispatch after the cooldown).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Optional

from k8s_llm_monitor_tpu.devtools.lockcheck import guarded_by, make_lock
from k8s_llm_monitor_tpu.resilience.retry import CircuitBreaker

logger = logging.getLogger("fleet.registry")


@dataclasses.dataclass
class ReplicaStats:
    """One replica's load snapshot — the shape ``GET /api/v1/stats``
    serves and ``Replica.stats()`` returns."""

    queue_depth: int = 0
    queue_tokens: int = 0
    busy_slots: int = 0
    total_slots: int = 0
    prefix_hits: int = 0
    prefix_misses: int = 0
    # Per-SLO-class queued tokens and the replica's brownout rung
    # (resilience/slo.py) — class-aware routing signals; absent keys mean
    # a pre-class replica (treated as all-standard, normal).
    queue_by_class: dict = dataclasses.field(default_factory=dict)
    brownout: int = 0
    # KV tier snapshot (engine ``kv_tier_stats()``): quant mode, host
    # spill/restore counters.  Absent on pre-tiering replicas — routing
    # never requires it; the fleet exporter and migration diagnostics do.
    kv_tier: dict = dataclasses.field(default_factory=dict)
    # Signal-scraper inputs (telemetry plane): admission headroom in
    # tokens (None on pre-telemetry replicas — None, not 0, so the
    # scraper records a NaN marker instead of fake emptiness), per-class
    # shed/preemption totals, and the per-class TTFT EMAs (classes with
    # no completion yet are simply absent).
    headroom_tokens: Optional[float] = None
    shed_by_class: dict = dataclasses.field(default_factory=dict)
    ttft_ema_by_class: dict = dataclasses.field(default_factory=dict)
    preemptions_by_class: dict = dataclasses.field(default_factory=dict)
    # Disaggregation role announced by the replica itself (FLEET_ROLE):
    # "prefill" | "decode" | "unified".  Absent on pre-role replicas —
    # treated as unified, so a mixed fleet keeps routing.
    role: str = "unified"
    # Lifecycle: a draining replica finishes its in-flight streams but
    # must receive no new dispatches and must not win prefix affinity.
    draining: bool = False

    @property
    def prefix_hit_rate(self) -> float:
        seen = self.prefix_hits + self.prefix_misses
        return self.prefix_hits / seen if seen else 0.0

    @classmethod
    def from_payload(cls, payload: dict) -> "ReplicaStats":
        """Parse the ``/api/v1/stats`` response body (``engine`` block)."""
        eng = (payload or {}).get("engine") or {}
        pc = eng.get("prefix_cache") or {}
        by_class = eng.get("queue_tokens_by_class") or {}
        headroom = eng.get("admission_headroom_tokens")
        return cls(
            queue_depth=int(eng.get("queue_depth", 0)),
            queue_tokens=int(eng.get("queue_tokens", 0)),
            busy_slots=int(eng.get("busy_slots", 0)),
            total_slots=int(eng.get("total_slots", 0)),
            prefix_hits=int(pc.get("hits", 0)),
            prefix_misses=int(pc.get("misses", 0)),
            queue_by_class={str(k): int(v) for k, v in by_class.items()},
            brownout=int(eng.get("brownout", 0)),
            kv_tier=dict(eng.get("kv_tier") or {}),
            headroom_tokens=(float(headroom) if headroom is not None
                             else None),
            shed_by_class={str(k): int(v) for k, v in
                           (eng.get("shed_by_class") or {}).items()},
            ttft_ema_by_class={str(k): float(v) for k, v in
                               (eng.get("ttft_ema_by_class") or {}).items()},
            preemptions_by_class={
                str(k): int(v) for k, v in
                (eng.get("preemptions_by_class") or {}).items()},
            role=str(eng.get("role") or "unified"),
            draining=bool(eng.get("draining", False)),
        )


@dataclasses.dataclass
class _Entry:
    replica: object
    breaker: CircuitBreaker
    ready: bool = False
    reason: str = "never probed"
    stats: ReplicaStats = dataclasses.field(default_factory=ReplicaStats)
    inflight: int = 0
    last_probe_s: float = 0.0
    dispatches: int = 0
    failures: int = 0


@dataclasses.dataclass
class Candidate:
    """A dispatchable replica as the routing policies see it."""

    replica_id: str
    replica: object
    stats: ReplicaStats
    inflight: int


@guarded_by("_lock", "_entries")
class ReplicaRegistry:
    """Thread-safe replica table.  Dispatch paths read ``candidates()``;
    the probe loop and the router's outcome callbacks write."""

    def __init__(self, breaker_failures: int = 3,
                 breaker_cooldown_s: float = 5.0):
        self._breaker_failures = breaker_failures
        self._breaker_cooldown_s = breaker_cooldown_s
        self._probe_thread: Optional[threading.Thread] = None
        self._probe_stop = threading.Event()
        # The cadence start_probes() runs at — the staleness yardstick
        # the telemetry plane compares probe ages against.
        self.probe_interval_s: float = 5.0
        self._entries: dict[str, _Entry] = {}
        # Lifecycle subscribers: fired outside the lock.  on_drain fires
        # once per rising edge of a replica's draining flag (the router's
        # prefix-handout sweep); on_remove fires when a replica leaves the
        # table (router/scraper state GC).
        self._on_drain: list = []
        self._on_remove: list = []
        # Created last (lockcheck: writes before the lock exists are
        # construction, not races).
        self._lock = make_lock("fleet.registry")

    # -- membership -----------------------------------------------------

    def add(self, replica) -> None:
        entry = _Entry(
            replica=replica,
            breaker=CircuitBreaker(
                failure_threshold=self._breaker_failures,
                cooldown_s=self._breaker_cooldown_s),
        )
        with self._lock:
            self._entries[replica.replica_id] = entry

    def remove(self, replica_id: str) -> None:
        """Drop a replica from the table.  Its breaker and inflight
        counters die with the entry — nothing keeps probing (or alarming
        on) a replica that left the fleet — and on_remove subscribers get
        one shot at GC'ing their own per-replica state."""
        with self._lock:
            removed = self._entries.pop(replica_id, None) is not None
        if removed:
            for cb in list(self._on_remove):
                try:
                    cb(replica_id)
                except Exception:  # noqa: BLE001 — GC hooks must not raise
                    logger.exception("on_remove hook failed for %s",
                                     replica_id)

    def subscribe_drain(self, callback) -> None:
        """``callback(replica_id)`` on the rising edge of a replica's
        draining announcement (probe-observed).  Called outside the lock."""
        self._on_drain.append(callback)

    def subscribe_remove(self, callback) -> None:
        """``callback(replica_id)`` after a replica is removed."""
        self._on_remove.append(callback)

    def ids(self) -> list[str]:
        with self._lock:
            return list(self._entries)

    def get(self, replica_id: str) -> Optional[_Entry]:
        with self._lock:
            return self._entries.get(replica_id)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    # -- probing --------------------------------------------------------

    def refresh(self, replica_id: str | None = None) -> None:
        """Probe one replica (or all): readiness + stats.  A probe that
        raises marks the replica unready and feeds the breaker; it never
        propagates — an unreachable replica is a routing fact, not a
        registry error."""
        with self._lock:
            items = [(rid, e.replica) for rid, e in self._entries.items()
                     if replica_id is None or rid == replica_id]
        for rid, replica in items:
            ready, reason, stats = False, "", None
            try:
                ready = bool(replica.readyz())
                if not ready:
                    reason = "replica reports not ready"
                stats = replica.stats()
            except Exception as exc:  # noqa: BLE001 — probe must not raise
                ready, reason = False, f"probe failed: {exc}"
            drain_edge = False
            with self._lock:
                entry = self._entries.get(rid)
                if entry is None:
                    continue
                was_ready = entry.ready
                was_draining = entry.stats.draining
                entry.ready = ready
                entry.reason = reason
                entry.last_probe_s = time.monotonic()
                if stats is not None:
                    entry.stats = stats
                    drain_edge = stats.draining and not was_draining
                if ready:
                    entry.breaker.record_success()
                else:
                    entry.breaker.record_failure()
            if ready != was_ready:
                logger.info("replica %s -> %s%s", rid,
                            "ready" if ready else "unready",
                            f" ({reason})" if reason else "")
            if drain_edge:
                logger.info("replica %s announced draining", rid)
                for cb in list(self._on_drain):
                    try:
                        cb(rid)
                    except Exception:  # noqa: BLE001 — best-effort sweep
                        logger.exception("on_drain hook failed for %s", rid)

    def start_probes(self, interval_s: float = 5.0) -> None:
        if self._probe_thread is not None:
            return
        self.probe_interval_s = float(interval_s)
        self._probe_stop.clear()

        def _loop() -> None:
            while not self._probe_stop.wait(timeout=interval_s):
                self.refresh()

        self._probe_thread = threading.Thread(
            target=_loop, name="fleet-probes", daemon=True)
        self._probe_thread.start()

    def stop_probes(self) -> None:
        self._probe_stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=5.0)
            self._probe_thread = None

    # -- dispatch bookkeeping -------------------------------------------

    def candidates(self) -> list[Candidate]:
        """Ready replicas whose breaker is not refusing calls, with the
        stats the policies rank on.  Breakers are consulted read-only here;
        the half-open trial slot is claimed at dispatch time via
        ``before_call`` so concurrent dispatches can't all pile onto one
        recovering replica."""
        out = []
        with self._lock:
            for rid, e in self._entries.items():
                if e.ready and not e.stats.draining \
                        and e.breaker.state != "open":
                    out.append(Candidate(rid, e.replica, e.stats, e.inflight))
        return out

    def note_dispatch(self, replica_id: str) -> None:
        with self._lock:
            entry = self._entries.get(replica_id)
            if entry is not None:
                entry.inflight += 1
                entry.dispatches += 1

    def note_done(self, replica_id: str, ok: bool) -> None:
        with self._lock:
            entry = self._entries.get(replica_id)
            if entry is None:
                return
            entry.inflight = max(0, entry.inflight - 1)
            if ok:
                entry.breaker.record_success()
            else:
                entry.failures += 1
                entry.breaker.record_failure()

    def mark_unready(self, replica_id: str, reason: str) -> None:
        """Failover fast-path: the router observed this replica die; don't
        wait for the next probe to stop routing there."""
        with self._lock:
            entry = self._entries.get(replica_id)
            if entry is not None:
                entry.ready = False
                entry.reason = reason

    # -- observability ---------------------------------------------------

    def snapshot(self) -> dict:
        """Per-replica view for ``/api/v1/stats``, the exporter, and the
        signal scraper.  ``probe_age_s`` is seconds since the last
        completed probe — None until the first probe finishes (the
        telemetry plane treats None as maximally stale)."""
        now = time.monotonic()
        with self._lock:
            return {
                rid: {
                    "ready": e.ready,
                    "reason": e.reason,
                    "role": e.stats.role,
                    "draining": e.stats.draining,
                    "inflight": e.inflight,
                    "dispatches": e.dispatches,
                    "failures": e.failures,
                    "breaker_state": e.breaker.state,
                    "queue_depth": e.stats.queue_depth,
                    "queue_tokens": e.stats.queue_tokens,
                    "queue_by_class": dict(e.stats.queue_by_class),
                    "brownout": e.stats.brownout,
                    "busy_slots": e.stats.busy_slots,
                    "total_slots": e.stats.total_slots,
                    "prefix_hit_rate": round(e.stats.prefix_hit_rate, 4),
                    "kv_tier": dict(e.stats.kv_tier),
                    "headroom_tokens": e.stats.headroom_tokens,
                    "shed_by_class": dict(e.stats.shed_by_class),
                    "ttft_ema_by_class": dict(e.stats.ttft_ema_by_class),
                    "preemptions_by_class":
                        dict(e.stats.preemptions_by_class),
                    "probe_age_s": (round(now - e.last_probe_s, 3)
                                    if e.last_probe_s > 0 else None),
                }
                for rid, e in self._entries.items()
            }
