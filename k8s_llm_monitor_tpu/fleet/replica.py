"""Replica adapters: one interface, two transports.

``LocalReplica`` wraps an in-process ``EngineService`` (or a supervised
``EngineSupervisor``) so tests and the bench can run a 2–4 replica fleet in
one CPU process — it speaks the token-level generation interface the
router's failover/hedging machinery needs (``generate`` → ``RequestHandle``).

``HTTPReplica`` fronts a remote monitor-server replica over its existing
HTTP API: ``/readyz`` + ``/api/v1/stats`` for probing (GETs, retried
through the shared ``Backoff`` budget), ``/api/v1/query`` SSE and
``/api/v1/analyze`` for traffic (POSTs, never retried — the router's
failover owns re-dispatch).  All calls carry explicit socket timeouts via
``monitor.client.ApiClient``.

Capability split: LocalReplica is token-level (``supports_tokens``),
HTTPReplica is text-level (``supports_query`` — the wire protocol streams
answer-text deltas, not token ids).  The router routes each request shape
over the replicas that support it.
"""

from __future__ import annotations

import logging

from k8s_llm_monitor_tpu.fleet.registry import ReplicaStats
from k8s_llm_monitor_tpu.resilience.tenancy import DEFAULT_TENANT

logger = logging.getLogger("fleet.replica")


class ReplicaUnavailable(RuntimeError):
    """The replica could not take this request (connection refused, died,
    adapter closed).  Routing-level signal: try another replica."""


class Replica:
    """Adapter interface the registry probes and the router dispatches on."""

    replica_id: str = ""
    supports_tokens = False
    supports_query = False
    supports_kv_migration = False

    # -- probing --------------------------------------------------------

    def readyz(self) -> bool:
        raise NotImplementedError

    def stats(self) -> ReplicaStats:
        raise NotImplementedError

    # -- token-level generation (in-process replicas) -------------------

    def generate(self, prompt_ids: list[int], sampling=None,
                 request_id: str | None = None, deadline_s: float = 0.0,
                 slo_class: str = "standard",
                 tenant: str = DEFAULT_TENANT):
        """Submit one generation; returns a ``RequestHandle``.  The quota
        charge for ``tenant`` already happened at the router — the replica
        only uses it for KV namespacing and journal accounting."""
        raise NotImplementedError(f"{self.replica_id}: token interface")

    # -- text-level query API (HTTP replicas) ---------------------------

    def query(self, question: str, slo_class: str = "interactive",
              tenant: str = DEFAULT_TENANT) -> dict:
        raise NotImplementedError(f"{self.replica_id}: query interface")

    def query_stream(self, question: str, slo_class: str = "interactive",
                     tenant: str = DEFAULT_TENANT):
        """Returns (request_id, model, iterator of text deltas)."""
        raise NotImplementedError(f"{self.replica_id}: query interface")

    def analyze(self, payload: dict,
                tenant: str = DEFAULT_TENANT) -> dict:
        raise NotImplementedError(f"{self.replica_id}: query interface")

    def diagnoses(self, limit: int = 0) -> dict:
        """Verdict history from the replica's standing diagnosis pipeline."""
        raise NotImplementedError(f"{self.replica_id}: query interface")

    # -- KV prefix migration (serving/kv_tier.py blob framing) ----------

    def fetch_prefix(self, token_ids: list[int],
                     tenant: str = DEFAULT_TENANT):
        """Framed KV pages for the longest cached prefix of ``token_ids``
        under ``tenant``'s namespace (``bytes``), or None on a cache miss.
        The router's migration path calls this on the prefix-affinity
        *owner* when dispatch landed elsewhere."""
        raise NotImplementedError(f"{self.replica_id}: kv migration")

    def install_prefix(self, blob: bytes,
                       tenant: str | None = None) -> str:
        """Install a fetched prefix blob into this replica's KV pool.
        With ``tenant`` set, a blob whose header names a different tenant
        is refused (``tenant_mismatch``).  Returns the engine's outcome
        string: ``installed`` / ``cached`` / ``incompatible`` /
        ``nospace`` / ``tenant_mismatch``."""
        raise NotImplementedError(f"{self.replica_id}: kv migration")

    # -- tracing ---------------------------------------------------------

    def fetch_trace(self, trace_id: str) -> list[dict]:
        """Span dicts this replica recorded for ``trace_id`` (may be
        empty).  The router's ``/api/v1/trace/<id>`` merge calls this on
        every replica to stitch one cross-process timeline."""
        return []

    def close(self) -> None:
        pass


class LocalReplica(Replica):
    """In-process replica: an ``EngineService`` (optionally owned by an
    ``EngineSupervisor``) behind the replica interface.

    ``kill()`` is the chaos hook: it stops the service abruptly so every
    in-flight handle resolves with an error result — exactly what the
    router's mid-stream failover must survive.
    """

    supports_tokens = True
    supports_kv_migration = True

    def __init__(self, replica_id: str, service=None, supervisor=None,
                 role: str = "unified"):
        assert (service is None) != (supervisor is None), \
            "exactly one of service/supervisor"
        self.replica_id = replica_id
        self.supervisor = supervisor
        self._service = service
        self._killed = False
        self.role = role
        self._draining = False

    @property
    def service(self):
        if self.supervisor is not None:
            return self.supervisor.service
        return self._service

    def readyz(self) -> bool:
        if self._killed:
            return False
        svc = self.service
        if svc is None:
            return False
        snap = svc.health.snapshot()
        ready = bool(snap["ready"])
        if self.supervisor is not None:
            ready = ready and self.supervisor.snapshot()["state"] == "serving"
        return ready

    def stats(self) -> ReplicaStats:
        svc = self.service
        if svc is None:
            raise ReplicaUnavailable(f"{self.replica_id}: no service")
        engine = svc.engine
        pc = engine.prefix_cache
        return ReplicaStats(
            queue_depth=engine.queue_depth,
            queue_tokens=engine.queue_tokens,
            busy_slots=engine.active_slots,
            total_slots=engine.ecfg.max_slots,
            prefix_hits=pc.hits if pc is not None else 0,
            prefix_misses=pc.misses if pc is not None else 0,
            queue_by_class=engine.queue_tokens_by_class(),
            brownout=engine.brownout() if engine.brownout is not None else 0,
            kv_tier=engine.kv_tier_stats(),
            headroom_tokens=float(engine.admission_headroom_tokens()),
            shed_by_class=dict(svc.shed_count_by_class),
            ttft_ema_by_class=dict(engine.ttft_ema_by_class),
            preemptions_by_class=dict(engine.preemptions_by_class),
            role=self.role,
            draining=self._draining,
        )

    def drain(self) -> None:
        """Announce draining: the next stats probe carries the flag, the
        router stops dispatching here, and in-flight streams finish (or
        fail over via the normal replay path).  ``close()`` remains the
        actual teardown — drain is an announcement, not a stop."""
        self._draining = True

    @property
    def draining(self) -> bool:
        return self._draining

    def generate(self, prompt_ids: list[int], sampling=None,
                 request_id: str | None = None, deadline_s: float = 0.0,
                 slo_class: str = "standard",
                 tenant: str = DEFAULT_TENANT):
        if self._killed:
            raise ReplicaUnavailable(f"{self.replica_id}: killed")
        try:
            if self.supervisor is not None:
                return self.supervisor.submit(
                    prompt_ids, sampling, request_id=request_id,
                    deadline_s=deadline_s, slo_class=slo_class,
                    tenant=tenant)
            return self.service.submit(
                prompt_ids, sampling, request_id=request_id,
                deadline_s=deadline_s, slo_class=slo_class, tenant=tenant)
        except RuntimeError as exc:
            # Dead service: a routing fact, not a caller error.
            raise ReplicaUnavailable(str(exc)) from exc

    def _call(self, fn):
        """Engine control call on the step thread (service/supervisor
        ``call`` seam); death/lifecycle refusals become routing facts."""
        if self._killed:
            raise ReplicaUnavailable(f"{self.replica_id}: killed")
        try:
            if self.supervisor is not None:
                return self.supervisor.call(fn)
            svc = self.service
            if svc is None:
                raise ReplicaUnavailable(f"{self.replica_id}: no service")
            return svc.call(fn)
        except (RuntimeError, TimeoutError) as exc:
            raise ReplicaUnavailable(str(exc)) from exc

    def fetch_prefix(self, token_ids: list[int],
                     tenant: str = DEFAULT_TENANT):
        ids = list(token_ids)
        return self._call(lambda e: e.export_prefix(ids, tenant=tenant))

    def install_prefix(self, blob: bytes,
                       tenant: str | None = None) -> str:
        return self._call(
            lambda e: e.install_prefix(blob, expected_tenant=tenant))

    def fetch_trace(self, trace_id: str) -> list[dict]:
        # In-process replicas share the process tracer: the router's
        # local spans_for() already saw these, and the merge dedups by
        # span id — returning them again is harmless but pointless.
        from k8s_llm_monitor_tpu.observability.tracing import get_tracer

        return get_tracer().spans_for(trace_id)

    def kill(self, reason: str = "injected replica death") -> None:
        """Chaos hook: die abruptly.  Handles for in-flight generations
        resolve with error results (the router's failover trigger)."""
        self._killed = True
        logger.warning("replica %s killed: %s", self.replica_id, reason)
        svc = self.service
        if svc is not None:
            svc.stop(timeout=10.0)

    def close(self) -> None:
        self._killed = True
        if self.supervisor is not None:
            self.supervisor.shutdown(grace_s=0.0)
        elif self._service is not None:
            self._service.stop(timeout=5.0)


class HTTPReplica(Replica):
    """Remote monitor-server replica over its HTTP API (SSE streaming for
    queries; explicit timeouts on every socket via ``ApiClient``)."""

    supports_query = True
    supports_kv_migration = True

    def __init__(self, replica_id: str, base_url: str, *,
                 connect_timeout_s: float = 2.0, read_timeout_s: float = 30.0,
                 client=None):
        from k8s_llm_monitor_tpu.monitor.client import ApiClient

        self.replica_id = replica_id
        self.base_url = base_url.rstrip("/")
        self.client = client or ApiClient(
            self.base_url,
            connect_timeout_s=connect_timeout_s,
            read_timeout_s=read_timeout_s)

    def readyz(self) -> bool:
        return self.client.readyz()

    def stats(self) -> ReplicaStats:
        return ReplicaStats.from_payload(self.client.stats())

    def query(self, question: str, slo_class: str = "interactive",
              tenant: str = DEFAULT_TENANT) -> dict:
        from k8s_llm_monitor_tpu.monitor.client import ApiConnectionError

        try:
            return self.client.query(question, slo_class=slo_class,
                                     tenant=tenant)
        except ApiConnectionError as exc:
            raise ReplicaUnavailable(str(exc)) from exc

    def query_stream(self, question: str, slo_class: str = "interactive",
                     tenant: str = DEFAULT_TENANT):
        from k8s_llm_monitor_tpu.monitor.client import ApiConnectionError

        try:
            return self.client.query_stream(question, slo_class=slo_class,
                                            tenant=tenant)
        except ApiConnectionError as exc:
            raise ReplicaUnavailable(str(exc)) from exc

    def analyze(self, payload: dict,
                tenant: str = DEFAULT_TENANT) -> dict:
        from k8s_llm_monitor_tpu.monitor.client import ApiConnectionError

        try:
            return self.client.analyze(payload, tenant=tenant)
        except ApiConnectionError as exc:
            raise ReplicaUnavailable(str(exc)) from exc

    def diagnoses(self, limit: int = 0) -> dict:
        from k8s_llm_monitor_tpu.monitor.client import ApiConnectionError

        try:
            return self.client.diagnoses(limit)
        except ApiConnectionError as exc:
            raise ReplicaUnavailable(str(exc)) from exc

    def fetch_prefix(self, token_ids: list[int],
                     tenant: str = DEFAULT_TENANT):
        from k8s_llm_monitor_tpu.monitor.client import ApiConnectionError

        try:
            return self.client.kv_prefix(token_ids, tenant=tenant)
        except ApiConnectionError as exc:
            raise ReplicaUnavailable(str(exc)) from exc

    def install_prefix(self, blob: bytes,
                       tenant: str | None = None) -> str:
        from k8s_llm_monitor_tpu.monitor.client import ApiConnectionError

        try:
            return self.client.kv_install(blob, tenant=tenant)
        except ApiConnectionError as exc:
            raise ReplicaUnavailable(str(exc)) from exc

    def fetch_trace(self, trace_id: str) -> list[dict]:
        from k8s_llm_monitor_tpu.monitor.client import ApiConnectionError

        try:
            payload = self.client.trace(trace_id)
        except ApiConnectionError:
            return []  # unknown trace / replica down: nothing to merge
        spans = payload.get("spans") if isinstance(payload, dict) else None
        return spans if isinstance(spans, list) else []

    def close(self) -> None:
        self.client.close()
