"""Fleet router: policy-ranked dispatch with hedging and mid-stream failover.

Routing policies (pluggable, ranked candidate lists — dispatch walks the
ranking so a refused/overloaded candidate falls through to the next):

* ``round_robin`` — baseline rotation; the control arm for the affinity
  hit-rate comparison.
* ``least_loaded`` — weighted load score from each replica's stats
  snapshot: queue-token backlog + busy-slot pressure + router-side inflight.
* ``affinity`` — rendezvous (highest-random-weight) hashing on a
  prompt-prefix digest, so same-prefix requests land on the replica whose
  ``PrefixCache`` already holds their pages.  Saturated preferred replicas
  spill to the least-loaded ranking (hot cache is worth nothing if the
  request queues behind a full batch).

Hedged dispatch (token-level path): when the primary has produced no token
after the EMA-p95 TTFT delay, a second replica gets the same request; the
first to produce a token wins and the loser is cancelled.  p95 is estimated
online as ``m + k·d`` where ``m`` is a TTFT EMA and ``d`` an EMA of absolute
deviation (for a normal tail, sigma ≈ 1.4826·MAD and p95 ≈ m + 1.645·sigma
≈ m + 2.45·d; ``k`` defaults to 3.0 for safety against hedging storms).

Mid-stream failover (token-level path): a replica that dies mid-generation
resolves its handle with an error result; the pump resubmits to the next
healthy replica with the already-streamed tokens folded into the prompt and
``max_tokens`` trimmed by the emitted count — the same idempotent-replay
contract as ``serving/supervisor.py`` — so the caller's stream continues
with zero duplicated and zero lost tokens.

The text-level path (``query``/``query_stream``/``analyze`` over
``HTTPReplica``) gets the same policy ranking and failover; a resumed SSE
stream suppresses the already-delivered character prefix.  Hedging is
token-level only (an SSE generator has no timed ``next``).

Disaggregated roles (docs/fleet.md "Disaggregated roles & autoscaling"):
when the fleet advertises both ``prefill``- and ``decode``-role replicas,
a new request prefills (plus first token) on a prefill replica, then the
finished prefix is streamed to a decode replica over the ``KVX1``
export/install migration path and the remaining budget continues there.
Every handoff failure mode — ``nospace``, ``incompatible``, owner death
mid-transfer, install timeout, a torn blob — degrades to unified-style
local decode on the prefill replica (whose KV already holds the prompt,
so the continuation is a prefix hit, not a re-prefill); a dead prefill
replica falls through to the normal failover replay.  A request is never
dropped by the handoff ladder.
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
import logging
import threading
import time
from typing import Optional

from k8s_llm_monitor_tpu.devtools.lockcheck import guarded_by, make_lock
from k8s_llm_monitor_tpu.fleet.registry import Candidate, ReplicaRegistry
from k8s_llm_monitor_tpu.fleet.replica import ReplicaUnavailable
from k8s_llm_monitor_tpu.observability.tracing import Tracer, get_tracer
from k8s_llm_monitor_tpu.resilience.errors import OverloadedError
from k8s_llm_monitor_tpu.resilience.retry import CircuitOpen
from k8s_llm_monitor_tpu.resilience.tenancy import (
    DEFAULT_TENANT,
    TenantGovernor,
    normalize_tenant,
)
from k8s_llm_monitor_tpu.serving.engine import GenerationResult, SamplingParams
from k8s_llm_monitor_tpu.serving.kv_tier import BlobError
from k8s_llm_monitor_tpu.serving.service import RequestHandle

logger = logging.getLogger("fleet.router")


# ---------------------------------------------------------------------------
# Routing policies
# ---------------------------------------------------------------------------


def _load_score(c: Candidate) -> float:
    """Weighted least-loaded signal: queue-token backlog dominates, busy
    slots and router-side inflight break ties (a replica with a full batch
    but an empty queue still beats one with a backlog)."""
    slot_pressure = (c.stats.busy_slots / c.stats.total_slots
                     if c.stats.total_slots else 0.0)
    return c.stats.queue_tokens + 64.0 * slot_pressure + 16.0 * c.inflight


def _slot_utilization(c: Candidate) -> float:
    return (c.stats.busy_slots / c.stats.total_slots
            if c.stats.total_slots else 0.0)


class RoutingPolicy:
    name = "base"

    def rank(self, candidates: list[Candidate],
             digest: bytes) -> list[Candidate]:
        raise NotImplementedError

    def preferred(self, candidates: list[Candidate],
                  digest: bytes) -> Optional[str]:
        """The replica this policy would ideally use (affinity accounting);
        None when the policy has no cache-topology preference."""
        return None


class RoundRobinPolicy(RoutingPolicy):
    name = "round_robin"

    def __init__(self) -> None:
        self._turn = itertools.count()

    def rank(self, candidates: list[Candidate],
             digest: bytes) -> list[Candidate]:
        if not candidates:
            return []
        ordered = sorted(candidates, key=lambda c: c.replica_id)
        k = next(self._turn) % len(ordered)
        return ordered[k:] + ordered[:k]


class LeastLoadedPolicy(RoutingPolicy):
    name = "least_loaded"

    def rank(self, candidates: list[Candidate],
             digest: bytes) -> list[Candidate]:
        return sorted(candidates,
                      key=lambda c: (_load_score(c), c.replica_id))


class PrefixAffinityPolicy(RoutingPolicy):
    """Rendezvous hashing on the prompt-prefix digest.

    Every (digest, replica) pair gets a deterministic weight; the highest
    weight wins.  Replica loss only remaps the keys that pointed at the
    lost replica (the consistent-hashing property), so a failover doesn't
    shuffle the whole fleet's cache topology.  A saturated winner spills to
    the least-loaded order, counted by the router as an affinity spill.
    """

    name = "affinity"

    @staticmethod
    def _weight(digest: bytes, replica_id: str) -> bytes:
        return hashlib.sha256(digest + replica_id.encode()).digest()

    @staticmethod
    def _saturated(c: Candidate) -> bool:
        return (c.stats.total_slots > 0
                and c.stats.busy_slots >= c.stats.total_slots
                and c.stats.queue_tokens > 0)

    def rank(self, candidates: list[Candidate],
             digest: bytes) -> list[Candidate]:
        ranked = sorted(candidates,
                        key=lambda c: self._weight(digest, c.replica_id),
                        reverse=True)
        if len(ranked) > 1 and self._saturated(ranked[0]):
            relief = [c for c in ranked[1:] if not self._saturated(c)]
            if relief:
                spill = sorted(relief,
                               key=lambda c: (_load_score(c), c.replica_id))
                rest = [c for c in ranked if c not in spill]
                ranked = spill + rest
        return ranked

    def preferred(self, candidates: list[Candidate],
                  digest: bytes) -> Optional[str]:
        if not candidates:
            return None
        best = max(candidates,
                   key=lambda c: self._weight(digest, c.replica_id))
        return best.replica_id


POLICIES = {
    "round_robin": RoundRobinPolicy,
    "least_loaded": LeastLoadedPolicy,
    "affinity": PrefixAffinityPolicy,
}


# ---------------------------------------------------------------------------
# Router
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class HedgeConfig:
    enabled: bool = False
    min_delay_s: float = 0.05     # floor: never hedge faster than this
    fixed_delay_s: float = 0.0    # >0 pins the delay (bench/tests)
    p95_mult: float = 3.0         # k in delay = ttft_ema + k * dev_ema
    cold_delay_s: float = 0.5     # before any TTFT sample exists


@dataclasses.dataclass
class _Flight:
    """Pump-thread state for one fleet-level request (mirrors the
    supervisor's ``_Tracked``: everything needed to replay elsewhere)."""

    rid: str
    prompt_ids: list[int]
    sampling: SamplingParams
    deadline_s: float
    digest: bytes
    slo_class: str
    tenant: str
    handle: RequestHandle               # fleet-level, what the caller holds
    inner: Optional[RequestHandle]      # current replica-level handle
    replica_id: str
    emitted: list[int] = dataclasses.field(default_factory=list)
    prior: list[int] = dataclasses.field(default_factory=list)
    attempts: int = 0                   # failovers consumed
    cancelled: bool = False
    dispatch_t0: float = 0.0
    # TraceContext minted at submit time (child of the caller's context
    # when one exists).  The pump/hedge threads re-enter it (Tracer.use)
    # before every replica call so failover replays and hedge legs join
    # the originating trace — the router's half of the one-merged-trace
    # contract.  Its own span ("router.request") is recorded when the
    # flight resolves, so children never point at an unrecorded parent.
    trace: object = None
    submit_t0: float = 0.0
    # Disaggregation: this flight was dispatched to a prefill-role replica
    # with a 1-token budget; on clean completion the pump runs the handoff
    # ladder instead of finishing the stream.
    pending_decode: bool = False


_DONE = object()
_HANDOFF = object()


@guarded_by("_lock", "dispatches", "completed", "failed", "sheds",
            "failovers", "hedges_fired", "hedges_won", "affinity_hits",
            "affinity_spills", "_migrations", "_ttft_m", "_ttft_dev",
            "_handoffs", "_recent_prefixes", "drain_sweeps")
class FleetRouter:
    """Routes requests over a ``ReplicaRegistry`` with the selected policy,
    per-replica circuit breaking, optional hedging, and mid-stream
    failover.  Token-level entry point is ``submit()`` (returns a
    ``RequestHandle``-compatible ticket); text-level entry points are
    ``query``/``query_stream``/``analyze``."""

    def __init__(self, registry: ReplicaRegistry, policy: str = "affinity",
                 hedge: HedgeConfig | None = None, max_failovers: int = 2,
                 affinity_prefix_tokens: int = 64,
                 stall_timeout_s: float = 120.0,
                 batch_spill_threshold: float = 0.75,
                 migrate_prefixes: bool = True,
                 drain_sweep_budget: int = 8,
                 governor: TenantGovernor | None = None):
        if policy not in POLICIES:
            raise ValueError(
                f"unknown policy {policy!r} (have {sorted(POLICIES)})")
        self.registry = registry
        self.policy = POLICIES[policy]()
        self.hedge = hedge or HedgeConfig()
        # Router-owned tenant governor: quota is charged ONCE per logical
        # request here — replica-level dispatches (hedge legs, failover
        # replays, decode handoffs) are fan-out of the same reservation
        # and must never re-charge it, so replicas behind a router run
        # without a governor of their own.
        self.governor = governor
        self.max_failovers = max_failovers
        self.affinity_prefix_tokens = affinity_prefix_tokens
        self.stall_timeout_s = stall_timeout_s
        # SLO-class routing (resilience/slo.py): batch only spills off its
        # affinity target onto replicas below this slot utilization.
        self.batch_spill_threshold = batch_spill_threshold
        self._ids = itertools.count()
        # counters (exporter gauges)
        self.dispatches = 0
        self.completed = 0
        self.failed = 0
        self.sheds = 0
        self.failovers = 0
        self.hedges_fired = 0
        self.hedges_won = 0
        self.affinity_hits = 0
        self.affinity_spills = 0
        # Prefix migration: on an affinity miss, fetch the shared KV
        # pages from the policy-preferred owner and install them on the
        # actual target before dispatch (serving/kv_tier.py framing) so
        # the spilled request still skips its re-prefill.  Outcome
        # counters feed prefix_migrations_total{outcome}.
        self.migrate_prefixes = migrate_prefixes
        self._migrations: dict[str, int] = {}
        # Prefill->decode handoff outcomes (fleet_handoffs_total{outcome}):
        # "decode" = continuation landed on a decode replica with the
        # installed prefix; "local" = degraded to local decode on the
        # prefill replica; failure-cause keys (nospace / incompatible /
        # owner_down / miss / torn / install_timeout / error / no_decode /
        # dispatch_failed) count WHY a handoff degraded.
        self._handoffs: dict[str, int] = {}
        # Recently-dispatched prefix heads: digest -> (head tokens, last
        # replica, tenant).  The drain sweep reads this to proactively
        # offer a draining replica's cached prefixes to their new
        # rendezvous owners; bounded LRU so it never grows with traffic.
        self._recent_prefixes: dict[bytes, tuple[list[int], str, str]] = {}
        self._recent_prefixes_cap = 128
        self.drain_sweep_budget = drain_sweep_budget
        self.drain_sweeps = 0
        # online TTFT stats for the hedge delay
        self._ttft_m: float | None = None
        self._ttft_dev: float = 0.0
        self._ttft_alpha = 0.2
        # Created last (lockcheck construction rule).
        self._lock = make_lock("fleet.router")
        # Membership lifecycle hooks: offer a draining replica's prefixes
        # to their replacements; GC affinity memory for removed replicas.
        registry.subscribe_drain(self._drain_sweep)
        registry.subscribe_remove(self.forget_replica)

    # -- shared plumbing -------------------------------------------------

    def _bump(self, name: str, by: int = 1) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + by)

    def counters(self) -> dict:
        with self._lock:
            return {
                "dispatches": self.dispatches,
                "completed": self.completed,
                "failed": self.failed,
                "sheds": self.sheds,
                "failovers": self.failovers,
                "hedges_fired": self.hedges_fired,
                "hedges_won": self.hedges_won,
                "affinity_hits": self.affinity_hits,
                "affinity_spills": self.affinity_spills,
                "prefix_migrations": dict(self._migrations),
                "handoffs": dict(self._handoffs),
                "drain_sweeps": self.drain_sweeps,
            }

    def telemetry_sample(self) -> dict:
        """The signal scraper's fleet input: every replica's last probe
        row plus the probe cadence the staleness rule is judged against.
        One registry lock pass, no HTTP — the probe loop already paid
        for the data."""
        return {
            "replicas": self.registry.snapshot(),
            "probe_interval_s": self.registry.probe_interval_s,
            "counters": self.counters(),
        }

    def replicas(self) -> list[tuple[str, object]]:
        """(replica_id, Replica) pairs — the cross-replica trace merge in
        ``GET /api/v1/trace/<id>`` walks every registered replica, ready
        or not (a replica that died mid-request still holds its spans)."""
        out = []
        for rid in self.registry.ids():
            entry = self.registry.get(rid)
            if entry is not None:
                out.append((rid, entry.replica))
        return out

    def _token_digest(self, prompt_ids: list[int],
                      tenant: str = DEFAULT_TENANT) -> bytes:
        # Tenant folded in so affinity routing mirrors the tenant-seeded
        # prefix-cache key space: two tenants sharing a prompt have
        # *different* cached prefixes, so they are different affinity keys.
        head = prompt_ids[: self.affinity_prefix_tokens]
        return hashlib.sha256(
            tenant.encode() + b"\x00"
            + b",".join(str(t).encode() for t in head)).digest()

    @staticmethod
    def _text_digest(question: str) -> bytes:
        return hashlib.sha256(question[:256].encode()).digest()

    def _note_ttft(self, dt: float) -> None:
        a = self._ttft_alpha
        with self._lock:
            if self._ttft_m is None:
                self._ttft_m = dt
                self._ttft_dev = dt / 2.0
            else:
                self._ttft_m += a * (dt - self._ttft_m)
                self._ttft_dev += a * (abs(dt - self._ttft_m)
                                       - self._ttft_dev)

    def hedge_delay_s(self) -> float:
        """Current hedge trigger: EMA-p95 of TTFT (see module docstring),
        or the configured fixed delay."""
        if self.hedge.fixed_delay_s > 0:
            return self.hedge.fixed_delay_s
        with self._lock:
            m, dev = self._ttft_m, self._ttft_dev
        if m is None:
            return max(self.hedge.min_delay_s, self.hedge.cold_delay_s)
        return max(self.hedge.min_delay_s, m + self.hedge.p95_mult * dev)

    def _ranked(self, digest: bytes, need_tokens: bool,
                slo_class: str = "standard") -> list[Candidate]:
        cands = [c for c in self.registry.candidates()
                 if (c.replica.supports_tokens if need_tokens
                     else c.replica.supports_query)]
        # Interactive traffic beats cache locality: always least-loaded,
        # whatever the configured policy, so an operator query never queues
        # behind the affinity target's backlog.
        if slo_class == "interactive":
            return sorted(cands,
                          key=lambda c: (_load_score(c), c.replica_id))
        ranked = self.policy.rank(cands, digest)
        # Batch keeps its affinity head (prefix pages are most valuable for
        # the long contexts batch carries) but only spills onto replicas
        # with headroom — saturating a second replica with background work
        # would steal slots from the classes above it.
        if slo_class == "batch" and len(ranked) > 1:
            ranked = [ranked[0]] + [
                c for c in ranked[1:]
                if _slot_utilization(c) < self.batch_spill_threshold]
        return ranked

    def _account_affinity(self, digest: bytes, chosen: str,
                          candidates: list[Candidate]) -> None:
        pref = self.policy.preferred(candidates, digest)
        if pref is None:
            return
        self._bump("affinity_hits" if chosen == pref else "affinity_spills")

    # -- prefix migration (affinity miss -> move the pages, not the work) -

    def _bump_migration(self, outcome: str) -> None:
        with self._lock:
            self._migrations[outcome] = self._migrations.get(outcome, 0) + 1

    def _bump_handoff(self, outcome: str) -> None:
        with self._lock:
            self._handoffs[outcome] = self._handoffs.get(outcome, 0) + 1

    def _maybe_migrate_prefix(self, digest: bytes, prompt_ids: list[int],
                              ranked: list[Candidate],
                              tenant: str = DEFAULT_TENANT) -> None:
        """When dispatch is about to land off the affinity owner, pull the
        owner's cached KV pages for this prompt and install them on the
        actual target first — the target's prefill then hits its prefix
        cache instead of recomputing the shared span.  Every failure mode
        degrades to plain re-prefill; this path must never lose a request.
        """
        if not self.migrate_prefixes or len(ranked) < 2:
            return
        target = ranked[0]
        pref = self.policy.preferred(ranked, digest)
        if pref is None or pref == target.replica_id:
            return  # hit: the pages are already where the request lands
        owner = next((c for c in ranked if c.replica_id == pref), None)
        if (owner is None or not owner.replica.supports_kv_migration
                or not target.replica.supports_kv_migration):
            return
        tracer = get_tracer()
        t_mig = time.monotonic()

        def _span(outcome: str, status: str = "ok") -> None:
            tracer.record(
                "router.migrate_prefix", t_mig, time.monotonic(),
                tracer.current(), status=status,
                attrs={"owner": pref, "target": target.replica_id,
                       "outcome": outcome})

        try:
            blob = owner.replica.fetch_prefix(prompt_ids, tenant=tenant)
        except ReplicaUnavailable:
            self._bump_migration("owner_down")
            _span("owner_down", status="error")
            return
        except Exception:  # noqa: BLE001 — migration is best-effort
            logger.exception("prefix fetch from %s failed", pref)
            self._bump_migration("error")
            _span("fetch_error", status="error")
            return
        if blob is None:
            self._bump_migration("miss")
            _span("miss")
            return
        try:
            outcome = target.replica.install_prefix(blob, tenant=tenant)
        except Exception:  # noqa: BLE001 — migration is best-effort
            logger.exception("prefix install on %s failed",
                             target.replica_id)
            self._bump_migration("error")
            _span("install_error", status="error")
            return
        self._bump_migration(str(outcome))
        _span(str(outcome))
        if outcome == "installed":
            logger.info("migrated prefix %s... %s -> %s",
                        digest[:4].hex(), pref, target.replica_id)

    # -- membership lifecycle: drain sweep + removal GC ------------------

    def _note_prefix(self, digest: bytes, prompt_ids: list[int],
                     replica_id: str,
                     tenant: str = DEFAULT_TENANT) -> None:
        head = list(prompt_ids[: self.affinity_prefix_tokens])
        with self._lock:
            self._recent_prefixes.pop(digest, None)
            self._recent_prefixes[digest] = (head, replica_id, tenant)
            while len(self._recent_prefixes) > self._recent_prefixes_cap:
                self._recent_prefixes.pop(
                    next(iter(self._recent_prefixes)))

    def forget_replica(self, replica_id: str) -> None:
        """Removal GC: drop the affinity-memory entries that point at a
        replica that left the fleet (wired to ``registry.subscribe_remove``
        — the registry already dropped its breaker/inflight state)."""
        with self._lock:
            for dig in [d for d, (_, owner, _t)
                        in self._recent_prefixes.items()
                        if owner == replica_id]:
                del self._recent_prefixes[dig]

    def _drain_sweep(self, replica_id: str) -> None:
        """Best-effort prefix handout on a replica's draining edge: offer
        up to ``drain_sweep_budget`` of its recently-served prefixes to
        their new rendezvous owners (the draining replica no longer wins
        affinity — ``candidates()`` excludes it — so without the sweep
        every one of its hot prefixes re-prefills cold elsewhere).  Every
        failure mode is swallowed: draining must never block on this."""
        entry = self.registry.get(replica_id)
        if (entry is None
                or not getattr(entry.replica, "supports_kv_migration",
                               False)):
            return
        with self._lock:
            owned = [(dig, head, ten) for dig, (head, owner, ten)
                     in self._recent_prefixes.items()
                     if owner == replica_id]
        cands = [c for c in self.registry.candidates()
                 if c.replica.supports_kv_migration
                 and c.replica_id != replica_id]
        if not cands or not owned:
            return
        moved = 0
        for dig, head, ten in owned:
            if moved >= self.drain_sweep_budget:
                break
            pref = self.policy.preferred(cands, dig)
            target = next((c for c in cands if c.replica_id == pref), None)
            if target is None:
                ranked = self.policy.rank(cands, dig)
                target = ranked[0] if ranked else None
            if target is None:
                break
            try:
                blob = entry.replica.fetch_prefix(head, tenant=ten)
            except ReplicaUnavailable:
                self._bump_migration("owner_down")
                break  # owner died mid-drain: nothing more to offer
            except Exception:  # noqa: BLE001 — sweep is best-effort
                logger.exception("drain sweep fetch from %s failed",
                                 replica_id)
                self._bump_migration("error")
                break
            if blob is None:
                self._bump_migration("miss")
                continue
            try:
                outcome = str(target.replica.install_prefix(blob,
                                                            tenant=ten))
            except Exception:  # noqa: BLE001 — sweep is best-effort
                logger.exception("drain sweep install on %s failed",
                                 target.replica_id)
                self._bump_migration("error")
                continue
            self._bump_migration(outcome)
            if outcome in ("installed", "cached"):
                moved += 1
                with self._lock:
                    self._recent_prefixes[dig] = (head, target.replica_id,
                                                  ten)
        if moved:
            self._bump("drain_sweeps", moved)
            logger.info("drain sweep moved %d prefixes off %s",
                        moved, replica_id)

    # -- token-level dispatch -------------------------------------------

    def _dispatch_tokens(self, ranked: list[Candidate],
                         prompt_ids: list[int], sampling: SamplingParams,
                         request_id: str, deadline_s: float,
                         exclude: frozenset[str] | set[str] = frozenset(),
                         slo_class: str = "standard",
                         tenant: str = DEFAULT_TENANT):
        """Try candidates in rank order; returns (replica_id, handle) or
        (None, last_error).  Breaker gates each attempt."""
        last_exc: Exception | None = None
        for cand in ranked:
            if cand.replica_id in exclude:
                continue
            entry = self.registry.get(cand.replica_id)
            if entry is None:
                continue
            try:
                entry.breaker.before_call()
            except CircuitOpen as exc:
                last_exc = exc
                continue
            try:
                handle = cand.replica.generate(
                    prompt_ids, sampling, request_id=request_id,
                    deadline_s=deadline_s, slo_class=slo_class,
                    tenant=tenant)
            except OverloadedError as exc:
                entry.breaker.record_success()  # alive, just shedding
                last_exc = exc
                continue
            except Exception as exc:  # noqa: BLE001 — routing fact
                entry.breaker.record_failure()
                self.registry.mark_unready(cand.replica_id, str(exc))
                last_exc = exc
                continue
            self.registry.note_dispatch(cand.replica_id)
            self._bump("dispatches")
            return cand.replica_id, handle
        return None, last_exc

    def submit(self, prompt_ids: list[int],
               sampling: SamplingParams | None = None,
               request_id: str | None = None,
               deadline_s: float = 0.0,
               slo_class: str = "standard",
               tenant: str = DEFAULT_TENANT) -> RequestHandle:
        """Admit one generation into the fleet.  Raises ``OverloadedError``
        when no replica will take it (counted as a shed); otherwise returns
        a handle whose stream survives replica death transparently.
        Tenant quota is charged here, once — every downstream replica
        dispatch (hedge, failover, handoff) rides the same reservation."""
        sampling = sampling or SamplingParams()
        tenant = normalize_tenant(tenant)
        rid = request_id or f"fleet-{next(self._ids)}"
        if self.governor is not None:
            # Raises a tenant-tagged OverloadedError (HTTP 429) before any
            # replica sees the request; reserves max_tokens until settle.
            self.governor.admit(
                tenant, rid, max_tokens=sampling.max_tokens,
                prompt_bytes=len(prompt_ids) * 4, slo_class=slo_class)
        tracer = get_tracer()
        # A fresh child of the caller's context (set by the HTTP server
        # from traceparent), or a new root when the router is where this
        # request's trace begins.
        parent = tracer.current()
        trace = Tracer.child(parent) if parent is not None \
            else tracer.new_trace()
        tracer.bind(rid, trace)
        digest = self._token_digest(prompt_ids, tenant)
        t_rank = time.monotonic()
        ranked = self._ranked(digest, need_tokens=True, slo_class=slo_class)
        # Disaggregated dispatch: with both roles present, the request
        # prefills (plus first token) on a prefill replica and the pump
        # hands the finished prefix to a decode replica.  A fleet missing
        # either role — or a 1-token request, where there is nothing to
        # hand off — dispatches unified.
        prefill_ranked = [c for c in ranked if c.stats.role == "prefill"]
        disagg = (bool(prefill_ranked)
                  and any(c.stats.role == "decode" for c in ranked)
                  and sampling.max_tokens > 1)
        chosen, handle = (None, None)
        with tracer.use(trace):
            if disagg:
                chosen, handle = self._dispatch_tokens(
                    prefill_ranked, prompt_ids,
                    dataclasses.replace(sampling, max_tokens=1),
                    f"{rid}-a0", deadline_s, slo_class=slo_class,
                    tenant=tenant)
                if chosen is None:
                    disagg = False  # no prefill taker: degrade to unified
            if not disagg and ranked and chosen is None:
                self._maybe_migrate_prefix(digest, prompt_ids, ranked,
                                           tenant)
                chosen, handle = self._dispatch_tokens(
                    ranked, prompt_ids, sampling, f"{rid}-a0", deadline_s,
                    slo_class=slo_class, tenant=tenant)
        if chosen is None:
            self._bump("sheds")
            if self.governor is not None:
                # Nothing was generated: release the token reservation.
                # The request-rate charge stands — a shed storm still
                # counts against the tenant's rate.
                self.governor.settle(rid)
                self.governor.note_shed(tenant)
            self._end_flight_span_at(trace, rid, t_rank, "error",
                                     outcome="shed")
            err = handle  # last error from dispatch, or None when empty
            if isinstance(err, OverloadedError):
                raise err
            raise OverloadedError(
                f"no replica available ({err or 'fleet empty'})",
                retriable=True, retry_after_s=1.0, slo_class=slo_class,
                request_id=rid, tenant=tenant)
        self._account_affinity(digest, chosen, ranked)
        self._note_prefix(digest, prompt_ids, chosen, tenant)
        tracer.record("router.dispatch", t_rank, time.monotonic(), trace,
                      attrs={"request_id": rid, "replica": chosen,
                             "attempt": 0, "class": slo_class,
                             "disaggregated": disagg})

        flight = _Flight(
            rid=rid, prompt_ids=list(prompt_ids), sampling=sampling,
            deadline_s=deadline_s, digest=digest, slo_class=slo_class,
            tenant=tenant,
            handle=RequestHandle(rid, eos_id=None), inner=handle,
            replica_id=chosen, dispatch_t0=time.monotonic(), trace=trace,
            submit_t0=t_rank, pending_decode=disagg)
        flight.handle._cancel_fn = lambda _rid: self._cancel_flight(flight)
        threading.Thread(target=self._pump, args=(flight,),
                         name=f"fleet-pump-{rid}", daemon=True).start()
        return flight.handle

    @staticmethod
    def _end_flight_span_at(trace, rid: str, t0: float, status: str,
                            **attrs) -> None:
        """Record the flight's own span (the context's span id itself, so
        every child span recorded under it has a real parent)."""
        if trace is None:
            return
        attrs["request_id"] = rid
        get_tracer().record(
            "router.request", t0, time.monotonic(), trace, status=status,
            span_id=trace.span_id, parent_id=trace.parent_id, attrs=attrs)

    def _end_flight_span(self, fl: _Flight, status: str, **attrs) -> None:
        self._end_flight_span_at(fl.trace, fl.rid, fl.submit_t0, status,
                                 replica=fl.replica_id,
                                 attempts=fl.attempts,
                                 tokens=len(fl.emitted), **attrs)

    def _cancel_flight(self, fl: _Flight) -> None:
        fl.cancelled = True
        inner = fl.inner
        if inner is not None:
            inner.cancel()

    def _settle_flight(self, fl: _Flight) -> None:
        """Finalize the tenant reservation on a terminal outcome: exactly
        the tokens streamed to the caller stay charged (``fl.emitted`` is
        appended once per delivered token, across every replica
        incarnation), the rest of the reservation is refunded.  Hedge
        losers and failover replays never touched the governor, so there
        is nothing to reconcile beyond this one settlement."""
        if self.governor is None:
            return
        self.governor.note_delivered(fl.rid, len(fl.emitted))
        self.governor.settle(fl.rid)

    # -- pump: stream, hedge, fail over ---------------------------------

    def _pump(self, fl: _Flight) -> None:
        # Pump threads are born context-less: re-enter the flight's trace
        # so the replica calls below (failover resubmits, hedge legs,
        # their HTTP hops) carry the originating traceparent.
        tracer = get_tracer()
        try:
            with tracer.use(fl.trace):
                while True:
                    outcome = self._consume(fl)
                    if outcome is _DONE:
                        return
                    if outcome is _HANDOFF:
                        # Prefill leg finished cleanly: hand the prefix to
                        # a decode replica (or degrade to local decode) —
                        # the prefill replica's inflight/breaker credit was
                        # already settled in _consume.
                        err = self._handoff(fl)
                        if err is None:
                            continue
                        return self._fail(fl, err)
                    # Replica died mid-generation: fold emitted tokens into
                    # the prompt, trim the budget, resubmit elsewhere
                    # (supervisor replay contract, fleet-wide).
                    self.registry.note_done(fl.replica_id, ok=False)
                    self.registry.mark_unready(fl.replica_id, str(outcome))
                    self._bump("failovers")
                    fl.attempts += 1
                    fl.pending_decode = False  # replay carries full budget
                    if fl.cancelled:
                        return self._fail(fl, "cancelled")
                    if fl.attempts > self.max_failovers:
                        return self._fail(
                            fl, f"failover budget exhausted: {outcome}")
                    remaining = fl.sampling.max_tokens - len(fl.emitted)
                    if remaining <= 0:
                        return self._finish_trimmed(fl)
                    replay = dataclasses.replace(
                        fl.sampling, max_tokens=remaining)
                    t_fo = time.monotonic()
                    ranked = self._ranked(fl.digest, need_tokens=True,
                                          slo_class=fl.slo_class)
                    chosen, handle = self._dispatch_tokens(
                        ranked, fl.prompt_ids + fl.emitted, replay,
                        f"{fl.rid}-a{fl.attempts}", fl.deadline_s,
                        exclude={fl.replica_id}, slo_class=fl.slo_class,
                        tenant=fl.tenant)
                    if chosen is None:
                        return self._fail(
                            fl, f"no healthy replica for failover ({handle})")
                    tracer.record(
                        "router.failover", t_fo, time.monotonic(), fl.trace,
                        attrs={"request_id": fl.rid, "from": fl.replica_id,
                               "to": chosen, "attempt": fl.attempts,
                               "tokens_folded": len(fl.emitted),
                               "cause": str(outcome)[:200]})
                    logger.info(
                        "request %s failed over %s -> %s after %d tokens",
                        fl.rid, fl.replica_id, chosen, len(fl.emitted))
                    fl.prior = list(fl.emitted)
                    fl.replica_id, fl.inner = chosen, handle
                    fl.dispatch_t0 = time.monotonic()
        except Exception:  # noqa: BLE001 — a pump must never strand a caller
            logger.exception("pump for %s crashed", fl.rid)
            self._fail(fl, "router pump error")

    def _consume(self, fl: _Flight):
        """Stream one replica incarnation into the fleet handle.  Returns
        ``_DONE`` on a delivered final result or an error-message string
        when the replica died and a failover should run."""
        inner = fl.inner
        first = not fl.emitted
        # Hedging doubles device work for one request: never for batch
        # traffic, and not while the primary reports brownout (degraded or
        # worse) — the extra dispatch is exactly what it is shedding.
        # (A pending-decode prefill leg never hedges either: its 1-token
        # budget is the short leg, and a hedge would race the full budget.)
        if (self.hedge.enabled and first and fl.attempts == 0
                and not fl.cancelled and fl.slo_class != "batch"
                and not fl.pending_decode
                and not self._replica_browned_out(fl.replica_id)):
            hedged = self._maybe_hedge(fl)
            if hedged is not None:
                inner = hedged
        last_progress = time.monotonic()
        while True:
            try:
                tok = inner.poll_token(timeout=0.2)
            except TimeoutError:
                if (time.monotonic() - last_progress > self.stall_timeout_s
                        and not fl.cancelled):
                    inner.cancel()
                    return "replica stalled (no token within "\
                           f"{self.stall_timeout_s:.0f}s)"
                continue
            last_progress = time.monotonic()
            if tok is None:
                res = inner.result(timeout=10.0)
                if res.finish_reason == "error" and not fl.cancelled:
                    return res.error or "replica failed"
                if (fl.pending_decode and not fl.cancelled
                        and res.finish_reason == "length"
                        and fl.sampling.max_tokens > len(fl.emitted)):
                    # The 1-token prefill budget is spent but the caller's
                    # budget isn't: this is the handoff point, not the end
                    # of the stream.  (EOS inside the prefill leg — a
                    # "stop" finish — completes normally below.)
                    self.registry.note_done(fl.replica_id, ok=True)
                    return _HANDOFF
                fl.handle._replay_prefix = list(fl.prior)
                self._settle_flight(fl)
                fl.handle._push([], res)
                self.registry.note_done(
                    fl.replica_id, ok=res.finish_reason != "error")
                self._bump("completed")
                self._end_flight_span(
                    fl, "error" if res.finish_reason == "error" else "ok",
                    finish_reason=res.finish_reason)
                return _DONE
            if not fl.emitted and not fl.prior:
                self._note_ttft(time.monotonic() - fl.dispatch_t0)
            fl.emitted.append(tok)
            fl.handle._push([tok], None)

    def _handoff(self, fl: _Flight) -> Optional[str]:
        """The prefill→decode handoff ladder.  The prefill replica P has
        finished the prompt (plus first token); its KV pool holds the full
        prefix.  Rungs, in order:

        1. Export the prefix from P and install it on the best decode
           candidate D; on ``installed``/``cached``, dispatch the
           remaining budget to D (suffix-only admission — the DistServe
           move).
        2. Any handoff failure (``nospace``, ``incompatible``, owner
           death, install timeout, torn blob, no decode candidate, D
           refusing the dispatch) degrades to **local decode on P** —
           P's prefix cache still holds the prompt, so this is a hit,
           not a re-prefill.
        3. P itself dead: the normal failover ranking over everyone else
           (a plain replay — the only rung that re-prefills).

        Returns None with ``fl.inner`` streaming the continuation, or an
        error message only when no replica anywhere would take it."""
        fl.pending_decode = False
        prefill_id = fl.replica_id
        remaining = fl.sampling.max_tokens - len(fl.emitted)
        cont = dataclasses.replace(fl.sampling, max_tokens=remaining)
        prompt = fl.prompt_ids + fl.emitted
        t0 = time.monotonic()
        ranked = self._ranked(fl.digest, need_tokens=True,
                              slo_class=fl.slo_class)
        entry = self.registry.get(prefill_id)
        owner = entry.replica if entry is not None else None
        decode_ranked = [c for c in ranked
                         if c.stats.role == "decode"
                         and c.replica_id != prefill_id
                         and c.replica.supports_kv_migration]

        cause: Optional[str] = None
        chosen, handle = None, None
        if not decode_ranked:
            cause = "no_decode"
        elif owner is None or not getattr(owner, "supports_kv_migration",
                                          False):
            cause = "owner_down"
        else:
            target = decode_ranked[0]
            blob = None
            try:
                blob = owner.fetch_prefix(prompt, tenant=fl.tenant)
            except ReplicaUnavailable:
                cause = "owner_down"
            except Exception:  # noqa: BLE001 — handoff is best-effort
                logger.exception("handoff fetch from %s failed", prefill_id)
                cause = "error"
            if cause is None and blob is None:
                cause = "miss"
            if cause is None:
                try:
                    outcome = str(target.replica.install_prefix(
                        blob, tenant=fl.tenant))
                except BlobError:
                    cause = "torn"
                except ReplicaUnavailable:
                    # Covers both install timeouts and a target that died
                    # mid-transfer — either way the blob never landed.
                    cause = "install_timeout"
                except Exception:  # noqa: BLE001 — handoff is best-effort
                    logger.exception("handoff install on %s failed",
                                     target.replica_id)
                    cause = "error"
                else:
                    if outcome not in ("installed", "cached"):
                        cause = outcome  # nospace | incompatible
            if cause is None:
                chosen, handle = self._dispatch_tokens(
                    [target], prompt, cont, f"{fl.rid}-d{fl.attempts}",
                    fl.deadline_s, slo_class=fl.slo_class,
                    tenant=fl.tenant)
                if chosen is None:
                    cause = "dispatch_failed"

        landing = "decode"
        if chosen is None:
            # Degrade: local decode on P (rung 2).  P may be draining or
            # mid-removal from the candidate set — dispatch to it directly
            # (draining replicas finish their own work, they just take no
            # NEW requests; a handoff fallback is this request's work).
            self._bump_handoff(cause or "error")
            local = next((c for c in ranked
                          if c.replica_id == prefill_id), None)
            if local is None and entry is not None:
                local = Candidate(prefill_id, entry.replica, entry.stats,
                                  entry.inflight)
            if local is not None:
                chosen, handle = self._dispatch_tokens(
                    [local], prompt, cont, f"{fl.rid}-l{fl.attempts}",
                    fl.deadline_s, slo_class=fl.slo_class,
                    tenant=fl.tenant)
            landing = "local"
        if chosen is None:
            # Rung 3: P is gone too — plain failover replay elsewhere.
            chosen, handle = self._dispatch_tokens(
                ranked, prompt, cont, f"{fl.rid}-f{fl.attempts}",
                fl.deadline_s, exclude={prefill_id},
                slo_class=fl.slo_class, tenant=fl.tenant)
            landing = "replay"
        if chosen is None:
            return (f"handoff failed ({cause or 'no target'}) and no "
                    "replica would take the continuation")
        self._bump_handoff(landing)
        get_tracer().record(
            "router.handoff", t0, time.monotonic(), fl.trace,
            status="ok" if landing == "decode" else "error",
            attrs={"request_id": fl.rid, "from": prefill_id,
                   "to": chosen, "landing": landing,
                   "cause": cause or "", "tokens": len(fl.emitted)})
        if landing != "decode":
            logger.info("handoff for %s degraded to %s on %s (%s)",
                        fl.rid, landing, chosen, cause)
        fl.prior = list(fl.emitted)
        fl.replica_id, fl.inner = chosen, handle
        fl.dispatch_t0 = time.monotonic()
        return None

    def _replica_browned_out(self, replica_id: str) -> bool:
        entry = self.registry.get(replica_id)
        return entry is not None and entry.stats.brownout >= 1

    def _maybe_hedge(self, fl: _Flight) -> Optional[RequestHandle]:
        """Wait the hedge delay for a first token; past it, race a second
        replica.  Returns the winning inner handle (the loser is cancelled)
        or None when no hedge happened.  Any token seen here is forwarded
        before returning, so ``_consume`` continues seamlessly."""
        delay = self.hedge_delay_s()
        primary = fl.inner
        try:
            tok = primary.poll_token(timeout=delay)
        except TimeoutError:
            tok = False  # no first token yet: hedge
        if tok is not False:
            if tok is not None:
                self._note_ttft(time.monotonic() - fl.dispatch_t0)
                fl.emitted.append(tok)
                fl.handle._push([tok], None)
            # else: stream ended inside the delay window (poll_token
            # re-armed the end sentinel for _consume).  Nothing to hedge.
            return None
        t_hedge = time.monotonic()
        ranked = self._ranked(fl.digest, need_tokens=True,
                              slo_class=fl.slo_class)
        chosen, hedge_handle = self._dispatch_tokens(
            ranked, fl.prompt_ids, fl.sampling, f"{fl.rid}-h",
            fl.deadline_s, exclude={fl.replica_id}, slo_class=fl.slo_class,
            tenant=fl.tenant)
        if chosen is None:
            return None
        self._bump("hedges_fired")
        winner_id, winner, loser_id, loser = self._race(
            fl.replica_id, primary, chosen, hedge_handle)
        if winner is hedge_handle:
            self._bump("hedges_won")
        get_tracer().record(
            "router.hedge", t_hedge, time.monotonic(), fl.trace,
            attrs={"request_id": fl.rid, "primary": fl.replica_id,
                   "hedge": chosen, "winner": winner_id,
                   "delay_s": round(delay, 6)})
        loser.cancel()
        # The loser keeps running to its (cancelled) completion on its own
        # replica; release the router-side inflight slot now.  Cancellation
        # is not a replica failure.
        self.registry.note_done(loser_id, ok=True)
        fl.replica_id, fl.inner = winner_id, winner
        return winner

    @staticmethod
    def _race(rid_a: str, ha: RequestHandle, rid_b: str, hb: RequestHandle):
        """First handle to show life (token or end-of-stream) wins.  A
        token seen here is NOT consumed — poll_token re-arms nothing for
        tokens, so peek by polling with a tiny timeout and pushing the
        token back is unsafe; instead the race polls with ``poll_token``
        and hands any consumed token straight back via the queue head."""
        while True:
            for rid, h in ((rid_a, ha), (rid_b, hb)):
                try:
                    tok = h.poll_token(timeout=0.005)
                except TimeoutError:
                    continue
                # Re-queue what we consumed so the winner's stream is
                # intact for _consume (FIFO queue: only safe because the
                # race is the sole consumer until it returns).
                if tok is not None:
                    h._tokens.queue.appendleft(tok)
                else:
                    pass  # poll_token already re-armed the end sentinel
                other_rid, other = (rid_b, hb) if h is ha else (rid_a, ha)
                return rid, h, other_rid, other

    def _fail(self, fl: _Flight, msg: str) -> None:
        self._bump("failed")
        self._settle_flight(fl)
        self._end_flight_span(fl, "error", error=msg[:200])
        fl.handle._replay_prefix = []
        fl.handle._push([], GenerationResult(
            request_id=fl.rid, token_ids=list(fl.emitted),
            finish_reason="error", ttft_s=0.0, latency_s=0.0, error=msg))

    def _finish_trimmed(self, fl: _Flight) -> None:
        """The dying replica had already emitted the full budget: complete
        with what was streamed (nothing left to regenerate)."""
        self._settle_flight(fl)
        self._end_flight_span(fl, "ok", finish_reason="length")
        fl.handle._replay_prefix = []
        fl.handle._push([], GenerationResult(
            request_id=fl.rid, token_ids=list(fl.emitted),
            finish_reason="length", ttft_s=0.0, latency_s=0.0))
        self._bump("completed")

    # -- text-level routing (HTTP replicas) ------------------------------

    def _dispatch_text(self, digest: bytes, op,
                       slo_class: str = "standard"):
        """Run ``op(replica)`` on the first candidate that takes it;
        connection-level failures fall through to the next candidate."""
        ranked = self._ranked(digest, need_tokens=False,
                              slo_class=slo_class)
        last_exc: Exception | None = None
        for cand in ranked:
            entry = self.registry.get(cand.replica_id)
            if entry is None:
                continue
            try:
                entry.breaker.before_call()
            except CircuitOpen as exc:
                last_exc = exc
                continue
            self.registry.note_dispatch(cand.replica_id)
            self._bump("dispatches")
            try:
                out = op(cand.replica)
            except OverloadedError as exc:
                entry.breaker.record_success()
                self.registry.note_done(cand.replica_id, ok=True)
                last_exc = exc
                continue
            except Exception as exc:  # noqa: BLE001 — routing fact
                self.registry.note_done(cand.replica_id, ok=False)
                self.registry.mark_unready(cand.replica_id, str(exc))
                last_exc = exc
                continue
            self._account_affinity(digest, cand.replica_id, ranked)
            return cand.replica_id, out
        self._bump("sheds")
        if isinstance(last_exc, OverloadedError):
            raise last_exc
        raise OverloadedError(
            f"no replica available ({last_exc or 'fleet empty'})",
            retriable=True, retry_after_s=1.0, slo_class=slo_class)

    def _admit_text(self, tenant: str, slo_class: str) -> None:
        """Rate-only quota for the text paths: there is no token budget to
        reserve up front (the replica owns generation), so charge one
        request-bucket token and settle the empty reservation at once.
        Raises the tenant-tagged 429 before any replica is contacted."""
        if self.governor is None:
            return
        rid = f"fleet-q-{next(self._ids)}"
        self.governor.admit(tenant, rid, max_tokens=0, slo_class=slo_class)
        self.governor.settle(rid)

    def query(self, question: str,
              slo_class: str = "interactive",
              tenant: str = DEFAULT_TENANT) -> dict:
        tenant = normalize_tenant(tenant)
        self._admit_text(tenant, slo_class)
        rid, payload = self._dispatch_text(
            self._text_digest(question),
            lambda r: r.query(question, slo_class=slo_class,
                              tenant=tenant),
            slo_class=slo_class)
        self.registry.note_done(rid, ok=True)
        return payload

    def analyze(self, payload: dict,
                tenant: str = DEFAULT_TENANT) -> dict:
        tenant = normalize_tenant(tenant)
        self._admit_text(tenant, "standard")
        rid, out = self._dispatch_text(
            self._text_digest(payload.get("type", "")),
            lambda r: r.analyze(payload, tenant=tenant))
        self.registry.note_done(rid, ok=True)
        return out

    def diagnoses(self, limit: int = 0) -> dict:
        """Verdict history from any one replica's standing pipeline.  A
        fixed digest keeps consecutive polls on the same replica (histories
        are per-replica rings, so a stable view beats a merged one)."""
        rid, out = self._dispatch_text(
            self._text_digest("diagnoses"), lambda r: r.diagnoses(limit))
        self.registry.note_done(rid, ok=True)
        if isinstance(out, dict):
            out = dict(out)
            out["replica"] = rid
        return out

    def query_stream(self, question: str, slo_class: str = "interactive",
                     tenant: str = DEFAULT_TENANT):
        """Returns (request_id, model, delta iterator).  The iterator fails
        over mid-stream: a new replica re-answers and the already-delivered
        character prefix is suppressed, so the caller sees a contiguous
        stream (exact for deterministic backends — greedy decode over the
        same evidence; the token-level path is the strict contract).
        Failover re-dispatches ride the original admission — the quota
        charge happens once, here."""
        tenant = normalize_tenant(tenant)
        self._admit_text(tenant, slo_class)
        digest = self._text_digest(question)
        rid, (rep_rid, model, chunks) = self._dispatch_text(
            digest, lambda r: r.query_stream(question, slo_class=slo_class,
                                             tenant=tenant),
            slo_class=slo_class)

        def deltas():
            nonlocal rid, chunks
            emitted = 0
            skip = 0
            attempts = 0
            while True:
                try:
                    for delta in chunks:
                        if skip:
                            take = delta[skip:]
                            skip = max(0, skip - len(delta))
                            delta = take
                        if delta:
                            emitted += len(delta)
                            yield delta
                    self.registry.note_done(rid, ok=True)
                    self._bump("completed")
                    return
                except GeneratorExit:
                    if hasattr(chunks, "close"):
                        chunks.close()
                    self.registry.note_done(rid, ok=True)
                    raise
                except Exception as exc:  # noqa: BLE001 — failover trigger
                    self.registry.note_done(rid, ok=False)
                    self.registry.mark_unready(rid, str(exc))
                    self._bump("failovers")
                    attempts += 1
                    if attempts > self.max_failovers:
                        self._bump("failed")
                        raise
                    try:
                        rid, (_, _, chunks) = self._dispatch_text(
                            digest,
                            lambda r: r.query_stream(question,
                                                     slo_class=slo_class,
                                                     tenant=tenant),
                            slo_class=slo_class)
                    except OverloadedError:
                        self._bump("failed")
                        raise exc from None
                    skip = emitted
                    logger.info("stream %s failed over mid-answer after "
                                "%d chars", rep_rid, emitted)

        return rep_rid, model, deltas()
