"""Standing watcher→LLM root-cause pipeline.

Closes the loop the reference left open: cluster events stream in through
the ``Watcher``'s ``EventHandler`` seam, a burst detector decides when the
cluster is "interesting enough" to spend a TPU generation on, a context
assembler packs a bounded evidence block (embedding top-k retrieval when an
encoder is configured, recency otherwise), and one grammar-constrained
root-cause query per burst lands in a verdict ring published as exporter
gauges and ``GET /api/v1/diagnoses``.

Threading model: ``DiagnosisEventHandler`` methods run on watcher threads
and must stay cheap — they append to rings and enqueue triggers.  The
single worker thread owns all LLM calls, so a slow generation can never
back up the watch streams; bursts arriving mid-generation coalesce into
the next query instead of queueing one generation each.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from collections import deque
from typing import Any, Callable

import numpy as np

from k8s_llm_monitor_tpu.devtools.lockcheck import guarded_by, make_lock
from k8s_llm_monitor_tpu.monitor.models import EventInfo, utcnow
from k8s_llm_monitor_tpu.monitor.watcher import EventHandler
from k8s_llm_monitor_tpu.observability.tracing import get_tracer

logger = logging.getLogger("diagnosis.pipeline")


class BurstDetector:
    """Sliding-window burst detection with a refractory cooldown.

    ``observe()`` returns True when the number of observations inside the
    trailing ``window_s`` reaches ``threshold`` AND at least ``cooldown_s``
    has passed since the last firing — one incident produces one trigger,
    not one per event above the threshold.  The clock is injectable so
    tests drive it deterministically.
    """

    def __init__(self, threshold: int = 5, window_s: float = 60.0,
                 cooldown_s: float = 120.0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if threshold < 1:
            raise ValueError("threshold must be >= 1")
        self.threshold = threshold
        self.window_s = window_s
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._times: deque[float] = deque()
        self._last_fire: float | None = None

    def observe(self) -> bool:
        now = self._clock()
        self._times.append(now)
        while self._times and now - self._times[0] > self.window_s:
            self._times.popleft()
        if len(self._times) < self.threshold:
            return False
        if (self._last_fire is not None
                and now - self._last_fire < self.cooldown_s):
            return False
        self._last_fire = now
        # Consume the window: the NEXT burst needs threshold fresh events,
        # so a long cooldown doesn't fire instantly off stale ones.
        self._times.clear()
        return True

    def pending(self) -> int:
        """Observations currently inside the window (diagnostics)."""
        now = self._clock()
        while self._times and now - self._times[0] > self.window_s:
            self._times.popleft()
        return len(self._times)


@guarded_by("_lock", "_texts")
class ContextAssembler:
    """Bounded cluster-context block from the recent event stream.

    Keeps the last ``capacity`` event texts; ``assemble(query)`` selects
    ``top_k`` of them — by embedding cosine similarity to the query when an
    encoder is available (``analysis.anomaly.EmbeddingAnomalyDetector`` or
    anything with ``embed(texts) -> [N, H]`` L2-normalized), else the most
    recent — renders them chronologically, and hard-caps the block at
    ``max_chars`` so prompt size stays bounded no matter how noisy the
    cluster gets.
    """

    def __init__(self, capacity: int = 64, top_k: int = 8,
                 max_chars: int = 2000, embedder: Any = None) -> None:
        self.capacity = capacity
        self.top_k = top_k
        self.max_chars = max_chars
        self.embedder = embedder
        self._texts: deque[str] = deque(maxlen=capacity)
        self._lock = make_lock("diagnosis.context")

    def add(self, text: str) -> None:
        with self._lock:
            self._texts.append(text)

    def __len__(self) -> int:
        with self._lock:
            return len(self._texts)

    def _select(self, texts: list[str], query: str) -> list[str]:
        if len(texts) <= self.top_k:
            return texts
        if self.embedder is not None:
            try:
                vecs = self.embedder.embed(list(texts) + [query])
                sims = vecs[:-1] @ vecs[-1]
                keep = sorted(np.argsort(-sims)[: self.top_k])
                return [texts[i] for i in keep]
            except Exception as exc:  # noqa: BLE001 — retrieval is best-effort
                logger.warning("embedding retrieval failed (%s); "
                               "falling back to recency", exc)
        return texts[-self.top_k:]

    def assemble(self, query: str = "") -> str:
        with self._lock:
            texts = list(self._texts)
        if not texts:
            return "## Recent cluster events\n- none observed\n"
        lines = ["## Recent cluster events"]
        budget = self.max_chars - len(lines[0]) - 1
        for text in self._select(texts, query):
            line = f"- {text}"
            if budget - len(line) - 1 < 0:
                break
            lines.append(line)
            budget -= len(line) + 1
        return "\n".join(lines) + "\n"


@guarded_by("_lock", "_history", "_counts", "_lag_ms")
class VerdictStore:
    """Ring-buffer verdict history + the counters behind the exporter's
    ``diagnosis_*`` gauges.  All access is lock-guarded: the pipeline
    worker publishes while HTTP handler threads snapshot."""

    SEVERITIES = ("info", "warning", "critical")

    def __init__(self, capacity: int = 64) -> None:
        self.capacity = capacity
        self._history: deque[dict[str, Any]] = deque(maxlen=capacity)
        self._counts: dict[str, int] = {s: 0 for s in self.SEVERITIES}
        self._lag_ms: float = 0.0
        self._lock = make_lock("diagnosis.verdicts")

    def publish(self, verdict: dict[str, Any], *, trigger: str = "",
                lag_ms: float = 0.0, model: str = "") -> dict[str, Any]:
        entry = {
            "verdict": dict(verdict),
            "trigger": trigger,
            "model": model,
            "lag_ms": round(lag_ms, 3),
            "timestamp": utcnow().isoformat(),
        }
        sev = verdict.get("severity", "")
        with self._lock:
            self._history.append(entry)
            if sev in self._counts:
                self._counts[sev] += 1
            self._lag_ms = lag_ms
        return entry

    def snapshot(self, limit: int = 0) -> list[dict[str, Any]]:
        """Newest-first history (the shape ``GET /api/v1/diagnoses``
        returns)."""
        with self._lock:
            items = list(self._history)
        items.reverse()
        return items[:limit] if limit > 0 else items

    def counts(self) -> dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def lag_ms(self) -> float:
        with self._lock:
            return self._lag_ms

    def __len__(self) -> int:
        with self._lock:
            return len(self._history)


class DiagnosisEventHandler(EventHandler):
    """The ``Watcher``-facing adapter: every event feeds the context ring;
    Warning events additionally feed the burst detector."""

    def __init__(self, pipeline: "DiagnosisPipeline") -> None:
        self.pipeline = pipeline

    @staticmethod
    def format_event(event: EventInfo) -> str:
        text = f"{event.reason}: {event.message}"
        if event.source:
            text += f" (source {event.source})"
        if event.count > 1:
            text += f" x{event.count}"
        return text

    def on_event(self, event: EventInfo) -> None:
        self.pipeline.offer(event)

    def on_pod_update(self, event_type: str, pod) -> None:  # noqa: D102
        # Pod phase churn lands in the context ring (it is exactly what a
        # root-cause prompt needs alongside the warning events) but does
        # not count toward bursts — event objects carry the signal.
        if event_type in ("MODIFIED", "DELETED"):
            phase = getattr(pod, "phase", "")
            if phase and phase != "Running":
                self.pipeline.context.add(
                    f"pod {getattr(pod, 'namespace', '?')}/"
                    f"{getattr(pod, 'name', '?')} phase={phase}")


class DiagnosisPipeline:
    """Watcher events → bursts → one constrained root-cause query each.

    ``analysis`` is an ``AnalysisEngine`` (anything with
    ``diagnose(question, context=...) -> dict``).  Triggers that arrive
    while a generation is running coalesce: the worker drains the queue
    and answers them with a single query over the merged reasons.
    """

    def __init__(self, analysis, cfg=None, *, embedder: Any = None,
                 brownout: Callable[[], int] | None = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        from k8s_llm_monitor_tpu.monitor.config import DiagnosisConfig

        self.analysis = analysis
        self.cfg = cfg or DiagnosisConfig()
        self._clock = clock
        # Brownout rung supplier (resilience/slo.py): at draining (>= 2)
        # trigger enqueue pauses — the engine is shedding real traffic, so
        # background diagnosis must not compete for its slots.
        self._brownout = brownout
        self.paused_total = 0
        self.detector = BurstDetector(
            threshold=self.cfg.burst_threshold,
            window_s=self.cfg.window_s,
            cooldown_s=self.cfg.cooldown_s,
            clock=clock,
        )
        self.context = ContextAssembler(
            capacity=self.cfg.max_context_events,
            top_k=self.cfg.context_top_k,
            max_chars=self.cfg.max_context_chars,
            embedder=embedder,
        )
        self.store = VerdictStore(capacity=self.cfg.history)
        self.handler = DiagnosisEventHandler(self)
        # Plan stage (remediation/executor.py RemediationEngine), wired by
        # build_server behind RemediationConfig; None leaves the pipeline
        # verdict-only.
        self.remediation: Any = None
        self.triggers_total = 0
        self.queries_total = 0
        self.errors_total = 0
        self._queue: queue.Queue = queue.Queue()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- watcher-thread side --------------------------------------------------

    def offer(self, event: EventInfo) -> None:
        """Feed one cluster event; cheap enough for watcher threads."""
        self.context.add(DiagnosisEventHandler.format_event(event))
        if event.type != "Warning":
            return
        if self.detector.observe():
            if self._brownout is not None:
                try:
                    paused = int(self._brownout()) >= 2
                except Exception:  # noqa: BLE001 — never drop the watcher
                    paused = False
                if paused:
                    self.paused_total += 1
                    return
            self.triggers_total += 1
            self._queue.put({
                "reason": event.reason or "warning burst",
                "t": self._clock(),
            })

    # -- worker side ----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="diagnosis-pipeline", daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        self._queue.put(None)
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.is_set():
            trigger = self._queue.get()
            if trigger is None or self._stop.is_set():
                return
            # Coalesce everything already queued into this one query.
            reasons = [trigger["reason"]]
            t0 = trigger["t"]
            while True:
                try:
                    extra = self._queue.get_nowait()
                except queue.Empty:
                    break
                if extra is None:
                    return
                reasons.append(extra["reason"])
            try:
                self._diagnose_once(reasons, t0)
            except Exception:  # noqa: BLE001 — the loop must survive
                self.errors_total += 1
                logger.exception("diagnosis query failed")

    def _diagnose_once(self, reasons: list[str], t_trigger: float) -> None:
        uniq = sorted(set(reasons))
        tracer = get_tracer()
        lag_s = max(0.0, self._clock() - t_trigger)
        with tracer.span("diagnosis.run", root=True,
                         attrs={"reasons": ", ".join(uniq)[:200],
                                "n_triggers": len(reasons)}) as run_sp:
            # Trigger span: queue-wait between burst detection (watcher
            # thread) and this worker picking it up.  The pipeline clock is
            # injectable, so the span is rebuilt on the real monotonic axis
            # from the measured lag rather than trusting t_trigger directly.
            t_run = time.monotonic()
            tracer.record("diagnosis.trigger", t_run - lag_s, t_run,
                          tracer.current(),
                          attrs={"reasons": ", ".join(uniq)[:200]})
            question = (
                "A burst of Warning events was just observed "
                f"(reasons: {', '.join(uniq)}). Identify the most probable "
                "root cause and the first remediation step."
            )
            with tracer.span("diagnosis.context") as ctx_sp:
                context = self.context.assemble(question)
                ctx_sp.attrs["context_chars"] = len(context)
            # Background root-cause work rides the lowest lane: interactive
            # operators must never queue behind an automatic trigger.
            with tracer.span("diagnosis.llm", attrs={"class": "batch"}):
                verdict = self.analysis.diagnose(question, context=context,
                                                 slo_class="batch")
            self.queries_total += 1
            lag_ms = max(0.0, (self._clock() - t_trigger) * 1000.0)
            run_sp.attrs["trigger_lag_ms"] = round(lag_ms, 1)
            run_sp.attrs["severity"] = str(verdict.get("severity", ""))
        self.store.publish(
            verdict, trigger=", ".join(uniq), lag_ms=lag_ms,
            model=getattr(getattr(self.analysis, "backend", None),
                          "name", ""))
        logger.info("diagnosis published: severity=%s component=%s "
                    "lag=%.0fms", verdict.get("severity"),
                    verdict.get("component"), lag_ms)
        # Plan stage: verdict → grammar-bounded action plan (and, when
        # configured, gated execution + verification).  After publish —
        # a failing plan stage must never cost the verdict, and
        # on_verdict itself never raises.
        if self.remediation is not None:
            self.remediation.on_verdict(
                verdict, trigger=", ".join(uniq), context=context)

    def run_pending(self) -> int:
        """Drain queued triggers synchronously (tests / no-thread mode).
        Returns the number of queries executed."""
        ran = 0
        batches: list[tuple[list[str], float]] = []
        reasons: list[str] = []
        t0 = None
        while True:
            try:
                trig = self._queue.get_nowait()
            except queue.Empty:
                break
            if trig is None:
                continue
            reasons.append(trig["reason"])
            t0 = trig["t"] if t0 is None else t0
        if reasons and t0 is not None:
            batches.append((reasons, t0))
        for rs, t in batches:
            try:
                self._diagnose_once(rs, t)
                ran += 1
            except Exception:  # noqa: BLE001
                self.errors_total += 1
                logger.exception("diagnosis query failed")
        return ran
