"""JSON-schema → token-FSM compiler for grammar-constrained decoding.

Following Willard & Louf 2023 ("Efficient Guided Generation for Large
Language Models" / Outlines): a schema is lowered to a regular grammar,
compiled through Thompson NFA → subset-construction DFA over *characters*,
then lifted to a dense token-transition table the on-device sampler indexes
per decode step.  With ``ByteTokenizer`` (ids 0=pad 1=bos 2=eos, bytes at
3..258) the char→token lift is exact and 1:1; multi-byte BPE vocabs would
walk each token's byte string through the char DFA the same way (the table
stays ``[states, vocab]`` — at 128k vocab that is the packed-mask future
work noted in docs/diagnosis.md).

The supported schema subset (deliberately the shape structured verdicts
need, all of it producing a *bounded* regular language so ``max_len`` is
finite and the engine can guarantee completion before ``max_tokens``):

  * ``object`` with ordered ``properties`` (all required, emitted in
    declaration order, compact separators — one canonical serialization);
  * ``string`` with ``maxLength`` (and optional ``minLength``) over a
    JSON-safe charset (printable ASCII minus ``"`` and ``\\``);
  * ``enum`` of strings;
  * ``number`` (bounded decimal), ``integer``, ``boolean``;
  * ``array`` of a supported item schema with ``maxItems``.

``parse_verdict`` is the single sanctioned place model output becomes
parsed JSON: it validates against the char DFA first, so ``json.loads``
can never see anything the grammar didn't admit (the graftcheck
``model-json`` lint rule flags raw ``json.loads`` of model output
everywhere else).
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Any, Iterable

import numpy as np

# ByteTokenizer special ids (utils/tokenizer.py) — the default lift target.
_PAD_ID, _BOS_ID, _EOS_ID = 0, 1, 2
_BYTE_OFFSET = 3
_BYTE_VOCAB = 259

# JSON-safe string payload charset: printable ASCII minus '"' and '\', so
# the canonical serialization needs no escape productions.
_STRING_CHARS = frozenset(
    chr(c) for c in range(0x20, 0x7F) if chr(c) not in ('"', "\\")
)
_DIGITS = frozenset("0123456789")
_DIGITS19 = frozenset("123456789")


class GrammarError(ValueError):
    """Schema unsupported, or text rejected by the compiled grammar."""


# ---------------------------------------------------------------------------
# regular-expression AST (bounded constructs only)
# ---------------------------------------------------------------------------


class _Node:
    pass


@dataclass(frozen=True)
class _Lit(_Node):
    text: str


@dataclass(frozen=True)
class _Class(_Node):
    chars: frozenset


@dataclass(frozen=True)
class _Seq(_Node):
    parts: tuple


@dataclass(frozen=True)
class _Alt(_Node):
    parts: tuple


@dataclass(frozen=True)
class _Empty(_Node):
    pass


def _seq(*parts: _Node) -> _Node:
    return _Seq(tuple(parts))


def _alt(*parts: _Node) -> _Node:
    return _Alt(tuple(parts))


def _rep(part: _Node, lo: int, hi: int) -> _Node:
    """``part{lo,hi}`` with bounded ``hi``, expanded as nested optionals
    (``p{0,3} = (p(p(p)?)?)?``) so a skipped copy can't be followed by a
    taken one."""
    if hi < lo or lo < 0:
        raise GrammarError(f"bad repetition bounds {{{lo},{hi}}}")
    opt: _Node = _Empty()
    for _ in range(hi - lo):
        opt = _alt(_seq(part, opt), _Empty())
    return _seq(*([part] * lo), opt)


# ---------------------------------------------------------------------------
# Thompson NFA → subset-construction char DFA
# ---------------------------------------------------------------------------


class _NFA:
    def __init__(self) -> None:
        self.eps: list[set[int]] = []
        self.edges: list[dict[str, set[int]]] = []

    def state(self) -> int:
        self.eps.append(set())
        self.edges.append({})
        return len(self.eps) - 1

    def add(self, src: int, ch: str, dst: int) -> None:
        self.edges[src].setdefault(ch, set()).add(dst)

    def build(self, node: _Node, src: int) -> int:
        """Wire ``node`` starting at ``src``; returns its exit state."""
        if isinstance(node, _Empty):
            return src
        if isinstance(node, _Lit):
            cur = src
            for ch in node.text:
                nxt = self.state()
                self.add(cur, ch, nxt)
                cur = nxt
            return cur
        if isinstance(node, _Class):
            if not node.chars:
                raise GrammarError("empty character class")
            dst = self.state()
            for ch in node.chars:
                self.add(src, ch, dst)
            return dst
        if isinstance(node, _Seq):
            cur = src
            for part in node.parts:
                cur = self.build(part, cur)
            return cur
        if isinstance(node, _Alt):
            out = self.state()
            for part in node.parts:
                entry = self.state()
                self.eps[src].add(entry)
                self.eps[self.build(part, entry)].add(out)
            return out
        raise GrammarError(f"unknown AST node {type(node).__name__}")

    def closure(self, states: Iterable[int]) -> frozenset:
        stack = list(states)
        seen = set(stack)
        while stack:
            for nxt in self.eps[stack.pop()]:
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append(nxt)
        return frozenset(seen)


@dataclass
class CharDFA:
    """Deterministic char automaton; state 0 is the start state."""

    trans: list[dict[str, int]]
    accept: list[bool]

    def matches(self, text: str) -> bool:
        state = 0
        for ch in text:
            nxt = self.trans[state].get(ch)
            if nxt is None:
                return False
            state = nxt
        return self.accept[state]

    def max_path_len(self) -> int:
        """Longest char count of any accepted string; -1 if unbounded."""
        n = len(self.trans)
        memo: list[int | None] = [None] * n
        on_stack = [False] * n
        UNBOUNDED = -1

        def longest(s: int) -> int:
            if on_stack[s]:
                return UNBOUNDED
            if memo[s] is not None:
                return memo[s]
            on_stack[s] = True
            best = 0 if self.accept[s] else -(10**9)
            for nxt in self.trans[s].values():
                sub = longest(nxt)
                if sub == UNBOUNDED:
                    on_stack[s] = False
                    memo[s] = UNBOUNDED
                    return UNBOUNDED
                best = max(best, 1 + sub)
            on_stack[s] = False
            memo[s] = best
            return best

        total = longest(0)
        return UNBOUNDED if total == UNBOUNDED else max(total, 0)


def _determinize(nfa: _NFA, start: int, final: int) -> CharDFA:
    start_set = nfa.closure([start])
    index: dict[frozenset, int] = {start_set: 0}
    order: list[frozenset] = [start_set]
    trans: list[dict[str, int]] = [{}]
    i = 0
    while i < len(order):
        cur = order[i]
        moves: dict[str, set[int]] = {}
        for s in cur:
            for ch, dsts in nfa.edges[s].items():
                moves.setdefault(ch, set()).update(dsts)
        for ch, dsts in moves.items():
            tgt = nfa.closure(dsts)
            if tgt not in index:
                index[tgt] = len(order)
                order.append(tgt)
                trans.append({})
            trans[i][ch] = index[tgt]
        i += 1
    accept = [final in subset for subset in order]
    dfa = CharDFA(trans=trans, accept=accept)
    _prune_dead_ends(dfa)
    return dfa


def _prune_dead_ends(dfa: CharDFA) -> None:
    """Drop transitions into states that cannot reach accept — a sampler
    steered into such a state would have no allowed token and no way to
    finish.  A correct construction produces none; this is the compile-time
    guarantee, not a runtime patch."""
    n = len(dfa.trans)
    co = [dfa.accept[s] for s in range(n)]
    changed = True
    while changed:
        changed = False
        for s in range(n):
            if not co[s] and any(co[d] for d in dfa.trans[s].values()):
                co[s] = True
                changed = True
    if not co[0]:
        raise GrammarError("grammar accepts no strings")
    for s in range(n):
        dfa.trans[s] = {ch: d for ch, d in dfa.trans[s].items() if co[d]}
        if not dfa.accept[s] and not dfa.trans[s] and co[s]:
            raise GrammarError("grammar has a dead-end state")


# ---------------------------------------------------------------------------
# schema → AST
# ---------------------------------------------------------------------------


def _json_string_ast(schema: dict[str, Any]) -> _Node:
    lo = int(schema.get("minLength", 0))
    hi = int(schema.get("maxLength", 64))
    if hi <= 0 or hi > 4096:
        raise GrammarError(f"string maxLength {hi} out of range")
    return _seq(_Lit('"'), _rep(_Class(_STRING_CHARS), lo, hi), _Lit('"'))


def _number_ast() -> _Node:
    # Bounded decimal: -?(0|[1-9]\d{0,5})(\.\d{1,4})?
    intpart = _alt(_Lit("0"), _seq(_Class(_DIGITS19), _rep(_Class(_DIGITS), 0, 5)))
    frac = _alt(_seq(_Lit("."), _rep(_Class(_DIGITS), 1, 4)), _Empty())
    return _seq(_alt(_Lit("-"), _Empty()), intpart, frac)


def _schema_ast(schema: dict[str, Any]) -> _Node:
    if "anyOf" in schema:
        # Tagged-union schemas (the action-plan grammar: one object shape
        # per verb).  Each arm must itself be a supported schema; the
        # alternation stays a bounded regular language because every arm is.
        arms = schema["anyOf"]
        if not isinstance(arms, list) or not arms:
            raise GrammarError("anyOf must be a non-empty list of schemas")
        return _alt(*[_schema_ast(arm) for arm in arms])
    if "enum" in schema:
        values = schema["enum"]
        if not values or not all(isinstance(v, str) for v in values):
            raise GrammarError("enum must be a non-empty list of strings")
        return _alt(*[_Lit(json.dumps(v)) for v in values])
    stype = schema.get("type")
    if stype == "string":
        return _json_string_ast(schema)
    if stype == "number":
        return _number_ast()
    if stype == "integer":
        if "minimum" in schema or "maximum" in schema:
            # Bounded integer range as a literal alternation — small ranges
            # only (replica counts, retry budgets), where enumerating keeps
            # the DFA tiny and the admitted set exact.
            lo = int(schema.get("minimum", 0))
            hi = int(schema.get("maximum", lo))
            if hi < lo or hi - lo > 256:
                raise GrammarError(
                    f"integer range [{lo},{hi}] unsupported (span > 256)")
            return _alt(*[_Lit(str(i)) for i in range(lo, hi + 1)])
        return _seq(
            _alt(_Lit("-"), _Empty()),
            _alt(_Lit("0"), _seq(_Class(_DIGITS19), _rep(_Class(_DIGITS), 0, 8))),
        )
    if stype == "boolean":
        return _alt(_Lit("true"), _Lit("false"))
    if stype == "array":
        items = schema.get("items")
        max_items = int(schema.get("maxItems", 8))
        if not isinstance(items, dict):
            raise GrammarError("array schema needs an items schema")
        if max_items <= 0 or max_items > 64:
            raise GrammarError(f"array maxItems {max_items} out of range")
        item = _schema_ast(items)
        body = _alt(
            _seq(item, _rep(_seq(_Lit(","), item), 0, max_items - 1)),
            _Empty(),
        )
        return _seq(_Lit("["), body, _Lit("]"))
    if stype == "object":
        props = schema.get("properties") or {}
        if not props:
            raise GrammarError("object schema needs properties")
        parts: list[_Node] = [_Lit("{")]
        for i, (key, sub) in enumerate(props.items()):
            if i:
                parts.append(_Lit(","))
            parts.append(_Lit(json.dumps(key) + ":"))
            parts.append(_schema_ast(sub))
        parts.append(_Lit("}"))
        return _seq(*parts)
    raise GrammarError(f"unsupported schema: {schema!r}")


def compile_schema(schema: dict[str, Any]) -> CharDFA:
    """Compile a supported JSON schema into its canonical-form char DFA."""
    nfa = _NFA()
    start = nfa.state()
    final = nfa.build(_schema_ast(schema), start)
    return _determinize(nfa, start, final)


# ---------------------------------------------------------------------------
# token lift
# ---------------------------------------------------------------------------


@dataclass
class TokenFSM:
    """Dense token-transition table the sampler masks against.

    ``trans[s, t]`` = next state after token ``t`` in state ``s``, or -1 when
    ``t`` is disallowed.  Row/state 0 is the FREE state — all tokens allowed,
    self-loop — so one compiled decode program serves batches mixing
    constrained lanes (state >= 1) and unconstrained lanes (state 0).
    Grammar states occupy rows 1..n; accept states self-loop on ``eos_id``
    (and allow nothing else once the char DFA has no outgoing edges), which
    is how a finished verdict forces end-of-sequence.
    """

    trans: np.ndarray  # [n_states + 1, vocab] int32
    start: int
    accept: np.ndarray  # [n_states + 1] bool
    eos_id: int
    max_len: int  # longest accepted token sequence incl. EOS; -1 unbounded

    @property
    def n_states(self) -> int:
        return self.trans.shape[0]

    @property
    def vocab_size(self) -> int:
        return self.trans.shape[1]

    def allowed(self, state: int) -> np.ndarray:
        return self.trans[state] >= 0

    def step(self, state: int, token: int) -> int:
        if state == 0:
            return 0
        if not 0 <= token < self.vocab_size:
            return -1
        return int(self.trans[state, token])

    def walk(self, tokens: Iterable[int], state: int | None = None) -> int:
        """Advance from ``state`` (default: start) through ``tokens``;
        returns -1 once any token is disallowed.  Used at (re-)admission to
        resume a preempted constrained request from its generated-so-far
        suffix."""
        cur = self.start if state is None else state
        for tok in tokens:
            if cur < 0:
                return -1
            cur = self.step(cur, int(tok))
        return cur

    @classmethod
    def from_table(cls, trans: np.ndarray, start: int, accept: np.ndarray,
                   eos_id: int, max_len: int = -1) -> "TokenFSM":
        """Hand-built FSMs (traceguard's toy grammar over a tiny vocab)."""
        trans = np.asarray(trans, dtype=np.int32)
        if trans.ndim != 2 or start < 1 or start >= trans.shape[0]:
            raise GrammarError("bad hand-built FSM table")
        if not np.all(trans[0] == 0):
            raise GrammarError("row 0 must be the all-allowed FREE state")
        return cls(trans=trans, start=start,
                   accept=np.asarray(accept, dtype=bool),
                   eos_id=eos_id, max_len=max_len)


def token_fsm(dfa: CharDFA, *, eos_id: int = _EOS_ID,
              vocab_size: int = _BYTE_VOCAB) -> TokenFSM:
    """Lift a char DFA onto the byte-tokenizer vocab.

    Char ``c`` maps to token ``ord(c) + 3`` (ByteTokenizer); DFA state ``s``
    maps to row ``s + 1`` (row 0 is FREE).  Accept rows gain an ``eos_id``
    self-loop so EOS — and only EOS, once the object is closed — finishes
    the sequence.
    """
    n = len(dfa.trans)
    trans = np.full((n + 1, vocab_size), -1, dtype=np.int32)
    trans[0, :] = 0
    for s, edges in enumerate(dfa.trans):
        for ch, dst in edges.items():
            tok = ord(ch) + _BYTE_OFFSET
            if tok >= vocab_size:
                raise GrammarError(
                    f"char {ch!r} does not fit vocab size {vocab_size}")
            trans[s + 1, tok] = dst + 1
        if dfa.accept[s]:
            trans[s + 1, eos_id] = s + 1
    accept = np.zeros(n + 1, dtype=bool)
    accept[1:] = np.asarray(dfa.accept, dtype=bool)
    chars = dfa.max_path_len()
    return TokenFSM(trans=trans, start=1, accept=accept, eos_id=eos_id,
                    max_len=-1 if chars < 0 else chars + 1)


# ---------------------------------------------------------------------------
# the Verdict schema
# ---------------------------------------------------------------------------

VERDICT_SCHEMA: dict[str, Any] = {
    "type": "object",
    "properties": {
        "severity": {"enum": ["info", "warning", "critical"]},
        "component": {"type": "string", "minLength": 1, "maxLength": 48},
        "root_cause": {"type": "string", "minLength": 1, "maxLength": 160},
        "recommendation": {"type": "string", "minLength": 1, "maxLength": 160},
        "confidence": {"type": "number"},
    },
    "required": ["severity", "component", "root_cause", "recommendation",
                 "confidence"],
}


_VERDICT_DFA: CharDFA | None = None
_VERDICT_FSMS: dict[tuple[int, int], TokenFSM] = {}


def verdict_dfa() -> CharDFA:
    global _VERDICT_DFA
    if _VERDICT_DFA is None:
        _VERDICT_DFA = compile_schema(VERDICT_SCHEMA)
    return _VERDICT_DFA


def verdict_fsm(*, eos_id: int = _EOS_ID,
                vocab_size: int = _BYTE_VOCAB) -> TokenFSM:
    """The cached token FSM for ``VERDICT_SCHEMA``."""
    key = (eos_id, vocab_size)
    fsm = _VERDICT_FSMS.get(key)
    if fsm is None:
        fsm = token_fsm(verdict_dfa(), eos_id=eos_id, vocab_size=vocab_size)
        _VERDICT_FSMS[key] = fsm
    return fsm


def parse_with_dfa(text: str, dfa: CharDFA) -> dict[str, Any]:
    """Validate ``text`` against a compiled grammar, then parse.

    The single sanctioned ``json.loads`` of model output in the tree: the
    char DFA runs first, so anything the constrained sampler could not have
    produced raises ``GrammarError`` instead of reaching the parser.  Every
    schema family funnels through here (``parse_verdict`` for verdicts,
    ``remediation.plans.parse_plan`` for action plans).
    """
    text = text.strip()
    if not dfa.matches(text):
        raise GrammarError(
            f"model output rejected by the grammar: {text[:120]!r}")
    return json.loads(text)


def parse_verdict(text: str, dfa: CharDFA | None = None) -> dict[str, Any]:
    """Validate ``text`` against the verdict grammar, then parse."""
    return parse_with_dfa(text, dfa or verdict_dfa())


def render_verdict(severity: str, component: str, root_cause: str,
                   recommendation: str, confidence: float) -> str:
    """Canonical serialization of a verdict — the TemplateBackend's
    deterministic path, guaranteed to satisfy ``VERDICT_SCHEMA``'s grammar
    (fields are clamped/filtered to the grammar's charset and bounds)."""

    def clean(s: str, max_len: int) -> str:
        out = "".join(ch for ch in s if ch in _STRING_CHARS)[:max_len]
        return out or "n/a"

    if severity not in ("info", "warning", "critical"):
        severity = "warning"
    conf = min(max(float(confidence), 0.0), 1.0)
    return (
        "{" + f'"severity":"{severity}",'
        f'"component":"{clean(component, 48)}",'
        f'"root_cause":"{clean(root_cause, 160)}",'
        f'"recommendation":"{clean(recommendation, 160)}",'
        f'"confidence":{conf:.2f}' + "}"
    )
