"""Diagnosis engine: grammar-constrained verdicts + standing pipeline.

Closes the monitor→LLM loop (ROADMAP item 5): ``grammar`` compiles a JSON
schema into a token-level FSM whose per-step masks run inside the serving
engine's on-device sampler (Willard & Louf 2023 style guided generation),
``pipeline`` turns the watcher event stream into batched root-cause
queries with verdicts published as gauges + a ring-buffer history, and
``session`` pins multi-turn follow-ups to the prefix-cached context.
"""

from k8s_llm_monitor_tpu.diagnosis.grammar import (  # noqa: F401
    GrammarError,
    TokenFSM,
    VERDICT_SCHEMA,
    compile_schema,
    parse_verdict,
    verdict_fsm,
)
from k8s_llm_monitor_tpu.diagnosis.pipeline import (  # noqa: F401
    BurstDetector,
    ContextAssembler,
    DiagnosisEventHandler,
    DiagnosisPipeline,
    VerdictStore,
)
from k8s_llm_monitor_tpu.diagnosis.session import (  # noqa: F401
    DiagnosisSession,
    SessionManager,
)
