"""Multi-turn diagnosis sessions pinned to a prefix-cached context.

A session freezes the cluster-context block at creation time and builds
every follow-up prompt as::

    preamble + pinned context + turn_1 Q/A + ... + new question

The pinned prefix is the point: the serving engine's PrefixCache (and the
fleet router's prefix-affinity policy) key on leading tokens, so every
follow-up in a session replays the same prefix — prefill work for the
shared context is paid once, and in fleet mode the whole conversation
lands on the replica whose KV pages already hold it.  Re-collecting
evidence per turn would defeat both.
"""

from __future__ import annotations

import time
import uuid
from typing import Callable

from k8s_llm_monitor_tpu.devtools.lockcheck import guarded_by, make_lock

# Bound the replayed conversation so prompts can't grow without limit;
# older turns drop off while the pinned context stays.
MAX_TURNS = 8
MAX_ANSWER_CHARS = 800


class DiagnosisSession:
    def __init__(self, session_id: str, context: str,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.session_id = session_id
        self.context = context  # pinned — never mutated after creation
        self.turns: list[tuple[str, str]] = []
        self._clock = clock
        self.created_at = clock()
        self.last_used = clock()

    def build_prompt(self, preamble: str, question: str) -> str:
        """Prompt with the pinned context first, so its token prefix is
        byte-identical across every turn of the session."""
        self.last_used = self._clock()
        parts = [preamble, self.context]
        for q, a in self.turns[-MAX_TURNS:]:
            parts.append(f"## Question\n{q}\n## Answer\n{a}\n")
        parts.append(f"## Question\n{question}\n## Answer\n")
        return "".join(parts)

    def record(self, question: str, answer: str) -> None:
        self.turns.append((question, answer[:MAX_ANSWER_CHARS]))
        self.last_used = self._clock()


@guarded_by("_lock", "_sessions")
class SessionManager:
    """TTL + LRU-capped registry of pinned-context sessions.

    ``get_or_create(session_id, context_fn)`` returns the existing session
    (ignoring ``context_fn`` — the pin holds) or creates one with a fresh
    context; an empty id mints a new session.  Idle sessions past
    ``ttl_s`` are evicted lazily on access; beyond ``max_sessions`` the
    least-recently-used goes first.
    """

    def __init__(self, ttl_s: float = 600.0, max_sessions: int = 16,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.ttl_s = ttl_s
        self.max_sessions = max_sessions
        self._clock = clock
        self._sessions: dict[str, DiagnosisSession] = {}
        self._lock = make_lock("diagnosis.sessions")

    def _evict_locked(self) -> None:
        now = self._clock()
        stale = [sid for sid, s in self._sessions.items()
                 if now - s.last_used > self.ttl_s]
        for sid in stale:
            del self._sessions[sid]
        while len(self._sessions) > self.max_sessions:
            oldest = min(self._sessions.values(), key=lambda s: s.last_used)
            del self._sessions[oldest.session_id]

    def get_or_create(
        self, session_id: str,
        context_fn: Callable[[], str],
    ) -> tuple[DiagnosisSession, bool]:
        """Returns (session, created).  ``context_fn`` runs only on
        creation — and outside the lock, since evidence collection can be
        slow."""
        with self._lock:
            self._evict_locked()
            if session_id and session_id in self._sessions:
                return self._sessions[session_id], False
        context = context_fn()
        with self._lock:
            # Re-check: a concurrent request may have created it meanwhile;
            # first creation wins so both turns share one pinned prefix.
            if session_id and session_id in self._sessions:
                return self._sessions[session_id], False
            sid = session_id or uuid.uuid4().hex[:12]
            session = DiagnosisSession(sid, context, clock=self._clock)
            self._sessions[sid] = session
            self._evict_locked()
            return session, True

    def get(self, session_id: str) -> DiagnosisSession | None:
        with self._lock:
            self._evict_locked()
            return self._sessions.get(session_id)

    def __len__(self) -> int:
        with self._lock:
            return len(self._sessions)
