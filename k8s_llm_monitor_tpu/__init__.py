"""k8s_llm_monitor_tpu — a TPU-native Kubernetes intelligent-monitoring framework.

A from-scratch rebuild of the capability set of Sabre94/k8s-llm-monitor
(reference mounted read-only at /root/reference), designed TPU-first:

- ``monitor/``  — the Kubernetes control plane: cluster client (+ fake in-memory
  backend), watch machinery, metrics manager and sources, network analyzer with
  RTT probing, CRD-driven battery-aware scheduler, UAV telemetry stack, the
  HTTP API (serving ``web/``'s dashboards), and the Analysis Engine the
  reference only sketched (``monitor/analysis.py``: prompt assembly from
  cluster evidence, root-cause / pod-communication / anomaly analyzers, and
  the /api/v1/query NL endpoint backed by the local TPU engine).  Capability
  parity with the reference's Go code (see SURVEY.md §2), re-derived in Python.
- ``cmd/``      — executable entrypoints: server, uav_agent, scheduler,
  test_k8s, demo.
- ``models/``   — Llama-3 / Qwen2-family decoder LMs and a BGE-style embedding
  encoder, written as pure-functional JAX (pytree params, jit-compiled steps).
- ``ops/``      — TPU compute primitives: RoPE, RMSNorm, fused attention with a
  paged KV cache (Pallas kernel + XLA fallback), sampling.
- ``parallel/`` — device mesh construction and GSPMD sharding rules
  (DP/TP/SP/PP) for serving and training over ICI/DCN.
- ``serving/``  — the inference engine: paged KV-cache allocator, continuous
  batching scheduler, streaming generation API.
- ``training/`` — sharded train step (loss, grad, optax update) for
  fine-tuning the analysis models.
"""

__version__ = "0.1.0"
