"""Cluster data models — the JSON API contract.

Parity target: ``/root/reference/pkg/models/models.go`` (PodInfo …
UAVReport, models.go:10-192) and ``pkg/models/scheduler.go:6-38``. Field
names here ARE the wire names (the Go structs' json tags), so
``to_jsonable`` needs no renaming map. Timestamps serialize as RFC3339 UTC,
matching Go ``time.Time`` marshaling.
"""

from __future__ import annotations

import dataclasses
import re
from dataclasses import dataclass, field
from datetime import datetime, timezone
from typing import Any

# ---------------------------------------------------------------------------
# serialization helpers
# ---------------------------------------------------------------------------

EPOCH = datetime(1, 1, 1, tzinfo=timezone.utc)  # Go zero time


def utcnow() -> datetime:
    return datetime.now(timezone.utc)


def rfc3339(ts: datetime | None) -> str:
    """Format like Go time.Time JSON marshaling (RFC3339, Z suffix)."""
    if ts is None:
        ts = EPOCH
    if ts.tzinfo is None:
        ts = ts.replace(tzinfo=timezone.utc)
    ts = ts.astimezone(timezone.utc)
    if ts.microsecond:
        return ts.strftime("%Y-%m-%dT%H:%M:%S.%f").rstrip("0") + "Z"
    return ts.strftime("%Y-%m-%dT%H:%M:%SZ")


_FRACTION = re.compile(r"\.(\d+)")


def parse_rfc3339(s: str | None) -> datetime | None:
    if not s:
        return None
    s = s.replace("Z", "+00:00")
    # RFC3339 allows any fractional-second width (Go's marshaler strips
    # trailing zeros; k8s emits nanoseconds), but fromisoformat on
    # Python < 3.11 accepts only exactly 3 or 6 digits — normalize to 6.
    m = _FRACTION.search(s)
    if m:
        s = f"{s[:m.start()]}.{m.group(1)[:6]:0<6}{s[m.end():]}"
    try:
        return datetime.fromisoformat(s)
    except ValueError:
        return None


def to_jsonable(obj: Any) -> Any:
    """Dataclass tree → JSON-ready plain structures.

    Honors per-field ``metadata={"omitempty": True}`` the way Go's
    ``json:",omitempty"`` does (drop zero values).
    """
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out: dict[str, Any] = {}
        for f in dataclasses.fields(obj):
            val = getattr(obj, f.name)
            if f.metadata.get("omitempty") and not val:
                continue
            out[f.metadata.get("name", f.name)] = to_jsonable(val)
        return out
    if isinstance(obj, datetime):
        return rfc3339(obj)
    if isinstance(obj, dict):
        return {k: to_jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_jsonable(v) for v in obj]
    return obj


def omitempty() -> dict[str, bool]:
    return {"omitempty": True}


# ---------------------------------------------------------------------------
# core resource models (ref pkg/models/models.go:10-83)
# ---------------------------------------------------------------------------


@dataclass
class ContainerInfo:
    name: str = ""
    image: str = ""
    state: str = ""
    ready: bool = False
    env: dict[str, str] = field(default_factory=dict)


@dataclass
class PodInfo:
    name: str = ""
    namespace: str = ""
    status: str = ""
    node_name: str = ""
    ip: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    start_time: datetime = field(default_factory=utcnow)
    containers: list[ContainerInfo] = field(default_factory=list)


@dataclass
class ServicePort:
    name: str = ""
    port: int = 0
    protocol: str = "TCP"


@dataclass
class ServiceInfo:
    name: str = ""
    namespace: str = ""
    type: str = "ClusterIP"
    cluster_ip: str = ""
    ports: list[ServicePort] = field(default_factory=list)
    selector: dict[str, str] = field(default_factory=dict)


@dataclass
class EventInfo:
    type: str = ""
    reason: str = ""
    message: str = ""
    source: str = ""
    timestamp: datetime = field(default_factory=utcnow)
    count: int = 0


@dataclass
class PortRule:
    protocol: str = "TCP"
    port: int = 0


@dataclass
class PeerRule:
    pod_selector: dict[str, str] = field(default_factory=dict)
    namespace_selector: dict[str, str] = field(default_factory=dict)


@dataclass
class NetworkPolicyRule:
    ports: list[PortRule] = field(default_factory=list)
    # 'from' is a Python keyword; the metadata name restores the wire key.
    from_: list[PeerRule] = field(default_factory=list, metadata={"name": "from"})
    to: list[PeerRule] = field(default_factory=list)


@dataclass
class NetworkPolicyInfo:
    name: str = ""
    namespace: str = ""
    pod_selector: dict[str, str] = field(default_factory=dict)
    ingress: list[NetworkPolicyRule] = field(default_factory=list)
    egress: list[NetworkPolicyRule] = field(default_factory=list)


# ---------------------------------------------------------------------------
# analysis models (ref pkg/models/models.go:85-118)
# ---------------------------------------------------------------------------

ANALYSIS_TYPES = ("pod_communication", "anomaly_detection", "root_cause")


@dataclass
class AnalysisRequest:
    type: str = ""
    parameters: dict[str, Any] = field(default_factory=dict)
    context: dict[str, Any] = field(default_factory=dict)


@dataclass
class AnalysisResponse:
    request_id: str = ""
    status: str = ""  # success | error | processing
    result: dict[str, Any] = field(default_factory=dict)
    error: str = field(default="", metadata=omitempty())
    # "validation" (caller's request is bad) vs "internal" (server-side
    # failure) — lets the API layer pick 4xx vs 5xx correctly
    error_kind: str = field(default="", metadata=omitempty())
    timestamp: datetime = field(default_factory=utcnow)


@dataclass
class CommunicationAnalysis:
    pod_a: str = ""
    pod_b: str = ""
    status: str = "unknown"  # connected | disconnected | unknown
    issues: list[str] = field(default_factory=list)
    solutions: list[str] = field(default_factory=list)
    confidence: float = 0.0


@dataclass
class SystemHealth:
    overall_health: str = ""
    components: dict[str, Any] = field(default_factory=dict)
    issues: list[str] = field(default_factory=list)
    suggestions: list[str] = field(default_factory=list)
    last_update: datetime = field(default_factory=utcnow)


# ---------------------------------------------------------------------------
# CRD models (ref pkg/models/models.go:120-158)
# ---------------------------------------------------------------------------


@dataclass
class CRDInfo:
    name: str = ""
    group: str = ""
    kind: str = ""
    scope: str = "Namespaced"  # Cluster | Namespaced
    versions: list[str] = field(default_factory=list)
    plural: str = ""
    singular: str = ""
    established: bool = False
    stored: bool = False
    creation_time: datetime = field(default_factory=utcnow)


@dataclass
class CustomResourceInfo:
    kind: str = ""
    name: str = ""
    namespace: str = ""
    group: str = ""
    version: str = ""
    spec: dict[str, Any] = field(default_factory=dict)
    status: dict[str, Any] = field(default_factory=dict)
    generation: int = 0
    creation_time: datetime = field(default_factory=utcnow)
    update_time: datetime = field(default_factory=utcnow)


@dataclass
class CRDEvent:
    type: str = ""  # Added | Modified | Deleted
    kind: str = ""
    group: str = ""
    version: str = ""
    name: str = ""
    namespace: str = ""
    object: dict[str, Any] = field(default_factory=dict)
    timestamp: datetime = field(default_factory=utcnow)


# ---------------------------------------------------------------------------
# network test models (ref pkg/models/models.go:160-179)
# ---------------------------------------------------------------------------


@dataclass
class RTTResult:
    success: bool = False
    rtt_ms: float = 0.0
    packet_loss: float = 0.0
    error_message: str = ""
    timestamp: datetime = field(default_factory=utcnow)
    method: str = ""  # ping | http | ...


@dataclass
class NetworkTestResult:
    pod_a: str = ""
    pod_b: str = ""
    rtt_results: list[RTTResult] = field(default_factory=list)
    average_rtt_ms: float = 0.0
    success_rate: float = 0.0
    test_count: int = 0
    latency_assessment: str = ""  # excellent | good | fair | poor | very_poor


# ---------------------------------------------------------------------------
# UAV report (ref pkg/models/models.go:181-192); state payload in uav.py
# ---------------------------------------------------------------------------


@dataclass
class UAVReport:
    node_name: str = ""
    node_ip: str = field(default="", metadata=omitempty())
    uav_id: str = ""
    source: str = ""
    status: str = ""
    timestamp: datetime = field(default_factory=utcnow)
    heartbeat_interval_seconds: int = field(default=0, metadata=omitempty())
    state: Any = field(default=None, metadata=omitempty())  # UAVState | dict
    metadata: dict[str, str] = field(default_factory=dict, metadata=omitempty())


# ---------------------------------------------------------------------------
# scheduler models (ref pkg/models/scheduler.go:6-38)
# ---------------------------------------------------------------------------


@dataclass
class SchedulingWorkload:
    name: str = ""
    namespace: str = ""
    type: str = field(default="", metadata=omitempty())


@dataclass
class SchedulingRequestSpec:
    workload: SchedulingWorkload = field(default_factory=SchedulingWorkload)
    minBatteryPercent: float = field(default=0.0, metadata=omitempty())
    preferredNodes: list[str] = field(default_factory=list, metadata=omitempty())
    annotations: dict[str, str] = field(default_factory=dict, metadata=omitempty())


@dataclass
class SchedulingRequestStatus:
    phase: str = field(default="", metadata=omitempty())
    assignedNode: str = field(default="", metadata=omitempty())
    assignedUAV: str = field(default="", metadata=omitempty())
    score: float = field(default=0.0, metadata=omitempty())
    message: str = field(default="", metadata=omitempty())
    lastUpdated: datetime | None = field(default=None, metadata=omitempty())


@dataclass
class SchedulingCandidate:
    node_name: str = ""
    uav_id: str = ""
    battery: float = 0.0
    last_heartbeat: datetime | None = None
    score: float = 0.0
