"""Metrics manager — concurrent collection + double-buffered snapshot.

Parity target: ``/root/reference/internal/metrics/manager.go`` — source
wiring per config (:61-134), start/ticker loop (:137-179), fan-out collect
with per-source threads and snapshot swap under a lock (:195-334), error
policy (node/pod errors propagate, network errors log-only, :322-331),
pull-side UAV wrapping with ``source:"pull"`` (:265-278), push ingestion
``update_uav_report`` (:391-449), read API (:337-388, :452-490), and the
cluster rollup with the reference's exact thresholds (:493-565).
"""

from __future__ import annotations

import logging
import threading
import time
from datetime import datetime
from typing import Any

from k8s_llm_monitor_tpu.devtools.lockcheck import guarded_by, make_lock
from k8s_llm_monitor_tpu.monitor.client import Client
from k8s_llm_monitor_tpu.monitor.config import MetricsConfig
from k8s_llm_monitor_tpu.monitor.metrics_types import (
    ClusterMetrics,
    MetricsSnapshot,
    NetworkMetrics,
    NodeMetrics,
    PodMetrics,
)
from k8s_llm_monitor_tpu.monitor.models import utcnow
from k8s_llm_monitor_tpu.monitor.sources import (
    NetworkMetricsSource,
    NodeMetricsSource,
    PodMetricsSource,
    StateFetcher,
    UAVMetricsSource,
)

logger = logging.getLogger("monitor.manager")


def _aware(ts: datetime) -> datetime:
    """Treat naive timestamps (agent clocks without an offset) as UTC."""
    from datetime import timezone

    return ts if ts.tzinfo is not None else ts.replace(tzinfo=timezone.utc)


class CollectError(Exception):
    pass


@guarded_by("_lock", "_snapshot", "_uav_snapshot",
            "collect_count", "last_collect_duration")
class Manager:
    """Owns the sources and the latest ``MetricsSnapshot``."""

    def __init__(
        self,
        client: Client,
        cfg: MetricsConfig | None = None,
        uav_fetcher: StateFetcher | None = None,
    ) -> None:
        cfg = cfg or MetricsConfig()
        self.cfg = cfg
        self.client = client
        namespaces = list(cfg.namespaces)

        self.node_source = NodeMetricsSource(client) if cfg.enable_node else None
        self.pod_source = (
            PodMetricsSource(client, namespaces) if cfg.enable_pod else None
        )
        self.network_source = (
            NetworkMetricsSource(
                client,
                namespaces,
                max_pairs=cfg.max_pod_pairs,
                timeout=cfg.network_timeout,
            )
            if cfg.enable_network
            else None
        )
        # UAV collector targets the first configured namespace with the
        # hardcoded agent label, like ref manager.go:121-129
        self.uav_source = (
            UAVMetricsSource(
                client,
                namespace=namespaces[0] if namespaces else "default",
                fetcher=uav_fetcher,
            )
            if cfg.enable_uav
            else None
        )

        self._snapshot = MetricsSnapshot(cluster_metrics=ClusterMetrics())
        self._uav_snapshot: dict[str, dict[str, Any]] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.collect_count = 0
        self.last_collect_duration = 0.0
        # Created last: lockcheck's guarded_by treats writes before the
        # lock exists as construction, not races.
        self._lock = make_lock("manager.snapshot", reentrant=True)

    # -- lifecycle (ref manager.go:137-192) ------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            if self._thread.is_alive():
                return
            self._thread = None  # previous loop finished after a timed-out stop
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="metrics-manager", daemon=True
        )
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            if self._thread.is_alive():
                # a probe is blocking collect(); keep the handle so a later
                # start() can't spawn a second concurrent loop
                logger.warning("metrics loop still busy after %.1fs stop", timeout)
                return
            self._thread = None

    def _loop(self) -> None:
        # immediate collect, then ticker (ref manager.go:141-179)
        try:
            self.collect()
        except Exception as exc:  # noqa: BLE001 — the loop must survive anything
            logger.exception("initial metrics collection failed: %s", exc)
        while not self._stop.wait(self.cfg.collect_interval):
            try:
                self.collect()
            except Exception as exc:  # noqa: BLE001
                logger.exception("metrics collection failed: %s", exc)

    # -- collection (ref manager.go:195-334) -------------------------------------

    def collect(self) -> MetricsSnapshot:
        start = time.monotonic()
        now = utcnow()
        snapshot = MetricsSnapshot(
            timestamp=now, cluster_metrics=ClusterMetrics(timestamp=now)
        )
        errors: dict[str, Exception] = {}
        uav_raw: dict[str, dict[str, Any]] | None = None

        def run_node() -> None:
            try:
                snapshot.node_metrics = self.node_source.collect()
            except Exception as exc:  # noqa: BLE001 — error policy is per-source
                errors["node"] = exc

        def run_pod() -> None:
            try:
                snapshot.pod_metrics = self.pod_source.collect()
            except Exception as exc:  # noqa: BLE001
                errors["pod"] = exc

        def run_network() -> None:
            try:
                snapshot.network_metrics = self.network_source.collect()
            except Exception as exc:  # noqa: BLE001
                errors["network"] = exc

        def run_uav() -> None:
            nonlocal uav_raw
            try:
                uav_raw = self.uav_source.collect()
            except Exception as exc:  # noqa: BLE001
                errors["uav"] = exc

        jobs = []
        if self.node_source:
            jobs.append(threading.Thread(target=run_node, daemon=True))
        if self.pod_source:
            jobs.append(threading.Thread(target=run_pod, daemon=True))
        if self.network_source:
            jobs.append(threading.Thread(target=run_network, daemon=True))
        if self.uav_source:
            jobs.append(threading.Thread(target=run_uav, daemon=True))
        for t in jobs:
            t.start()
        for t in jobs:
            t.join()

        self._calculate_cluster_metrics(snapshot)

        # pull-side UAV entries wrapped with source:"pull" (ref :265-278)
        uav_entries: dict[str, dict[str, Any]] | None = None
        if uav_raw is not None:
            uav_entries = {
                node: {
                    "node_name": node,
                    "status": "active",
                    "source": "pull",
                    "timestamp": now,
                    "last_heartbeat": now,
                    "state": state,
                }
                for node, state in uav_raw.items()
            }

        with self._lock:
            self._snapshot = snapshot
            if uav_entries is not None:
                # Rebuild from this cycle's pull results (the reference
                # replaces the snapshot wholesale, which self-prunes removed
                # nodes), then retain push-side ("agent") entries whose
                # heartbeat is still fresh — pushes carry richer state. An
                # entry advertising its own heartbeat interval widens the
                # window so slow pushers don't flap between shapes.
                merged = dict(uav_entries)
                for node, existing in self._uav_snapshot.items():
                    hb = existing.get("last_heartbeat")
                    interval = existing.get("heartbeat_interval_seconds", 0) or 0
                    fresh_window = max(
                        interval * 2, self.cfg.collect_interval * 2, 30
                    )
                    if (
                        existing.get("source") == "agent"
                        and isinstance(hb, datetime)
                        and (now - _aware(hb)).total_seconds() < fresh_window
                    ):
                        merged[node] = existing
                self._uav_snapshot = merged
            # Counters live under the same lock as the snapshot: status
            # readers report (snapshot, collect_count, duration) as one
            # consistent triple.  Writing them outside the lock raced the
            # readers — lockcheck's guarded_by caught this.
            self.last_collect_duration = time.monotonic() - start
            self.collect_count += 1
        logger.info(
            "metrics collection completed in %.2fs (nodes: %d, pods: %d, network: %d, uavs: %d)",
            self.last_collect_duration,
            len(snapshot.node_metrics),
            len(snapshot.pod_metrics),
            len(snapshot.network_metrics),
            len(uav_raw or {}),
        )

        # error policy (ref manager.go:322-331)
        if "node" in errors:
            raise CollectError(f"node metrics: {errors['node']}")
        if "pod" in errors:
            raise CollectError(f"pod metrics: {errors['pod']}")
        if "network" in errors:
            logger.warning("network metrics collection had errors: %s", errors["network"])
        if "uav" in errors:
            logger.warning("uav metrics collection had errors: %s", errors["uav"])
        return snapshot

    # -- push ingestion (ref manager.go:391-449) ---------------------------------

    def update_uav_report(self, report) -> None:
        if report is None or not report.node_name:
            return
        ts = _aware(report.timestamp) if report.timestamp else utcnow()
        entry: dict[str, Any] = {
            "node_name": report.node_name,
            "uav_id": report.uav_id,
            "status": report.status or "active",
            "source": report.source or "agent",
            "timestamp": ts,
            "last_heartbeat": ts,
        }
        if report.node_ip:
            entry["node_ip"] = report.node_ip
        if report.heartbeat_interval_seconds > 0:
            entry["heartbeat_interval_seconds"] = report.heartbeat_interval_seconds
        if report.metadata:
            entry["metadata"] = dict(report.metadata)
        if report.state is not None:
            entry["state"] = report.state
        with self._lock:
            self._uav_snapshot[report.node_name] = entry
        logger.debug(
            "UAV report ingested: node=%s uav=%s status=%s",
            report.node_name,
            report.uav_id,
            entry["status"],
        )

    # -- read API (ref manager.go:337-388, 452-490) -------------------------------

    def get_latest_snapshot(self) -> MetricsSnapshot:
        with self._lock:
            return self._snapshot

    def get_node_metrics(self, node_name: str) -> NodeMetrics:
        with self._lock:
            node = self._snapshot.node_metrics.get(node_name)
        if node is None:
            raise KeyError(f"node {node_name} not found in snapshot")
        return node

    def get_pod_metrics(self, namespace: str, name: str) -> PodMetrics:
        with self._lock:
            pod = self._snapshot.pod_metrics.get(f"{namespace}/{name}")
        if pod is None:
            raise KeyError(f"pod {namespace}/{name} not found in snapshot")
        return pod

    def get_cluster_metrics(self) -> ClusterMetrics:
        with self._lock:
            return self._snapshot.cluster_metrics or ClusterMetrics()

    def get_network_metrics(self) -> list[NetworkMetrics]:
        with self._lock:
            return list(self._snapshot.network_metrics)

    def get_uav_metrics(self) -> dict[str, dict[str, Any]]:
        with self._lock:
            return dict(self._uav_snapshot)

    def get_single_uav_metrics(self, node_name: str) -> dict[str, Any] | None:
        with self._lock:
            entry = self._uav_snapshot.get(node_name)
            return dict(entry) if entry is not None else None

    def send_uav_command(
        self, node: str, command: str, params: dict[str, Any] | None = None
    ) -> dict[str, Any]:
        """Push a flight command to a node's UAV agent (ref SendCommandToUAV,
        uav_metrics.go:236-287).  Requires the UAV source to be enabled."""
        if self.uav_source is None:
            raise ValueError("UAV metrics source is disabled")
        return self.uav_source.send_command(node, command, params)

    def uav_heartbeats(self) -> dict[str, datetime]:
        """Derived from the snapshot entries — single source of truth."""
        with self._lock:
            return {
                node: _aware(e["last_heartbeat"])
                for node, e in self._uav_snapshot.items()
                if isinstance(e.get("last_heartbeat"), datetime)
            }

    def test_pod_communication(self, pod_a: str, pod_b: str) -> NetworkMetrics:
        """On-demand single-pair probe (ref network_metrics.go:292-325)."""
        source = self.network_source or NetworkMetricsSource(
            self.client, self.cfg.namespaces
        )
        return source.test_pair(pod_a, pod_b)

    # -- cluster rollup (ref manager.go:493-565) ----------------------------------

    def _calculate_cluster_metrics(self, snapshot: MetricsSnapshot) -> None:
        cluster = snapshot.cluster_metrics
        nodes = snapshot.node_metrics.values()
        pods = snapshot.pod_metrics.values()

        cluster.total_nodes = len(snapshot.node_metrics)
        cluster.healthy_nodes = sum(1 for n in nodes if n.healthy)
        cluster.total_pods = len(snapshot.pod_metrics)
        cluster.running_pods = sum(1 for p in pods if p.phase == "Running")

        cluster.total_cpu = sum(n.cpu_capacity for n in nodes)
        cluster.used_cpu = sum(n.cpu_usage for n in nodes)
        cluster.total_memory = sum(n.memory_capacity for n in nodes)
        cluster.used_memory = sum(n.memory_usage for n in nodes)
        cluster.total_gpus = sum(n.gpu_count for n in nodes)
        # "available" accelerator = usage < 50% (ref manager.go:529-535)
        cluster.available_gpus = sum(
            1 for n in nodes for u in n.gpu_usage if u < 50.0
        )

        if cluster.total_cpu > 0:
            cluster.cpu_usage_rate = cluster.used_cpu / cluster.total_cpu * 100.0
        if cluster.total_memory > 0:
            cluster.memory_usage_rate = (
                cluster.used_memory / cluster.total_memory * 100.0
            )

        cluster.issues = []
        if cluster.healthy_nodes < cluster.total_nodes:
            cluster.issues.append(
                f"{cluster.total_nodes - cluster.healthy_nodes} nodes are unhealthy"
            )
        if cluster.cpu_usage_rate > 80:
            cluster.issues.append(f"High CPU usage: {cluster.cpu_usage_rate:.1f}%")
        if cluster.memory_usage_rate > 80:
            cluster.issues.append(
                f"High memory usage: {cluster.memory_usage_rate:.1f}%"
            )

        if not cluster.issues:
            cluster.health_status = "healthy"
        elif (
            cluster.cpu_usage_rate > 90
            or cluster.memory_usage_rate > 90
            or cluster.healthy_nodes < cluster.total_nodes / 2
        ):
            cluster.health_status = "critical"
        else:
            cluster.health_status = "warning"
