"""In-pod exec RTT probes (ping + HTTP timing).

Parity target: ``/root/reference/internal/k8s/rtt_tester.go`` —
bidirectional ping (:73-91), conditional HTTP timing for HTTP-looking
targets (:94-105, :300-320), in-pod command execution over the exec
subresource (:170-216; here: ``ClusterBackend.exec_in_pod``), output
parsing (:219-297) and stats/latency grading (:323-369).
"""

from __future__ import annotations

import logging
import statistics

from k8s_llm_monitor_tpu.monitor.client import Client
from k8s_llm_monitor_tpu.monitor.cluster import ClusterError
from k8s_llm_monitor_tpu.monitor.models import (
    NetworkTestResult,
    PodInfo,
    RTTResult,
    utcnow,
)

logger = logging.getLogger("monitor.rtt")

PING_COUNT = 3
PING_TIMEOUT_S = 5
HTTP_TIMEOUT_S = 5
HTTP_APP_HINTS = ("nginx", "httpd", "apache", "web")


def parse_pod_ref(pod_ref: str) -> tuple[str, str]:
    """'ns/name' → (ns, name); bare name → ('default', name).

    ref network.go:85-91 parsePodName.
    """
    parts = pod_ref.split("/")
    if len(parts) == 2:
        return parts[0], parts[1]
    return "default", parts[0]


def parse_ping_output(output: str) -> tuple[float, int, float]:
    """(rtt average ms, sample count, packet loss %) from ping stdout.

    Per-line ``time=`` extraction + packet-loss line scan, matching ref
    rtt_tester.go:219-297.
    """
    rtts: list[float] = []
    loss = 0.0
    for line in output.splitlines():
        if "time=" in line and "ms" in line:
            try:
                token = line.split("time=")[1].split()[0]
                rtts.append(float(token.removesuffix("ms")))
            except (IndexError, ValueError):
                pass
        if "packet loss" in line:
            for part in line.split():
                if "%" in part:
                    try:
                        loss = float(part.rstrip("%,"))
                    except ValueError:
                        pass
    avg = statistics.fmean(rtts) if rtts else 0.0
    return avg, len(rtts), loss


def is_http_service(pod: PodInfo) -> bool:
    """Label/image heuristic from ref rtt_tester.go:300-320."""
    app = pod.labels.get("app", "").lower()
    if any(h in app for h in HTTP_APP_HINTS):
        return True
    for c in pod.containers:
        img = c.image.lower()
        if "nginx" in img or "httpd" in img:
            return True
    return False


def assess_latency(rtt_ms: float) -> str:
    """Grading bands from ref rtt_tester.go:354-369."""
    if rtt_ms == 0:
        return "unknown"
    if rtt_ms < 1:
        return "excellent"
    if rtt_ms < 5:
        return "good"
    if rtt_ms < 50:
        return "fair"
    if rtt_ms < 100:
        return "poor"
    return "very_poor"


class RTTTester:
    """Active probes executed inside the source pod via the backend exec seam."""

    def __init__(self, client: Client) -> None:
        self.client = client

    def test_pod_connectivity(self, pod_a: str, pod_b: str) -> NetworkTestResult:
        ns_a, name_a = parse_pod_ref(pod_a)
        ns_b, name_b = parse_pod_ref(pod_b)
        info_a = self.client.get_pod(ns_a, name_a)
        info_b = self.client.get_pod(ns_b, name_b)

        result = NetworkTestResult(pod_a=pod_a, pod_b=pod_b)

        # bidirectional ping (ref rtt_tester.go:73-91)
        if info_b.ip:
            r = self._ping_from_pod(info_a, info_b.ip)
            r.method = "ping"
            result.rtt_results.append(r)
            result.test_count += 1
        if info_a.ip:
            r = self._ping_from_pod(info_b, info_a.ip)
            r.method = "ping_reverse"
            result.rtt_results.append(r)
            result.test_count += 1

        # HTTP timing when the target looks like an HTTP service
        if is_http_service(info_b) and info_b.ip:
            r = self._http_from_pod(info_a, info_b.ip, 80)
            r.method = "http"
            result.rtt_results.append(r)
            result.test_count += 1

        self._calculate_stats(result)
        return result

    # -- probes ---------------------------------------------------------------

    def _ping_from_pod(self, pod: PodInfo, target_ip: str) -> RTTResult:
        result = RTTResult(timestamp=utcnow(), method="ping")
        cmd = ["ping", "-c", str(PING_COUNT), "-W", str(PING_TIMEOUT_S), target_ip]
        try:
            stdout, stderr, rc = self.client.exec_in_pod(
                pod.namespace, pod.name, cmd, timeout=PING_TIMEOUT_S * PING_COUNT
            )
        except ClusterError as exc:
            result.error_message = f"ping exec failed: {exc}"
            logger.error("ping from %s to %s failed: %s", pod.name, target_ip, exc)
            return result
        if rc != 0 and not stdout:
            result.error_message = stderr.strip() or f"ping exited {rc}"
            return result
        rtt, count, loss = parse_ping_output(stdout)
        if count > 0:
            result.rtt_ms = rtt
            result.success = True
        result.packet_loss = loss
        return result

    def _http_from_pod(self, pod: PodInfo, target_ip: str, port: int) -> RTTResult:
        result = RTTResult(timestamp=utcnow(), method="http")
        cmd = [
            "curl",
            "-s",
            "-o",
            "/dev/null",
            "-w",
            "%{time_total}",
            "-m",
            str(HTTP_TIMEOUT_S),
            f"http://{target_ip}:{port}",
        ]
        try:
            stdout, stderr, rc = self.client.exec_in_pod(
                pod.namespace, pod.name, cmd, timeout=HTTP_TIMEOUT_S + 2
            )
        except ClusterError as exc:
            result.error_message = f"http exec failed: {exc}"
            logger.error("curl from %s to %s failed: %s", pod.name, target_ip, exc)
            return result
        if rc != 0:
            # curl prints %{time_total} even on failure (e.g. connection
            # refused) — a nonzero exit must not count as a timed success
            result.error_message = stderr.strip() or f"curl exited {rc}"
            return result
        try:
            # curl -w time_total prints seconds (ref rtt_tester.go:253-264)
            result.rtt_ms = float(stdout.strip()) * 1000.0
            result.success = True
        except ValueError:
            result.error_message = stderr.strip() or f"unparseable curl output {stdout!r}"
        return result

    # -- stats (ref rtt_tester.go:323-351) -------------------------------------

    def _calculate_stats(self, result: NetworkTestResult) -> None:
        if not result.rtt_results:
            result.latency_assessment = "unknown"
            return
        successes = [r for r in result.rtt_results if r.success]
        if successes:
            result.average_rtt_ms = statistics.fmean(r.rtt_ms for r in successes)
            result.success_rate = len(successes) / len(result.rtt_results) * 100.0
        result.latency_assessment = assess_latency(result.average_rtt_ms)
