"""High-level cluster client + wire-shape converters.

Parity target: ``/root/reference/internal/k8s/client.go:35-480`` (read
APIs, CRD upsert) and ``internal/k8s/converter.go:13-111`` (raw object →
model conversion, incl. the non-secret env extraction at converter.go:37-41
and container-state naming at :85-111). The client is backend-agnostic:
pass a ``FakeCluster`` for tests/dev mode or a ``KubeRestBackend`` for a
real cluster.
"""

from __future__ import annotations

import logging
from typing import Any

from k8s_llm_monitor_tpu.monitor.cluster import (
    ClusterBackend,
    ClusterError,
    NotFound,
    WatchStream,
)
from k8s_llm_monitor_tpu.monitor.models import (
    ContainerInfo,
    CustomResourceInfo,
    EventInfo,
    NetworkPolicyInfo,
    NetworkPolicyRule,
    PeerRule,
    PodInfo,
    PortRule,
    ServiceInfo,
    ServicePort,
    UAVReport,
    parse_rfc3339,
    rfc3339,
    utcnow,
)

logger = logging.getLogger("monitor.client")

UAV_METRICS_GVR = ("monitoring.io", "v1", "uavmetrics")
SCHEDULING_GVR = ("scheduler.io", "v1", "schedulingrequests")


# ---------------------------------------------------------------------------
# converters (ref internal/k8s/converter.go)
# ---------------------------------------------------------------------------


def container_state_name(status: dict[str, Any]) -> str:
    """running/waiting:<reason>/terminated:<reason> (ref converter.go:85-111)."""
    state = status.get("state", {})
    if "running" in state:
        return "running"
    if "waiting" in state:
        reason = state["waiting"].get("reason", "")
        return f"waiting:{reason}" if reason else "waiting"
    if "terminated" in state:
        reason = state["terminated"].get("reason", "")
        return f"terminated:{reason}" if reason else "terminated"
    return "unknown"


_SECRET_HINTS = ("PASSWORD", "SECRET", "TOKEN", "KEY", "CREDENTIAL")


def convert_pod(raw: dict[str, Any]) -> PodInfo:
    md = raw.get("metadata", {})
    spec = raw.get("spec", {})
    status = raw.get("status", {})
    statuses = {s.get("name"): s for s in status.get("containerStatuses", [])}
    containers = []
    for c in spec.get("containers", []):
        st = statuses.get(c.get("name"), {})
        env = {}
        for e in c.get("env", []):
            name = e.get("name", "")
            # skip secret-looking and valueFrom-only env (ref converter.go:37-41)
            if "value" not in e:
                continue
            if any(h in name.upper() for h in _SECRET_HINTS):
                continue
            env[name] = e.get("value", "")
        containers.append(
            ContainerInfo(
                name=c.get("name", ""),
                image=c.get("image", ""),
                state=container_state_name(st),
                ready=bool(st.get("ready", False)),
                env=env,
            )
        )
    return PodInfo(
        name=md.get("name", ""),
        namespace=md.get("namespace", ""),
        status=status.get("phase", ""),
        node_name=spec.get("nodeName", ""),
        ip=status.get("podIP", ""),
        labels=dict(md.get("labels", {}) or {}),
        start_time=parse_rfc3339(status.get("startTime")) or utcnow(),
        containers=containers,
    )


def convert_service(raw: dict[str, Any]) -> ServiceInfo:
    md = raw.get("metadata", {})
    spec = raw.get("spec", {})
    return ServiceInfo(
        name=md.get("name", ""),
        namespace=md.get("namespace", ""),
        type=spec.get("type", "ClusterIP"),
        cluster_ip=spec.get("clusterIP", ""),
        ports=[
            ServicePort(
                name=p.get("name", ""),
                port=int(p.get("port", 0)),
                protocol=p.get("protocol", "TCP"),
            )
            for p in spec.get("ports", [])
        ],
        selector=dict(spec.get("selector", {}) or {}),
    )


def convert_event(raw: dict[str, Any]) -> EventInfo:
    ts = (
        raw.get("lastTimestamp")
        or raw.get("eventTime")
        or raw.get("metadata", {}).get("creationTimestamp")
    )
    return EventInfo(
        type=raw.get("type", ""),
        reason=raw.get("reason", ""),
        message=raw.get("message", ""),
        source=raw.get("source", {}).get("component", ""),
        timestamp=parse_rfc3339(ts) or utcnow(),
        count=int(raw.get("count", 1) or 1),
    )


def convert_network_policy(raw: dict[str, Any]) -> NetworkPolicyInfo:
    md = raw.get("metadata", {})
    spec = raw.get("spec", {})

    def peers(items: list[dict]) -> list[PeerRule]:
        return [
            PeerRule(
                pod_selector=dict(
                    (p.get("podSelector") or {}).get("matchLabels", {}) or {}
                ),
                namespace_selector=dict(
                    (p.get("namespaceSelector") or {}).get("matchLabels", {}) or {}
                ),
            )
            for p in items
        ]

    def rules(items: list[dict], peer_key: str) -> list[NetworkPolicyRule]:
        out = []
        for r in items or []:
            rule = NetworkPolicyRule(
                ports=[
                    PortRule(
                        protocol=p.get("protocol", "TCP"), port=int(p.get("port", 0))
                    )
                    for p in r.get("ports", [])
                ]
            )
            if peer_key == "from":
                rule.from_ = peers(r.get("from", []))
            else:
                rule.to = peers(r.get("to", []))
            out.append(rule)
        return out

    return NetworkPolicyInfo(
        name=md.get("name", ""),
        namespace=md.get("namespace", ""),
        pod_selector=dict(
            (spec.get("podSelector") or {}).get("matchLabels", {}) or {}
        ),
        ingress=rules(spec.get("ingress", []), "from"),
        egress=rules(spec.get("egress", []), "to"),
    )


def convert_custom_resource(
    raw: dict[str, Any], group: str, kind: str
) -> CustomResourceInfo:
    """ref client.go convertUnstructuredToModel + getLastUpdateTime."""
    md = raw.get("metadata", {})
    managed = md.get("managedFields") or []
    update_ts = None
    if managed and managed[0].get("time"):
        update_ts = parse_rfc3339(managed[0]["time"])
    creation = parse_rfc3339(md.get("creationTimestamp")) or utcnow()
    return CustomResourceInfo(
        kind=kind,
        name=md.get("name", ""),
        namespace=md.get("namespace", ""),
        group=group,
        version=raw.get("apiVersion", ""),
        spec=dict(raw.get("spec", {}) or {}),
        status=dict(raw.get("status", {}) or {}),
        generation=int(md.get("generation", 0) or 0),
        creation_time=creation,
        update_time=update_ts or creation,
    )


def sanitize_resource_name(name: str) -> str:
    """ref client.go:452-461."""
    name = name.lower().replace("_", "-").replace(".", "-").strip()
    return name or "unknown"


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class Client:
    """Cluster client over a ``ClusterBackend``.

    Mirrors the reference Client's read API (client.go:103-247) and the
    UAVMetric CRD surface (client.go:255-450).
    """

    def __init__(
        self,
        backend: ClusterBackend,
        namespaces: list[str] | None = None,
        default_namespace: str = "default",
    ) -> None:
        self.backend = backend
        self._namespaces = list(namespaces or [default_namespace])
        self.default_namespace = default_namespace

    # -- basic reads ---------------------------------------------------------

    def namespaces(self) -> list[str]:
        return list(self._namespaces)

    def test_connection(self) -> str:
        version = self.backend.server_version()
        logger.info("Connected to Kubernetes cluster: %s", version)
        return version

    def get_cluster_info(self) -> dict[str, Any]:
        """ref client.go:115-150 — version, node count, pod count, namespaces."""
        version = self.backend.server_version()
        nodes = self.backend.list_nodes()
        pod_count = 0
        for ns in self._namespaces:
            try:
                pod_count += len(self.backend.list_pods(ns))
            except ClusterError as exc:
                logger.warning("Failed to list pods in namespace %s: %s", ns, exc)
        return {
            "version": version,
            "nodes": len(nodes),
            "pods": pod_count,
            "namespaces": list(self._namespaces),
        }

    def get_pods(self, namespace: str) -> list[PodInfo]:
        return [convert_pod(p) for p in self.backend.list_pods(namespace)]

    def get_pod(self, namespace: str, name: str) -> PodInfo:
        for p in self.backend.list_pods(namespace):
            if p.get("metadata", {}).get("name") == name:
                return convert_pod(p)
        raise NotFound(f"pod {namespace}/{name} not found")

    def get_services(self, namespace: str) -> list[ServiceInfo]:
        return [convert_service(s) for s in self.backend.list_services(namespace)]

    def get_events(self, namespace: str, limit: int = 50) -> list[EventInfo]:
        return [
            convert_event(e) for e in self.backend.list_events(namespace, limit=limit)
        ]

    def get_network_policies(self, namespace: str) -> list[NetworkPolicyInfo]:
        return [
            convert_network_policy(p)
            for p in self.backend.list_network_policies(namespace)
        ]

    def get_pod_logs(self, namespace: str, name: str, tail_lines: int = 100) -> str:
        return self.backend.pod_logs(namespace, name, tail_lines=tail_lines)

    def exec_in_pod(
        self, namespace: str, pod: str, command: list[str], timeout: float = 10.0
    ) -> tuple[str, str, int]:
        return self.backend.exec_in_pod(namespace, pod, command, timeout=timeout)

    # -- UAVMetric CRD surface (ref client.go:255-450) ------------------------

    def list_uav_metrics_crd(self, namespace: str = "") -> list[CustomResourceInfo]:
        group, version, plural = UAV_METRICS_GVR
        items = self.backend.list_custom_resources(
            group, version, plural, namespace or None
        )
        return [convert_custom_resource(o, group, "UAVMetric") for o in items]

    def upsert_uav_metric(self, namespace: str, report: UAVReport) -> None:
        """Get-then-create-or-update of ``uavmetric-<node>``.

        Spec/status/label layout matches ref client.go:316-450 so the CRD
        contract (and the scheduler reading it) is wire-compatible.
        """
        if report is None:
            raise ValueError("uav report is None")
        if not report.node_name:
            raise ValueError("uav report missing node name")
        namespace = namespace or self.default_namespace
        group, version, plural = UAV_METRICS_GVR
        name = f"uavmetric-{sanitize_resource_name(report.node_name)}"

        spec: dict[str, Any] = {
            "node_name": report.node_name,
            "uav_id": report.uav_id,
        }
        state = report.state
        if state is not None:
            get = (
                (lambda blk, k, d=0: getattr(getattr(state, blk), k, d))
                if not isinstance(state, dict)
                else (lambda blk, k, d=0: (state.get(blk) or {}).get(k, d))
            )
            spec["gps"] = {
                "latitude": get("gps", "latitude", 0.0),
                "longitude": get("gps", "longitude", 0.0),
                "altitude": get("gps", "altitude", 0.0),
                "relative_altitude": get("gps", "relative_altitude", 0.0),
                "satellite_count": get("gps", "satellite_count", 0),
                "fix_type": get("gps", "fix_type", 0),
            }
            spec["battery"] = {
                "voltage": get("battery", "voltage", 0.0),
                "remaining_percent": get("battery", "remaining_percent", 0.0),
                "remaining_capacity": get("battery", "remaining_capacity", 0.0),
                "temperature": get("battery", "temperature", 0.0),
            }
            spec["flight"] = {
                "mode": get("flight", "mode", ""),
                "armed": get("flight", "armed", False),
                "ground_speed": get("flight", "ground_speed", 0.0),
                "vertical_speed": get("flight", "vertical_speed", 0.0),
            }
            spec["health"] = {
                "system_status": get("health", "system_status", ""),
                "error_count": get("health", "error_count", 0),
                "warning_count": get("health", "warning_count", 0),
            }

        status_payload = {
            "last_update": rfc3339(report.timestamp or utcnow()),
            "collection_status": report.status or "active",
        }
        if report.heartbeat_interval_seconds > 0:
            # Advertised cadence: lets the scheduler judge staleness
            # (monitor/scheduler.py) instead of trusting a frozen
            # "active" status — the reference parses the heartbeat but
            # never uses it (controller.go:202-203, SURVEY §2.7 soft spot).
            status_payload["heartbeat_interval_seconds"] = (
                report.heartbeat_interval_seconds)
        labels: dict[str, Any] = {
            "app": "uav-agent",
            "monitoring.io/component": "uav-metrics",
            "monitoring.io/node": sanitize_resource_name(report.node_name),
        }
        if report.uav_id:
            labels["monitoring.io/uav-id"] = sanitize_resource_name(report.uav_id)
        if report.node_ip:
            labels["monitoring.io/node-ip"] = report.node_ip

        body = {
            "apiVersion": "monitoring.io/v1",
            "kind": "UAVMetric",
            "metadata": {"name": name, "namespace": namespace, "labels": labels},
            "spec": spec,
            "status": status_payload,
        }
        try:
            existing = self.backend.get_custom_resource(
                group, version, plural, namespace, name
            )
        except NotFound:
            self.backend.create_custom_resource(group, version, plural, namespace, body)
            return
        existing["spec"] = spec
        existing["status"] = status_payload
        merged = dict(existing.get("metadata", {}).get("labels", {}) or {})
        merged.update(labels)
        existing.setdefault("metadata", {})["labels"] = merged
        self.backend.update_custom_resource(group, version, plural, namespace, existing)

    # -- watch passthrough ---------------------------------------------------

    def watch(self, kind: str, namespace: str) -> WatchStream:
        return self.backend.watch(kind, namespace)


# ---------------------------------------------------------------------------
# monitor-API client (the server's own HTTP surface)
# ---------------------------------------------------------------------------


class ApiConnectionError(ClusterError):
    """The monitor server could not be reached (or died mid-response).

    Connection-level, not application-level: the replica is a routing
    fact for the fleet tier, which maps this to ``ReplicaUnavailable``.
    """


class ApiClient:
    """HTTP client for the monitor server's own API with the kube_rest
    retry discipline: every socket carries an explicit timeout, GETs
    (probes: ``/readyz``, ``/health``, ``/api/v1/stats``) retry through a
    ``resilience.Backoff`` budget, and POSTs (``/api/v1/query``,
    ``/api/v1/analyze``) are NEVER retried — a query may have side effects
    (admission, generation) and re-dispatch belongs to the fleet router,
    which owns idempotent failover.

    Probe GETs use the short connect timeout (a dead replica must not
    stall the probe loop); query POSTs use the long read timeout, which
    for SSE applies *between* reads so a healthy slow stream is fine.
    """

    # Ceiling on any client-side retry hint, whatever the server says —
    # a misconfigured replica must not park callers for minutes.
    retry_cap_s = 30.0

    def __init__(self, base_url: str, *, connect_timeout_s: float = 2.0,
                 read_timeout_s: float = 30.0, backoff=None):
        import random as _random

        from k8s_llm_monitor_tpu.resilience.retry import Backoff

        self.base_url = base_url.rstrip("/")
        self.connect_timeout_s = connect_timeout_s
        self.read_timeout_s = read_timeout_s
        self.backoff = backoff or Backoff(
            base_s=0.1, cap_s=2.0, attempts=3, rng=_random.Random(0))
        # Decorrelated-jitter state for overload retry hints, per SLO
        # class: consecutive 429s of the same class spread a thundering
        # herd; any successful POST resets the whole map.
        self._retry_rng = _random.Random(1)
        self._retry_prev_s: dict[str, float] = {}

    # -- plumbing ------------------------------------------------------------

    def _url(self, path: str) -> str:
        return f"{self.base_url}{path}"

    def _open(self, path: str, body: dict[str, Any] | None = None,
              timeout: float = 2.0):
        import json as _json
        import urllib.request

        data = None
        headers = self._trace_headers()
        if body is not None:
            data = _json.dumps(body).encode()
            headers["Content-Type"] = "application/json"
        req = urllib.request.Request(self._url(path), data=data,
                                     headers=headers)
        return urllib.request.urlopen(req, timeout=timeout)  # noqa: S310

    @staticmethod
    def _trace_headers() -> dict[str, str]:
        """W3C traceparent for the calling thread's current context, so
        every outbound hop (probes, queries, hedge legs, failover
        replays, KV migration) joins the originating trace."""
        from k8s_llm_monitor_tpu.observability.tracing import get_tracer

        tp = get_tracer().current_traceparent()
        return {"traceparent": tp} if tp else {}

    def _retry_hint_s(self, server_hint_s: float, slo_class: str) -> float:
        """Client-side retry delay from the server's per-class hint:
        decorrelated jitter (``min(cap, uniform(hint, 3 * previous))``),
        so N clients shed in the same step don't all come back on the
        same tick, with ``retry_cap_s`` bounding runaway growth."""
        base = max(0.05, server_hint_s)
        prev = self._retry_prev_s.get(slo_class, base)
        delay = min(self.retry_cap_s,
                    self._retry_rng.uniform(base, max(base, prev * 3.0)))
        self._retry_prev_s[slo_class] = delay
        return delay

    def _overloaded_from(self, exc) -> "OverloadedError | None":
        """Map a 429/503 reply carrying shed evidence to OverloadedError.

        The raised error's ``retry_after_s`` is the server's hint passed
        through :meth:`_retry_hint_s` — NOT a flat fallback — so callers
        that sleep on it honor the replica's per-class pushback."""
        import json as _json

        from k8s_llm_monitor_tpu.resilience.errors import OverloadedError

        if exc.code not in (429, 503):
            return None
        try:
            payload = _json.loads(exc.read().decode())
        except Exception:  # noqa: BLE001 — plain-text 503s exist
            payload = {}
        if exc.code == 503 and payload.get("error_kind") != "overloaded":
            return None
        slo_class = str(payload.get("slo_class", ""))
        try:
            hint = float(payload.get("retry_after_s", 1.0))
        except (TypeError, ValueError):
            hint = 1.0
        return OverloadedError(
            payload.get("reason", f"HTTP {exc.code}"),
            queue_depth=int(payload.get("queue_depth", 0)),
            queue_tokens=int(payload.get("queue_tokens", 0)),
            retriable=exc.code == 429,
            retry_after_s=self._retry_hint_s(hint, slo_class),
            slo_class=slo_class,
            request_id=str(payload.get("request_id", "") or ""),
            tenant=str(payload.get("tenant", "") or ""),
        )

    def _get_json(self, path: str) -> dict[str, Any]:
        """GET with the Backoff retry budget (idempotent: safe to retry)."""
        import json as _json
        import time as _time
        import urllib.error

        delays = list(self.backoff.delays()) + [None]
        last: Exception | None = None
        for delay in delays:
            try:
                with self._open(path, timeout=self.connect_timeout_s) as resp:
                    return _json.loads(resp.read().decode())
            except urllib.error.HTTPError as exc:
                if exc.code < 500:  # the server answered; don't hammer it
                    raise ApiConnectionError(
                        f"GET {path}: HTTP {exc.code}") from exc
                last = exc
            except (urllib.error.URLError, OSError, ValueError) as exc:
                last = exc
            if delay is not None:
                _time.sleep(delay)
        raise ApiConnectionError(f"GET {path}: {last}") from last

    def _post_json(self, path: str, body: dict[str, Any],
                   timeout: float) -> dict[str, Any]:
        """POST, never retried.  4xx/5xx JSON bodies are returned (the
        API ships structured error responses); overload replies raise."""
        import json as _json
        import urllib.error

        try:
            with self._open(path, body=body, timeout=timeout) as resp:
                out = _json.loads(resp.read().decode())
            self._retry_prev_s.clear()  # accepted: end the jitter streak
            return out
        except urllib.error.HTTPError as exc:
            over = self._overloaded_from(exc)
            if over is not None:
                raise over from exc
            try:
                return _json.loads(exc.read().decode())
            except Exception:  # noqa: BLE001
                raise ApiConnectionError(
                    f"POST {path}: HTTP {exc.code}") from exc
        except (urllib.error.URLError, OSError) as exc:
            raise ApiConnectionError(f"POST {path}: {exc}") from exc

    # -- probes (GET, retried) ----------------------------------------------

    def readyz(self) -> bool:
        import urllib.error

        try:
            with self._open("/readyz", timeout=self.connect_timeout_s) as r:
                return r.status == 200
        except urllib.error.HTTPError:
            return False
        except (urllib.error.URLError, OSError) as exc:
            raise ApiConnectionError(f"GET /readyz: {exc}") from exc

    def health(self) -> dict[str, Any]:
        return self._get_json("/health")

    def stats(self) -> dict[str, Any]:
        return self._get_json("/api/v1/stats")

    def diagnoses(self, limit: int = 0) -> dict[str, Any]:
        path = "/api/v1/diagnoses"
        if limit > 0:
            path += f"?limit={int(limit)}"
        return self._get_json(path)

    def trace(self, ref: str) -> dict[str, Any]:
        """GET /api/v1/trace/<ref> — spans for a request or trace id
        (the router's cross-replica merge source)."""
        from urllib.parse import quote

        return self._get_json(f"/api/v1/trace/{quote(ref, safe='')}")

    def traces(self, limit: int = 20) -> dict[str, Any]:
        """GET /api/v1/trace — recent traces in the replica's ring."""
        return self._get_json(f"/api/v1/trace?limit={int(limit)}")

    # -- KV prefix migration (POST, never retried) ---------------------------

    def kv_prefix(self, token_ids: list[int],
                  tenant: str = "") -> bytes | None:
        """POST /api/v1/kv/prefix — framed KV pages for the longest
        cached prefix of ``token_ids`` under ``tenant``'s namespace
        (serving/kv_tier.py blob), or None on a 404 cache miss."""
        import urllib.error

        body: dict[str, Any] = {"token_ids": [int(t) for t in token_ids]}
        if tenant:
            body["tenant"] = tenant
        try:
            with self._open("/api/v1/kv/prefix", body=body,
                            timeout=self.read_timeout_s) as resp:
                return resp.read()
        except urllib.error.HTTPError as exc:
            if exc.code == 404:
                return None
            over = self._overloaded_from(exc)
            if over is not None:
                raise over from exc
            raise ApiConnectionError(
                f"POST /api/v1/kv/prefix: HTTP {exc.code}") from exc
        except (urllib.error.URLError, OSError) as exc:
            raise ApiConnectionError(
                f"POST /api/v1/kv/prefix: {exc}") from exc

    def kv_install(self, blob: bytes, tenant: str | None = None) -> str:
        """POST /api/v1/kv/install — raw blob body; returns the engine's
        outcome string (``installed``/``cached``/``incompatible``/
        ``nospace``/``tenant_mismatch``).  ``tenant`` rides the
        ``X-Tenant-Id`` header (the body is the raw blob) and makes the
        receiver refuse a blob whose header names someone else."""
        import json as _json
        import urllib.error
        import urllib.request

        headers = self._trace_headers()
        headers["Content-Type"] = "application/octet-stream"
        if tenant:
            headers["X-Tenant-Id"] = tenant
        req = urllib.request.Request(
            self._url("/api/v1/kv/install"), data=bytes(blob),
            headers=headers)
        try:
            with urllib.request.urlopen(  # noqa: S310
                    req, timeout=self.read_timeout_s) as resp:
                payload = _json.loads(resp.read().decode())
        except urllib.error.HTTPError as exc:
            over = self._overloaded_from(exc)
            if over is not None:
                raise over from exc
            raise ApiConnectionError(
                f"POST /api/v1/kv/install: HTTP {exc.code}") from exc
        except (urllib.error.URLError, OSError, ValueError) as exc:
            raise ApiConnectionError(
                f"POST /api/v1/kv/install: {exc}") from exc
        return str(payload.get("outcome", "error"))

    # -- queries (POST, never retried) ---------------------------------------

    def query(self, question: str,
              slo_class: str = "", tenant: str = "") -> dict[str, Any]:
        body: dict[str, Any] = {"question": question}
        if slo_class:
            body["slo_class"] = slo_class
        if tenant:
            body["tenant"] = tenant
        return self._post_json("/api/v1/query", body,
                               timeout=self.read_timeout_s)

    def analyze(self, payload: dict[str, Any],
                tenant: str = "") -> dict[str, Any]:
        if tenant:
            payload = dict(payload, tenant=tenant)
        return self._post_json("/api/v1/analyze", payload,
                               timeout=self.read_timeout_s)

    def query_stream(self, question: str, slo_class: str = "",
                     tenant: str = ""):
        """POST /api/v1/query with ``stream: true``; returns
        ``(request_id, model, deltas)`` where ``deltas`` yields answer-text
        chunks.  Mid-stream socket death raises ``ApiConnectionError`` from
        the iterator — the router's failover trigger."""
        import json as _json
        import urllib.error

        body: dict[str, Any] = {"question": question, "stream": True}
        if slo_class:
            body["slo_class"] = slo_class
        if tenant:
            body["tenant"] = tenant
        try:
            resp = self._open("/api/v1/query", body=body,
                              timeout=self.read_timeout_s)
        except urllib.error.HTTPError as exc:
            over = self._overloaded_from(exc)
            if over is not None:
                raise over from exc
            raise ApiConnectionError(
                f"POST /api/v1/query: HTTP {exc.code}") from exc
        except (urllib.error.URLError, OSError) as exc:
            raise ApiConnectionError(f"POST /api/v1/query: {exc}") from exc
        self._retry_prev_s.clear()  # admitted: end the jitter streak

        def events():
            import http.client

            try:
                with resp:
                    for raw in resp:
                        line = raw.decode("utf-8", "replace").strip()
                        if not line.startswith("data: "):
                            continue
                        yield _json.loads(line[len("data: "):])
            except (OSError, ValueError, http.client.HTTPException) as exc:
                # IncompleteRead (a HTTPException, not an OSError) is what
                # a replica death mid-chunk actually raises.
                raise ApiConnectionError(f"stream died: {exc}") from exc

        stream = events()
        first = next(stream, None)
        if first is None:
            raise ApiConnectionError("stream ended before any event")
        request_id = first.get("request_id", "")
        model = first.get("model", "")

        def deltas():
            ev = first
            while ev is not None:
                if ev.get("done"):
                    return
                delta = ev.get("delta", "")
                if delta:
                    yield delta
                ev = next(stream, None)
            # EOF without the done event: the replica died mid-answer but
            # the response ended cleanly.  Surface a dead stream so the
            # caller fails over instead of accepting a truncated answer.
            raise ApiConnectionError("stream ended without done event")

        return request_id, model, deltas()

    def close(self) -> None:  # symmetry with pooled clients; nothing held
        pass
