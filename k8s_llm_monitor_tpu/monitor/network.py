"""Pod-communication analyzer — the rule-based diagnosis pipeline.

Parity target: ``/root/reference/internal/k8s/network.go:34-315`` — the
5-check pipeline (pod status, network-policy overlap, service targeting,
CoreDNS health, live RTT probe) accumulating ``issues``/``solutions`` into
a ``CommunicationAnalysis``, with the reference's final-status rule
(no issues → connected/0.9 else disconnected/0.7, network.go:306-315).

This evidence also feeds the Analysis Engine (analysis.py): the LLM
receives the raw check findings and generates the root-cause narrative the
reference never implemented.
"""

from __future__ import annotations

import logging

from k8s_llm_monitor_tpu.monitor.client import Client
from k8s_llm_monitor_tpu.monitor.cluster import ClusterError
from k8s_llm_monitor_tpu.monitor.models import (
    CommunicationAnalysis,
    NetworkPolicyInfo,
    PodInfo,
    ServiceInfo,
)
from k8s_llm_monitor_tpu.monitor.rtt import RTTTester, parse_pod_ref

logger = logging.getLogger("monitor.network")


def _selector_matches(selector: dict[str, str], labels: dict[str, str]) -> bool:
    """Simplified label match (ref network.go:199-208, 254-261)."""
    return any(labels.get(k) == v for k, v in selector.items())


class NetworkAnalyzer:
    def __init__(self, client: Client, enable_rtt: bool = True) -> None:
        self.client = client
        self.rtt_tester = RTTTester(client)
        self.enable_rtt = enable_rtt

    def analyze_pod_communication(
        self, pod_a: str, pod_b: str
    ) -> CommunicationAnalysis:
        ns_a, name_a = parse_pod_ref(pod_a)
        ns_b, name_b = parse_pod_ref(pod_b)
        info_a = self.client.get_pod(ns_a, name_a)
        info_b = self.client.get_pod(ns_b, name_b)

        analysis = CommunicationAnalysis(pod_a=pod_a, pod_b=pod_b, status="unknown")

        self._check_pod_status(info_a, analysis)
        self._check_pod_status(info_b, analysis)
        self._check_network_policies(info_a, info_b, analysis)
        self._check_service_connectivity(info_a, info_b, analysis)
        self._check_dns_connectivity(analysis)
        if self.enable_rtt:
            self._check_rtt_connectivity(pod_a, pod_b, analysis)
        self._determine_final_status(analysis)
        return analysis

    # -- check 1: pod running (ref network.go:104-111) -------------------------

    def _check_pod_status(
        self, pod: PodInfo, analysis: CommunicationAnalysis
    ) -> None:
        if pod.status != "Running":
            analysis.issues.append(
                f"Pod {pod.namespace}/{pod.name} is not running (status: {pod.status})"
            )
            analysis.solutions.append(
                f"Check Pod {pod.namespace}/{pod.name} logs and events for issues"
            )

    # -- check 2: network policies (ref network.go:114-208) --------------------

    def _check_network_policies(
        self, pod_a: PodInfo, pod_b: PodInfo, analysis: CommunicationAnalysis
    ) -> None:
        policies: list[NetworkPolicyInfo] = []
        for ns in {pod_a.namespace, pod_b.namespace}:
            try:
                policies.extend(self.client.get_network_policies(ns))
            except ClusterError as exc:
                logger.warning("failed to get network policies for %s: %s", ns, exc)
                return
        for policy in policies:
            if _selector_matches(policy.pod_selector, pod_a.labels) or _selector_matches(
                policy.pod_selector, pod_b.labels
            ):
                analysis.issues.append(
                    f"Network policy {policy.namespace}/{policy.name} may affect communication"
                )
                analysis.solutions.append(
                    f"Review network policy {policy.namespace}/{policy.name} rules"
                )

    # -- check 3: service targets pod B (ref network.go:211-244) ---------------

    def _check_service_connectivity(
        self, pod_a: PodInfo, pod_b: PodInfo, analysis: CommunicationAnalysis
    ) -> None:
        try:
            services = self.client.get_services(pod_b.namespace)
        except ClusterError as exc:
            logger.warning(
                "failed to get services for %s: %s", pod_b.namespace, exc
            )
            return
        target: ServiceInfo | None = next(
            (
                s
                for s in services
                if s.selector and _selector_matches(s.selector, pod_b.labels)
            ),
            None,
        )
        if target is None:
            analysis.issues.append(
                f"No service found targeting Pod {pod_b.namespace}/{pod_b.name}"
            )
            analysis.solutions.append(
                f"Create a service to expose Pod {pod_b.namespace}/{pod_b.name}"
            )

    # -- check 4: CoreDNS health (ref network.go:247-267) ----------------------

    def _check_dns_connectivity(self, analysis: CommunicationAnalysis) -> None:
        try:
            pods = self.client.get_pods("kube-system")
        except ClusterError as exc:
            logger.warning("failed to get CoreDNS pods: %s", exc)
            return
        running = any(
            "coredns" in p.name and p.status == "Running" for p in pods
        )
        if not running:
            analysis.issues.append("CoreDNS is not running properly")
            analysis.solutions.append("Check CoreDNS pods in kube-system namespace")

    # -- check 5: live RTT probe (ref network.go:270-303) ----------------------

    def _check_rtt_connectivity(
        self, pod_a: str, pod_b: str, analysis: CommunicationAnalysis
    ) -> None:
        try:
            result = self.rtt_tester.test_pod_connectivity(pod_a, pod_b)
        except ClusterError as exc:
            analysis.issues.append(f"RTT test failed: {exc}")
            analysis.solutions.append(
                "Check that the pods support in-pod network command execution"
            )
            return

        if result.success_rate < 50:
            analysis.issues.append(
                f"Poor network connectivity, success rate only {result.success_rate:.1f}%"
            )
            analysis.solutions.append("Check network policies and firewall configuration")
        elif result.success_rate < 100:
            analysis.issues.append(
                f"Network packet loss detected, success rate {result.success_rate:.1f}%"
            )
            analysis.solutions.append("Check network quality and node status")

        if result.latency_assessment == "fair":
            analysis.issues.append(
                f"Moderate network latency, average RTT {result.average_rtt_ms:.2f}ms"
            )
            analysis.solutions.append(
                "Consider optimizing network configuration or checking network load"
            )
        elif result.latency_assessment in ("poor", "very_poor"):
            analysis.issues.append(
                f"High network latency, average RTT {result.average_rtt_ms:.2f}ms"
            )
            analysis.solutions.append(
                "Check network configuration and inter-node connectivity"
            )
        logger.info(
            "RTT %s -> %s: success %.1f%%, avg %.2fms, grade %s",
            pod_a,
            pod_b,
            result.success_rate,
            result.average_rtt_ms,
            result.latency_assessment,
        )

    # -- verdict (ref network.go:306-315) --------------------------------------

    def _determine_final_status(self, analysis: CommunicationAnalysis) -> None:
        if not analysis.issues:
            analysis.status = "connected"
            analysis.confidence = 0.9
            analysis.solutions.append("No obvious issues detected")
        else:
            analysis.status = "disconnected"
            analysis.confidence = 0.7
