"""Cluster backend seam + fake in-memory cluster.

The reference talks to a real Kubernetes API server through client-go
(``/root/reference/internal/k8s/client.go:25-45``) and is consequently
untestable without a cluster (zero test files, SURVEY §4). This module
fixes that: every cluster touchpoint the product needs — lists, logs, exec,
watch streams, CRDs/CRs, metrics-server usage — goes through the
``ClusterBackend`` interface, with two implementations:

- ``FakeCluster`` (here): an in-memory cluster with real watch-stream
  semantics (subscriber queues, closable streams for reconnect tests),
  failure injection, and an exec simulator for the RTT probes.
- ``KubeRestBackend`` (kube_rest.py): a stdlib-HTTP client speaking to a
  real API server via kubeconfig (no external k8s package needed).

Objects cross the seam in Kubernetes API wire shape (metadata/spec/status
dicts), so converters and consumers behave identically against both
backends.
"""

from __future__ import annotations

import copy
import itertools
import queue
import re
import threading
from datetime import datetime, timezone
from typing import Any, Callable, Iterator

from k8s_llm_monitor_tpu.devtools.lockcheck import make_lock
from k8s_llm_monitor_tpu.monitor.models import rfc3339, utcnow

# ---------------------------------------------------------------------------
# resource-quantity parsing (cpu millicores, memory bytes)
# ---------------------------------------------------------------------------

_MEM_SUFFIX = {
    "Ki": 1024,
    "Mi": 1024**2,
    "Gi": 1024**3,
    "Ti": 1024**4,
    "Pi": 1024**5,
    "K": 1000,
    "k": 1000,
    "M": 1000**2,
    "G": 1000**3,
    "T": 1000**4,
    "P": 1000**5,
}


def parse_cpu_millis(q: str | int | float | None) -> int:
    """'250m' → 250, '2' → 2000, '1.5' → 1500, 100n → 0 (sub-milli floors)."""
    if q is None or q == "":
        return 0
    if isinstance(q, (int, float)):
        return int(float(q) * 1000)
    s = str(q).strip()
    if s.endswith("n"):
        return int(float(s[:-1]) / 1e6)
    if s.endswith("u"):
        return int(float(s[:-1]) / 1e3)
    if s.endswith("m"):
        return int(float(s[:-1]))
    return int(float(s) * 1000)


def parse_mem_bytes(q: str | int | float | None) -> int:
    """'128Mi' → 134217728, '1Gi' → 2**30, plain number → bytes."""
    if q is None or q == "":
        return 0
    if isinstance(q, (int, float)):
        return int(q)
    s = str(q).strip()
    for suffix, mult in _MEM_SUFFIX.items():
        if s.endswith(suffix):
            return int(float(s[: -len(suffix)]) * mult)
    return int(float(s))


# ---------------------------------------------------------------------------
# watch streams
# ---------------------------------------------------------------------------


class WatchStream:
    """One live watch: iterate (event_type, object) until closed.

    ``event_type`` ∈ {"ADDED", "MODIFIED", "DELETED"}; iteration ends when
    the stream closes (server side or via ``close()``), mirroring a k8s
    watch channel closing so consumers exercise their reconnect loops.
    """

    _CLOSE = object()

    def __init__(self) -> None:
        self._q: queue.Queue[Any] = queue.Queue()
        self._closed = threading.Event()

    def put(self, event_type: str, obj: dict[str, Any]) -> None:
        if not self._closed.is_set():
            self._q.put((event_type, obj))

    def close(self) -> None:
        if not self._closed.is_set():
            self._closed.set()
            self._q.put(self._CLOSE)

    @property
    def closed(self) -> bool:
        return self._closed.is_set()

    def __iter__(self) -> Iterator[tuple[str, dict[str, Any]]]:
        while True:
            if self._closed.is_set() and self._q.empty():
                return  # closed and drained (incl. re-iteration after close)
            item = self._q.get()
            if item is self._CLOSE:
                self._q.put(self._CLOSE)  # keep the sentinel for other readers
                return
            yield item


# ---------------------------------------------------------------------------
# backend interface
# ---------------------------------------------------------------------------


class ClusterError(Exception):
    """Any backend failure (unreachable API, missing resource, ...)."""


class NotFound(ClusterError):
    pass


class Conflict(ClusterError):
    pass


class ClusterBackend:
    """The seam every cluster touchpoint goes through.

    All list/get results are deep copies in Kubernetes wire shape.
    Subclasses must implement everything; the base raises.
    """

    # -- discovery / core reads
    def server_version(self) -> str:
        raise NotImplementedError

    def list_nodes(self) -> list[dict[str, Any]]:
        raise NotImplementedError

    def list_pods(self, namespace: str) -> list[dict[str, Any]]:
        raise NotImplementedError

    def list_services(self, namespace: str) -> list[dict[str, Any]]:
        raise NotImplementedError

    def list_events(self, namespace: str, limit: int = 0) -> list[dict[str, Any]]:
        raise NotImplementedError

    def list_network_policies(self, namespace: str) -> list[dict[str, Any]]:
        raise NotImplementedError

    def pod_logs(self, namespace: str, name: str, tail_lines: int = 100) -> str:
        raise NotImplementedError

    def exec_in_pod(
        self, namespace: str, pod: str, command: list[str], timeout: float = 10.0
    ) -> tuple[str, str, int]:
        """Run a command in a pod; returns (stdout, stderr, exit code)."""
        raise NotImplementedError

    # -- metrics.k8s.io
    def node_usage(self) -> list[dict[str, Any]]:
        """NodeMetrics list items: {metadata.name, usage:{cpu,memory}}."""
        raise NotImplementedError

    def pod_usage(self, namespace: str) -> list[dict[str, Any]]:
        """PodMetrics list items incl. containers[].usage."""
        raise NotImplementedError

    # -- watches
    def watch(self, kind: str, namespace: str) -> WatchStream:
        """kind ∈ {pods, services, events}."""
        raise NotImplementedError

    def watch_crds(self) -> WatchStream:
        raise NotImplementedError

    def watch_custom_resources(
        self, group: str, version: str, plural: str, namespace: str | None
    ) -> WatchStream:
        raise NotImplementedError

    # -- CRDs / custom resources (dynamic client equivalent)
    def list_crds(self) -> list[dict[str, Any]]:
        raise NotImplementedError

    def list_custom_resources(
        self, group: str, version: str, plural: str, namespace: str | None
    ) -> list[dict[str, Any]]:
        raise NotImplementedError

    def get_custom_resource(
        self, group: str, version: str, plural: str, namespace: str | None, name: str
    ) -> dict[str, Any]:
        raise NotImplementedError

    def create_custom_resource(
        self,
        group: str,
        version: str,
        plural: str,
        namespace: str | None,
        body: dict[str, Any],
    ) -> dict[str, Any]:
        raise NotImplementedError

    def update_custom_resource(
        self,
        group: str,
        version: str,
        plural: str,
        namespace: str | None,
        body: dict[str, Any],
    ) -> dict[str, Any]:
        raise NotImplementedError

    def update_custom_resource_status(
        self,
        group: str,
        version: str,
        plural: str,
        namespace: str | None,
        body: dict[str, Any],
    ) -> dict[str, Any]:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# fake in-memory cluster
# ---------------------------------------------------------------------------

ExecHandler = Callable[[str, str, list[str]], tuple[str, str, int]]


class FakeCluster(ClusterBackend):
    """In-memory cluster with watch fan-out, failure injection, exec sim.

    Test ergonomics:
    - builder helpers (``add_node``/``add_pod``/... ) accept plain kwargs
      and fill in wire-shape boilerplate;
    - ``fail_next("list_pods", n)`` makes the next n calls raise, and
      ``close_watches()`` severs live streams — both for recovery tests;
    - exec is simulated: ``ping``/``curl`` get synthetic outputs whose RTT
      depends on whether source and target share a node (override per-pod
      with ``set_exec_handler``).
    """

    def __init__(self, version: str = "v1.29.0-fake") -> None:
        self._lock = make_lock("fake_cluster", reentrant=True)
        self._version = version
        self._nodes: dict[str, dict] = {}
        self._pods: dict[tuple[str, str], dict] = {}  # (ns, name)
        self._services: dict[tuple[str, str], dict] = {}
        self._statefulsets: dict[tuple[str, str], dict] = {}
        self._events: dict[str, list[dict]] = {}  # ns -> list
        self._netpols: dict[tuple[str, str], dict] = {}
        self._logs: dict[tuple[str, str], list[str]] = {}
        self._crds: dict[str, dict] = {}  # metadata.name
        # (group, plural, ns or "", name) -> object
        self._crs: dict[tuple[str, str, str, str], dict] = {}
        self._node_usage: dict[str, dict[str, Any]] = {}
        self._pod_usage: dict[tuple[str, str], dict[str, Any]] = {}
        self._watchers: dict[tuple, list[WatchStream]] = {}
        self._fail: dict[str, int] = {}
        self._exec_handler: ExecHandler | None = None
        self._uid = itertools.count(1)
        self.metrics_server_available = True
        # synthetic RTT model for the exec simulator (ms)
        self.same_node_rtt_ms = 0.4
        self.cross_node_rtt_ms = 2.5

    # -- failure injection ---------------------------------------------------

    def fail_next(self, method: str, times: int = 1) -> None:
        with self._lock:
            self._fail[method] = self._fail.get(method, 0) + times

    def _maybe_fail(self, method: str) -> None:
        with self._lock:
            n = self._fail.get(method, 0)
            if n > 0:
                self._fail[method] = n - 1
                raise ClusterError(f"injected failure: {method}")

    def close_watches(self) -> None:
        """Sever all live watch streams (tests of reconnect loops)."""
        with self._lock:
            streams = [s for lst in self._watchers.values() for s in lst]
            self._watchers.clear()
        for s in streams:
            s.close()

    # -- builders ------------------------------------------------------------

    def add_node(
        self,
        name: str,
        cpu: str = "4",
        memory: str = "16Gi",
        disk: str = "100Gi",
        labels: dict[str, str] | None = None,
        ready: bool = True,
        pressure: list[str] | None = None,
        tpu_chips: int = 0,
        tpu_model: str = "tpu-v5e",
    ) -> dict:
        alloc_factor = 0.95
        capacity = {
            "cpu": cpu,
            "memory": memory,
            "ephemeral-storage": disk,
        }
        allocatable = {
            "cpu": f"{int(parse_cpu_millis(cpu) * alloc_factor)}m",
            "memory": str(int(parse_mem_bytes(memory) * alloc_factor)),
            "ephemeral-storage": str(int(parse_mem_bytes(disk) * alloc_factor)),
        }
        if tpu_chips:
            capacity["google.com/tpu"] = str(tpu_chips)
            allocatable["google.com/tpu"] = str(tpu_chips)
        conditions = [
            {"type": "Ready", "status": "True" if ready else "False"},
        ]
        for cond in pressure or []:
            conditions.append({"type": cond, "status": "True"})
        node = {
            "metadata": {
                "name": name,
                "uid": f"node-{next(self._uid)}",
                "labels": dict(labels or {}),
                "creationTimestamp": rfc3339(utcnow()),
            },
            "status": {
                "capacity": capacity,
                "allocatable": allocatable,
                "conditions": conditions,
                "nodeInfo": {"kubeletVersion": self._version},
            },
        }
        if tpu_chips:
            node["metadata"]["labels"].setdefault(
                "cloud.google.com/gke-tpu-accelerator", tpu_model
            )
        with self._lock:
            self._nodes[name] = node
        return node

    def add_pod(
        self,
        name: str,
        namespace: str = "default",
        node: str = "",
        ip: str = "",
        phase: str = "Running",
        labels: dict[str, str] | None = None,
        containers: list[dict] | None = None,
        image: str = "nginx:1.25",
        ready: bool = True,
        restarts: int = 0,
        requests: dict[str, str] | None = None,
        limits: dict[str, str] | None = None,
        env: dict[str, str] | None = None,
        start_time: datetime | None = None,
    ) -> dict:
        uid = next(self._uid)
        if not ip:
            ip = f"10.244.{uid % 250}.{(uid * 7) % 250 + 1}"
        if containers is None:
            containers = [
                {
                    "name": name.split("-")[0] or "main",
                    "image": image,
                    "env": [{"name": k, "value": v} for k, v in (env or {}).items()],
                    "resources": {
                        "requests": dict(requests or {}),
                        "limits": dict(limits or {}),
                    },
                }
            ]
        statuses = [
            {
                "name": c["name"],
                "ready": ready and phase == "Running",
                "restartCount": restarts,
                "state": (
                    {"running": {"startedAt": rfc3339(start_time or utcnow())}}
                    if phase == "Running"
                    else {"waiting": {"reason": phase}}
                ),
            }
            for c in containers
        ]
        pod = {
            "metadata": {
                "name": name,
                "namespace": namespace,
                "uid": f"pod-{uid}",
                "labels": dict(labels or {}),
                "creationTimestamp": rfc3339(start_time or utcnow()),
            },
            "spec": {"nodeName": node, "containers": containers},
            "status": {
                "phase": phase,
                "podIP": ip if phase == "Running" else "",
                "startTime": rfc3339(start_time or utcnow()),
                "containerStatuses": statuses,
            },
        }
        with self._lock:
            self._pods[(namespace, name)] = pod
        self._notify(("pods", namespace), "ADDED", pod)
        return pod

    def update_pod(self, namespace: str, name: str, **changes: Any) -> dict:
        with self._lock:
            pod = self._pods[(namespace, name)]
            if "phase" in changes:
                pod["status"]["phase"] = changes["phase"]
                if changes["phase"] != "Running":
                    pod["status"]["podIP"] = ""
            if "labels" in changes:
                pod["metadata"]["labels"] = dict(changes["labels"])
            if "node" in changes:
                pod["spec"]["nodeName"] = changes["node"]
            snapshot = copy.deepcopy(pod)
        self._notify(("pods", namespace), "MODIFIED", snapshot)
        return snapshot

    def delete_pod(self, namespace: str, name: str,
                   dry_run: bool = False) -> None:
        self._maybe_fail("delete_pod")
        with self._lock:
            if (namespace, name) not in self._pods:
                raise NotFound(f"pod {namespace}/{name} not found")
            if dry_run:
                return
            pod = self._pods.pop((namespace, name))
        self._notify(("pods", namespace), "DELETED", pod)

    # -- remediation verbs ---------------------------------------------------
    # Mutations mirror the KubeRestBackend surface (dry_run maps to the
    # server-side ``dryRun=All`` semantics: full validation, no state
    # change) and honor ``fail_next`` so executor breaker paths are
    # testable without a real API server.

    def add_statefulset(
        self,
        name: str,
        namespace: str = "default",
        replicas: int = 1,
        labels: dict[str, str] | None = None,
    ) -> dict:
        sts = {
            "metadata": {
                "name": name,
                "namespace": namespace,
                "uid": f"sts-{next(self._uid)}",
                "labels": dict(labels or {}),
                "creationTimestamp": rfc3339(utcnow()),
            },
            "spec": {"replicas": int(replicas)},
            "status": {"readyReplicas": int(replicas)},
        }
        with self._lock:
            self._statefulsets[(namespace, name)] = sts
        return sts

    def list_statefulsets(self, namespace: str) -> list[dict[str, Any]]:
        self._maybe_fail("list_statefulsets")
        with self._lock:
            return [
                copy.deepcopy(s)
                for (ns, _), s in sorted(self._statefulsets.items())
                if ns == namespace
            ]

    def get_statefulset_scale(self, namespace: str, name: str) -> int:
        self._maybe_fail("get_statefulset_scale")
        with self._lock:
            try:
                sts = self._statefulsets[(namespace, name)]
            except KeyError:
                raise NotFound(f"statefulset {namespace}/{name} not found")
            return int(sts["spec"].get("replicas", 0))

    def scale_statefulset(self, namespace: str, name: str, replicas: int,
                          dry_run: bool = False) -> None:
        self._maybe_fail("scale_statefulset")
        with self._lock:
            if (namespace, name) not in self._statefulsets:
                raise NotFound(f"statefulset {namespace}/{name} not found")
            if dry_run:
                return
            sts = self._statefulsets[(namespace, name)]
            sts["spec"]["replicas"] = int(replicas)
            sts["status"]["readyReplicas"] = int(replicas)

    def rollout_restart(self, namespace: str, name: str,
                        dry_run: bool = False) -> int:
        """Restart the workload's pods: every pod whose name starts with
        ``name`` returns to a fresh Running state (phase reset, restart
        counters zeroed) — the fake-cluster equivalent of the rollout
        replacing crashed pods with healthy ones.  Returns the pod count.
        """
        self._maybe_fail("rollout_restart")
        with self._lock:
            matched = [
                (ns, pn) for (ns, pn) in self._pods
                if ns == namespace and pn.startswith(name)
            ]
            if not matched:
                raise NotFound(
                    f"workload {namespace}/{name} matches no pods")
            if dry_run:
                return len(matched)
            snapshots = []
            for key in matched:
                pod = self._pods[key]
                pod["status"]["phase"] = "Running"
                pod["status"]["startTime"] = rfc3339(utcnow())
                for st in pod["status"].get("containerStatuses", []):
                    st["ready"] = True
                    st["restartCount"] = 0
                    st["state"] = {"running": {"startedAt": rfc3339(utcnow())}}
                snapshots.append(copy.deepcopy(pod))
        for snap in snapshots:
            self._notify(("pods", namespace), "MODIFIED", snap)
        return len(snapshots)

    def cordon_node(self, name: str, dry_run: bool = False) -> None:
        self._maybe_fail("cordon_node")
        with self._lock:
            if name not in self._nodes:
                raise NotFound(f"node {name} not found")
            if dry_run:
                return
            self._nodes[name].setdefault("spec", {})["unschedulable"] = True

    def add_service(
        self,
        name: str,
        namespace: str = "default",
        selector: dict[str, str] | None = None,
        ports: list[tuple[str, int, str]] | None = None,
        type_: str = "ClusterIP",
        cluster_ip: str = "",
    ) -> dict:
        uid = next(self._uid)
        svc = {
            "metadata": {
                "name": name,
                "namespace": namespace,
                "uid": f"svc-{uid}",
                "creationTimestamp": rfc3339(utcnow()),
            },
            "spec": {
                "type": type_,
                "clusterIP": cluster_ip or f"10.96.{uid % 250}.{uid % 200 + 1}",
                "selector": dict(selector or {}),
                "ports": [
                    {"name": n, "port": p, "protocol": proto}
                    for n, p, proto in (ports or [("http", 80, "TCP")])
                ],
            },
        }
        with self._lock:
            self._services[(namespace, name)] = svc
        self._notify(("services", namespace), "ADDED", svc)
        return svc

    def add_event(
        self,
        namespace: str = "default",
        type_: str = "Normal",
        reason: str = "",
        message: str = "",
        component: str = "kubelet",
        count: int = 1,
        involved_object: str = "",
        timestamp: datetime | None = None,
    ) -> dict:
        ev = {
            "metadata": {
                "name": f"ev-{next(self._uid)}",
                "namespace": namespace,
            },
            "type": type_,
            "reason": reason,
            "message": message,
            "source": {"component": component},
            "count": count,
            "lastTimestamp": rfc3339(timestamp or utcnow()),
            "involvedObject": {"name": involved_object, "namespace": namespace},
        }
        with self._lock:
            self._events.setdefault(namespace, []).append(ev)
        self._notify(("events", namespace), "ADDED", ev)
        return ev

    def add_network_policy(
        self,
        name: str,
        namespace: str = "default",
        pod_selector: dict[str, str] | None = None,
        ingress: list[dict] | None = None,
        egress: list[dict] | None = None,
    ) -> dict:
        pol = {
            "metadata": {"name": name, "namespace": namespace},
            "spec": {
                "podSelector": {"matchLabels": dict(pod_selector or {})},
                "ingress": ingress or [],
                "egress": egress or [],
            },
        }
        with self._lock:
            self._netpols[(namespace, name)] = pol
        return pol

    def set_pod_logs(self, namespace: str, name: str, lines: list[str]) -> None:
        with self._lock:
            self._logs[(namespace, name)] = list(lines)

    def set_node_usage(self, name: str, cpu: str, memory: str) -> None:
        with self._lock:
            self._node_usage[name] = {
                "metadata": {"name": name},
                "usage": {"cpu": cpu, "memory": memory},
            }

    def set_pod_usage(
        self,
        namespace: str,
        name: str,
        cpu: str,
        memory: str,
        containers: list[dict] | None = None,
    ) -> None:
        with self._lock:
            self._pod_usage[(namespace, name)] = {
                "metadata": {"name": name, "namespace": namespace},
                "containers": containers
                or [
                    {
                        "name": name.split("-")[0] or "main",
                        "usage": {"cpu": cpu, "memory": memory},
                    }
                ],
            }

    def set_exec_handler(self, handler: ExecHandler | None) -> None:
        self._exec_handler = handler

    # -- CRD builders --------------------------------------------------------

    def define_crd(
        self,
        group: str,
        kind: str,
        plural: str,
        singular: str = "",
        scope: str = "Namespaced",
        versions: list[str] | None = None,
        established: bool = True,
    ) -> dict:
        name = f"{plural}.{group}"
        crd = {
            "metadata": {
                "name": name,
                "creationTimestamp": rfc3339(utcnow()),
            },
            "spec": {
                "group": group,
                "scope": scope,
                "names": {
                    "kind": kind,
                    "plural": plural,
                    "singular": singular or kind.lower(),
                },
                "versions": [
                    {"name": v, "served": True, "storage": i == 0}
                    for i, v in enumerate(versions or ["v1"])
                ],
            },
            "status": {
                "conditions": (
                    [{"type": "Established", "status": "True"}] if established else []
                )
            },
        }
        with self._lock:
            self._crds[name] = crd
        self._notify(("crds",), "ADDED", crd)
        return crd

    # -- ClusterBackend implementation ---------------------------------------

    def server_version(self) -> str:
        self._maybe_fail("server_version")
        return self._version

    def list_nodes(self) -> list[dict]:
        self._maybe_fail("list_nodes")
        with self._lock:
            return copy.deepcopy(list(self._nodes.values()))

    def list_pods(self, namespace: str) -> list[dict]:
        self._maybe_fail("list_pods")
        with self._lock:
            return copy.deepcopy(
                [p for (ns, _), p in self._pods.items() if ns == namespace]
            )

    def list_services(self, namespace: str) -> list[dict]:
        self._maybe_fail("list_services")
        with self._lock:
            return copy.deepcopy(
                [s for (ns, _), s in self._services.items() if ns == namespace]
            )

    def list_events(self, namespace: str, limit: int = 0) -> list[dict]:
        self._maybe_fail("list_events")
        with self._lock:
            evs = copy.deepcopy(self._events.get(namespace, []))
        if limit and len(evs) > limit:
            evs = evs[-limit:]
        return evs

    def list_network_policies(self, namespace: str) -> list[dict]:
        self._maybe_fail("list_network_policies")
        with self._lock:
            return copy.deepcopy(
                [p for (ns, _), p in self._netpols.items() if ns == namespace]
            )

    def pod_logs(self, namespace: str, name: str, tail_lines: int = 100) -> str:
        self._maybe_fail("pod_logs")
        with self._lock:
            if (namespace, name) not in self._pods:
                raise NotFound(f"pod {namespace}/{name} not found")
            lines = self._logs.get((namespace, name), [])
        if tail_lines and len(lines) > tail_lines:
            lines = lines[-tail_lines:]
        return "\n".join(lines) + ("\n" if lines else "")

    # -- exec simulation -----------------------------------------------------

    def exec_in_pod(
        self, namespace: str, pod: str, command: list[str], timeout: float = 10.0
    ) -> tuple[str, str, int]:
        self._maybe_fail("exec_in_pod")
        with self._lock:
            if (namespace, pod) not in self._pods:
                raise NotFound(f"pod {namespace}/{pod} not found")
        if self._exec_handler is not None:
            return self._exec_handler(namespace, pod, command)
        return self._simulate_exec(namespace, pod, command)

    def _find_pod_by_ip(self, ip: str) -> dict | None:
        for p in self._pods.values():
            if p["status"].get("podIP") == ip:
                return p
        return None

    def _simulate_exec(
        self, namespace: str, pod: str, command: list[str]
    ) -> tuple[str, str, int]:
        """Synthesize ping/curl output the RTT parser understands."""
        prog = command[0] if command else ""
        with self._lock:
            src = self._pods[(namespace, pod)]
            target_ip = command[-1] if command else ""
            # curl URLs look like http://ip:port/
            m = re.search(r"(\d+\.\d+\.\d+\.\d+)", target_ip)
            tgt = self._find_pod_by_ip(m.group(1)) if m else None
            if tgt is None:
                return "", f"unknown host {target_ip}", 1
            same_node = src["spec"].get("nodeName") and src["spec"].get(
                "nodeName"
            ) == tgt["spec"].get("nodeName")
            rtt = self.same_node_rtt_ms if same_node else self.cross_node_rtt_ms
        if prog == "ping":
            n = 3
            if "-c" in command:
                n = int(command[command.index("-c") + 1])
            ip = m.group(1)
            lines = [f"PING {ip} ({ip}): 56 data bytes"]
            for i in range(n):
                lines.append(
                    f"64 bytes from {ip}: icmp_seq={i} ttl=64 "
                    f"time={rtt + 0.01 * i:.3f} ms"
                )
            lines += [
                f"--- {ip} ping statistics ---",
                f"{n} packets transmitted, {n} packets received, 0% packet loss",
                f"round-trip min/avg/max = {rtt:.3f}/{rtt:.3f}/{rtt:.3f} ms",
            ]
            return "\n".join(lines) + "\n", "", 0
        if prog == "curl":
            return f"{rtt / 1000.0:.6f}", "", 0
        return "", f"exec: {prog}: not found", 127

    # -- metrics.k8s.io ------------------------------------------------------

    def node_usage(self) -> list[dict]:
        self._maybe_fail("node_usage")
        if not self.metrics_server_available:
            raise ClusterError("metrics-server unavailable")
        with self._lock:
            out = []
            for name, node in self._nodes.items():
                if name in self._node_usage:
                    out.append(copy.deepcopy(self._node_usage[name]))
                else:
                    cap = node["status"]["capacity"]
                    out.append(
                        {
                            "metadata": {"name": name},
                            "usage": {
                                "cpu": f"{int(parse_cpu_millis(cap['cpu']) * 0.25)}m",
                                "memory": str(
                                    int(parse_mem_bytes(cap["memory"]) * 0.3)
                                ),
                            },
                        }
                    )
            return out

    def pod_usage(self, namespace: str) -> list[dict]:
        self._maybe_fail("pod_usage")
        if not self.metrics_server_available:
            raise ClusterError("metrics-server unavailable")
        with self._lock:
            out = []
            for (ns, name), pod in self._pods.items():
                if ns != namespace or pod["status"]["phase"] != "Running":
                    continue
                if (ns, name) in self._pod_usage:
                    out.append(copy.deepcopy(self._pod_usage[(ns, name)]))
                else:
                    out.append(
                        {
                            "metadata": {"name": name, "namespace": ns},
                            "containers": [
                                {"name": c["name"], "usage": {"cpu": "5m", "memory": "16Mi"}}
                                for c in pod["spec"]["containers"]
                            ],
                        }
                    )
            return out

    # -- watches -------------------------------------------------------------

    def _subscribe(self, topic: tuple) -> WatchStream:
        stream = WatchStream()
        with self._lock:
            self._watchers.setdefault(topic, []).append(stream)
        return stream

    def _notify(self, topic: tuple, event_type: str, obj: dict) -> None:
        with self._lock:
            streams = list(self._watchers.get(topic, []))
            # CR topics additionally fan out to all-namespace watchers
            if topic and topic[0] == "cr" and len(topic) == 4 and topic[3]:
                streams += self._watchers.get(topic[:3] + ("",), [])
        snapshot = copy.deepcopy(obj)
        for s in streams:
            s.put(event_type, snapshot)

    def watch(self, kind: str, namespace: str) -> WatchStream:
        self._maybe_fail("watch")
        if kind not in ("pods", "services", "events"):
            raise ClusterError(f"unknown watch kind {kind}")
        return self._subscribe((kind, namespace))

    def watch_crds(self) -> WatchStream:
        self._maybe_fail("watch_crds")
        return self._subscribe(("crds",))

    def watch_custom_resources(
        self, group: str, version: str, plural: str, namespace: str | None
    ) -> WatchStream:
        self._maybe_fail("watch_custom_resources")
        return self._subscribe(("cr", group, plural, namespace or ""))

    # -- custom resources ----------------------------------------------------

    def _crd_for(self, group: str, plural: str) -> dict:
        name = f"{plural}.{group}"
        crd = self._crds.get(name)
        if crd is None:
            raise NotFound(f"CRD {name} not defined")
        return crd

    def list_crds(self) -> list[dict]:
        self._maybe_fail("list_crds")
        with self._lock:
            return copy.deepcopy(list(self._crds.values()))

    def list_custom_resources(
        self, group: str, version: str, plural: str, namespace: str | None
    ) -> list[dict]:
        self._maybe_fail("list_custom_resources")
        with self._lock:
            self._crd_for(group, plural)
            out = []
            for (g, p, ns, _), obj in self._crs.items():
                if g == group and p == plural and (not namespace or ns == namespace):
                    out.append(copy.deepcopy(obj))
            return out

    def get_custom_resource(
        self, group: str, version: str, plural: str, namespace: str | None, name: str
    ) -> dict:
        self._maybe_fail("get_custom_resource")
        with self._lock:
            obj = self._crs.get((group, plural, namespace or "", name))
            if obj is None:
                raise NotFound(f"{plural}.{group} {namespace}/{name} not found")
            return copy.deepcopy(obj)

    def create_custom_resource(
        self,
        group: str,
        version: str,
        plural: str,
        namespace: str | None,
        body: dict,
    ) -> dict:
        self._maybe_fail("create_custom_resource")
        name = body["metadata"]["name"]
        key = (group, plural, namespace or "", name)
        with self._lock:
            crd = self._crd_for(group, plural)
            if key in self._crs:
                raise Conflict(f"{plural}.{group} {name} already exists")
            obj = copy.deepcopy(body)
            obj.setdefault("apiVersion", f"{group}/{version}")
            obj.setdefault("kind", crd["spec"]["names"]["kind"])
            md = obj["metadata"]
            if namespace:
                md["namespace"] = namespace
            md.setdefault("uid", f"cr-{next(self._uid)}")
            md["generation"] = 1
            md.setdefault("creationTimestamp", rfc3339(utcnow()))
            md["managedFields"] = [{"time": rfc3339(utcnow())}]
            self._crs[key] = obj
            snapshot = copy.deepcopy(obj)
        self._notify(("cr", group, plural, namespace or ""), "ADDED", snapshot)
        return snapshot

    def update_custom_resource(
        self,
        group: str,
        version: str,
        plural: str,
        namespace: str | None,
        body: dict,
    ) -> dict:
        self._maybe_fail("update_custom_resource")
        name = body["metadata"]["name"]
        key = (group, plural, namespace or "", name)
        with self._lock:
            old = self._crs.get(key)
            if old is None:
                raise NotFound(f"{plural}.{group} {name} not found")
            obj = copy.deepcopy(body)
            obj["metadata"]["generation"] = old["metadata"].get("generation", 1) + 1
            obj["metadata"].setdefault(
                "creationTimestamp", old["metadata"].get("creationTimestamp")
            )
            obj["metadata"]["managedFields"] = [{"time": rfc3339(utcnow())}]
            self._crs[key] = obj
            snapshot = copy.deepcopy(obj)
        self._notify(("cr", group, plural, namespace or ""), "MODIFIED", snapshot)
        return snapshot

    def update_custom_resource_status(
        self,
        group: str,
        version: str,
        plural: str,
        namespace: str | None,
        body: dict,
    ) -> dict:
        """Status-subresource write: only .status is applied."""
        self._maybe_fail("update_custom_resource_status")
        name = body["metadata"]["name"]
        key = (group, plural, namespace or "", name)
        with self._lock:
            obj = self._crs.get(key)
            if obj is None:
                raise NotFound(f"{plural}.{group} {name} not found")
            obj["status"] = copy.deepcopy(body.get("status", {}))
            snapshot = copy.deepcopy(obj)
        self._notify(("cr", group, plural, namespace or ""), "MODIFIED", snapshot)
        return snapshot

    def delete_custom_resource(
        self, group: str, version: str, plural: str, namespace: str | None, name: str
    ) -> None:
        with self._lock:
            obj = self._crs.pop((group, plural, namespace or "", name), None)
        if obj is not None:
            self._notify(("cr", group, plural, namespace or ""), "DELETED", obj)


def seed_demo_cluster(fake: FakeCluster) -> FakeCluster:
    """Populate a small 3-node demo cluster (the dev-mode default world).

    Mirrors the reference's k3d demo topology (docs/k3d-deployment.md:
    1 server + 2 agents) with a TPU node, app pods, a service, events and
    netpols so every API route returns non-trivial data without a cluster.
    """
    fake.add_node("k3d-demo-server-0", cpu="4", memory="8Gi", labels={"role": "server"})
    fake.add_node("k3d-demo-agent-0", cpu="8", memory="16Gi", labels={"role": "agent"})
    fake.add_node(
        "k3d-demo-agent-1",
        cpu="8",
        memory="16Gi",
        labels={"role": "agent"},
        tpu_chips=8,
    )
    fake.add_pod(
        "web-frontend-7d4b9c6f5-x2x1p",
        node="k3d-demo-agent-0",
        labels={"app": "web-frontend"},
        requests={"cpu": "100m", "memory": "128Mi"},
        limits={"cpu": "500m", "memory": "512Mi"},
    )
    fake.add_pod(
        "api-backend-6f5d8b7c9-k3k2m",
        node="k3d-demo-agent-1",
        labels={"app": "api-backend"},
        requests={"cpu": "200m", "memory": "256Mi"},
        limits={"cpu": "1", "memory": "1Gi"},
    )
    fake.add_pod(
        "coredns-5d78c9869d-abcde",
        namespace="kube-system",
        node="k3d-demo-server-0",
        labels={"k8s-app": "kube-dns"},
        image="coredns/coredns:1.11",
    )
    fake.add_service(
        "api-backend",
        selector={"app": "api-backend"},
        ports=[("http", 8080, "TCP")],
    )
    fake.add_event(
        reason="Scheduled",
        message="Successfully assigned default/web-frontend to k3d-demo-agent-0",
        component="default-scheduler",
        involved_object="web-frontend-7d4b9c6f5-x2x1p",
    )
    fake.set_pod_logs(
        "default",
        "api-backend-6f5d8b7c9-k3k2m",
        ["listening on :8080", "GET /healthz 200"],
    )
    fake.define_crd("monitoring.io", "UAVMetric", "uavmetrics")
    fake.define_crd("scheduler.io", "SchedulingRequest", "schedulingrequests")
    return fake
