"""Per-node UAV agent: telemetry HTTP API + report push loop.

Parity target: ``/root/reference/cmd/uav-agent/main.go`` — the :9090 HTTP
surface (``GET /health``, ``GET /api/v1/{state,gps,attitude,battery,
flight}``, ``POST /api/v1/command/{arm,disarm,takeoff,land,rtl,mode}``,
main.go:84-280) and the report loop POSTing a full ``UAVReport`` to
``<master>/api/v1/uav/report`` on a ticker with the first report sent
immediately (main.go:326-416). Identity comes from flags/env
(NODE_NAME/NODE_IP/MASTER_URL/REPORT_INTERVAL, main.go:27-63).
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

from k8s_llm_monitor_tpu.monitor.models import rfc3339, utcnow
from k8s_llm_monitor_tpu.monitor.uav import MAVLinkSimulator

logger = logging.getLogger("monitor.agent")


class UAVAgent:
    def __init__(
        self,
        node_name: str,
        node_ip: str = "",
        uav_id: str = "",
        port: int = 9090,
        master_url: str = "",
        report_interval: float = 10.0,
        poster=None,  # injectable for tests: poster(url, payload_dict)
    ) -> None:
        self.node_name = node_name
        self.node_ip = node_ip
        self.uav_id = uav_id or f"uav-{node_name}"
        self.port = port
        self.master_url = master_url.rstrip("/")
        self.report_interval = report_interval
        self.simulator = MAVLinkSimulator(self.uav_id, node_name)
        self._poster = poster or self._http_post
        self._httpd: ThreadingHTTPServer | None = None
        self._http_thread: threading.Thread | None = None
        self._report_thread: threading.Thread | None = None
        self._stop = threading.Event()
        self.reports_sent = 0
        self.report_errors = 0

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> None:
        self.simulator.start()
        handler = _make_handler(self)
        self._httpd = ThreadingHTTPServer(("0.0.0.0", self.port), handler)
        self.port = self._httpd.server_address[1]
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="uav-agent-http", daemon=True
        )
        self._http_thread.start()
        if self.master_url:
            self._report_thread = threading.Thread(
                target=self._report_loop, name="uav-agent-report", daemon=True
            )
            self._report_thread.start()
        logger.info(
            "uav-agent for %s serving on :%d (master: %s)",
            self.node_name,
            self.port,
            self.master_url or "<none>",
        )

    def stop(self) -> None:
        self._stop.set()
        self.simulator.stop()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        for t in (self._http_thread, self._report_thread):
            if t is not None:
                t.join(timeout=5)
        self._http_thread = self._report_thread = None

    # -- report push (ref main.go:326-416) ---------------------------------------

    def build_report(self) -> dict[str, Any]:
        state = self.simulator.get_state()
        report: dict[str, Any] = {
            "node_name": self.node_name,
            "uav_id": self.uav_id,
            "source": "agent",
            "status": "active",
            "timestamp": rfc3339(utcnow()),
            "heartbeat_interval_seconds": int(self.report_interval),
            "state": state,
        }
        if self.node_ip:
            report["node_ip"] = self.node_ip
        return report

    def send_report(self) -> bool:
        url = f"{self.master_url}/api/v1/uav/report"
        try:
            self._poster(url, self.build_report())
            self.reports_sent += 1
            return True
        except Exception as exc:  # noqa: BLE001 — loop must survive outages
            self.report_errors += 1
            logger.warning("report to %s failed: %s", url, exc)
            return False

    def _report_loop(self) -> None:
        self.send_report()  # first report immediately (ref main.go:337)
        while not self._stop.wait(self.report_interval):
            self.send_report()

    @staticmethod
    def _http_post(url: str, payload: dict[str, Any]) -> None:
        req = urllib.request.Request(
            url,
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            resp.read()


def _make_handler(agent: UAVAgent) -> type[BaseHTTPRequestHandler]:
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt: str, *args: Any) -> None:
            logger.debug("%s %s", self.address_string(), fmt % args)

        def _json(self, payload: Any, status: int = 200) -> None:
            body = json.dumps(payload).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self) -> None:  # noqa: N802
            state = agent.simulator.get_state()
            routes = {
                "/health": lambda: {
                    "status": "healthy",
                    "uav_id": agent.uav_id,
                    "node_name": agent.node_name,
                    "timestamp": rfc3339(utcnow()),
                },
                "/api/v1/state": lambda: state,
                "/api/v1/gps": lambda: state["gps"],
                "/api/v1/attitude": lambda: state["attitude"],
                "/api/v1/battery": lambda: state["battery"],
                "/api/v1/flight": lambda: state["flight"],
            }
            fn = routes.get(self.path.split("?")[0])
            if fn is None:
                return self._json({"error": "not found"}, 404)
            self._json(fn())

        def do_POST(self) -> None:  # noqa: N802
            path = self.path.split("?")[0]
            if not path.startswith("/api/v1/command/"):
                return self._json({"error": "not found"}, 404)
            command = path[len("/api/v1/command/") :]
            length = int(self.headers.get("Content-Length", 0) or 0)
            try:
                body = json.loads(self.rfile.read(length)) if length else {}
            except json.JSONDecodeError:
                return self._json({"error": "invalid JSON body"}, 400)
            sim = agent.simulator
            ok, detail = True, ""
            if command == "arm":
                ok = sim.arm()
                detail = "" if ok else "arm rejected: no 3D GPS fix"
            elif command == "disarm":
                sim.disarm()
            elif command == "takeoff":
                ok = sim.take_off(float(body.get("altitude", 50.0)))
                detail = "" if ok else "takeoff rejected: not armed"
            elif command == "land":
                sim.land()
            elif command == "rtl":
                sim.return_to_launch()
            elif command == "mode":
                mode = body.get("mode", "")
                if not mode:
                    return self._json({"error": "mode is required"}, 400)
                sim.set_flight_mode(mode)
            else:
                return self._json({"error": f"unknown command {command}"}, 404)
            payload = {
                "status": "success" if ok else "rejected",
                "command": command,
                "timestamp": rfc3339(utcnow()),
            }
            if detail:
                payload["message"] = detail
            self._json(payload, 200 if ok else 409)

    return Handler
