"""Real Kubernetes API-server backend — stdlib HTTP, no client package.

Implements the full ``ClusterBackend`` seam (monitor/cluster.py) against a
live API server, covering what the reference does through client-go:

- kubeconfig parsing (cluster/user/context, token, CA and client cert/key,
  both file and inline base64 ``*-data`` forms) with in-cluster fallback
  (reference ``internal/k8s/client.go:40-45``);
- typed core reads: nodes/pods/services/events/networkpolicies/logs
  (``client.go:153-241``), metrics.k8s.io node/pod usage;
- chunked-JSON **watch streams** for core kinds, CRDs, and custom resources
  (``watcher.go:74-127``, ``crd_watcher.go:85-240``) adapted onto the
  ``WatchStream`` interface (closing the stream severs the HTTP response so
  reader threads exit and the watcher's reconnect loop takes over);
- CRD/CR CRUD incl. the ``/status`` subresource (dynamic-client equivalent,
  ``client.go:255-450``, ``controller.go:223-250``);
- ``pods/exec`` over a WebSocket upgrade (``v4.channel.k8s.io``) for the RTT
  probes — the reference uses SPDY (``rtt_tester.go:170-216``); WebSocket is
  the API server's other supported exec transport and needs no third-party
  dependency.

Error mapping: HTTP 404 → NotFound, 409 → Conflict, anything else →
ClusterError; callers already speak these (monitor/client.py).
"""

from __future__ import annotations

import atexit
import base64
import hashlib
import json
import logging
import os
import secrets
import socket
import ssl
import struct
import tempfile
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from datetime import datetime, timezone
from typing import Any, Iterator

import yaml

from k8s_llm_monitor_tpu.devtools.lockcheck import make_lock
from k8s_llm_monitor_tpu.monitor.cluster import (
    ClusterBackend,
    ClusterError,
    Conflict,
    NotFound,
    WatchStream,
)
from k8s_llm_monitor_tpu.resilience.faults import get_injector
from k8s_llm_monitor_tpu.resilience.retry import (
    Backoff,
    CircuitBreaker,
    CircuitOpen,
)

logger = logging.getLogger("monitor.kube_rest")

_SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

_CORE_KINDS = {"pods", "services", "events"}


class _HttpWatchStream(WatchStream):
    """WatchStream bound to a live chunked HTTP response: closing it also
    severs the response so the blocked reader thread unblocks."""

    def __init__(self, resp) -> None:
        super().__init__()
        self._resp = resp

    def close(self) -> None:
        try:
            self._resp.close()
        except Exception:  # noqa: BLE001 — already dead is fine
            pass
        super().close()


# ---------------------------------------------------------------------------
# WebSocket framing (RFC 6455) — just enough for pods/exec v4.channel.k8s.io
# ---------------------------------------------------------------------------


def ws_accept_key(key: str) -> str:
    """Server handshake accept token for a client Sec-WebSocket-Key."""
    magic = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"
    return base64.b64encode(
        hashlib.sha1((key + magic).encode()).digest()).decode()


def ws_encode_frame(opcode: int, payload: bytes, mask: bool,
                    fin: bool = True) -> bytes:
    """Encode one websocket frame.  Client→server frames are masked;
    ``fin=False`` starts a fragmented message (continuations use opcode 0)."""
    head = bytes([(0x80 if fin else 0x00) | opcode])
    n = len(payload)
    mask_bit = 0x80 if mask else 0
    if n < 126:
        head += bytes([mask_bit | n])
    elif n < 1 << 16:
        head += bytes([mask_bit | 126]) + struct.pack(">H", n)
    else:
        head += bytes([mask_bit | 127]) + struct.pack(">Q", n)
    if mask:
        key = secrets.token_bytes(4)
        body = bytes(b ^ key[i % 4] for i, b in enumerate(payload))
        return head + key + body
    return head + payload


def _read_exact(rfile, n: int) -> bytes:
    """Read exactly n bytes or raise ClusterError (a short read mid-frame
    means the peer died — treating it as data would mis-frame the stream)."""
    buf = b""
    while len(buf) < n:
        chunk = rfile.read(n - len(buf))
        if not chunk:
            raise ClusterError(
                f"exec stream truncated ({len(buf)}/{n} bytes of frame)")
        buf += chunk
    return buf


def ws_read_frame(rfile) -> tuple[bool, int, bytes] | None:
    """Read one frame; returns (fin, opcode, payload), or None on clean EOF
    or a close frame.  Raises ClusterError if the stream dies mid-frame.
    ``fin=False``/opcode 0 frames are fragments of one logical message —
    the caller reassembles (exec_in_pod)."""
    head = rfile.read(2)
    if len(head) < 2:
        return None
    fin = bool(head[0] & 0x80)
    opcode = head[0] & 0x0F
    masked = head[1] & 0x80
    n = head[1] & 0x7F
    if n == 126:
        n = struct.unpack(">H", _read_exact(rfile, 2))[0]
    elif n == 127:
        n = struct.unpack(">Q", _read_exact(rfile, 8))[0]
    key = _read_exact(rfile, 4) if masked else b""
    payload = _read_exact(rfile, n) if n else b""
    if masked and payload:
        payload = bytes(b ^ key[i % 4] for i, b in enumerate(payload))
    if opcode == 0x8:  # close
        return None
    return fin, opcode, payload


# ---------------------------------------------------------------------------
# backend
# ---------------------------------------------------------------------------


class KubeRestBackend(ClusterBackend):
    """ClusterBackend speaking the Kubernetes REST wire format directly."""

    def __init__(
        self,
        base_url: str,
        *,
        token: str | None = None,
        ssl_context: ssl.SSLContext | None = None,
        timeout: float = 15.0,
        watch_timeout: float = 3600.0,
        backoff: Backoff | None = None,
        breaker: CircuitBreaker | None = None,
    ) -> None:
        self.base_url = base_url.rstrip("/")
        self.token = token
        # Every HTTP call carries an explicit socket timeout: ``timeout``
        # for unary requests, ``watch_timeout`` for streams (a watch is
        # *supposed* to idle; a GET is not).  Neither may be None — an
        # unbounded read on a dead apiserver wedges every poll thread.
        self.timeout = float(timeout)
        self.watch_timeout = float(watch_timeout)
        # Retry discipline shared by every unary request; the breaker also
        # gates watch connects so a 5xx storm cannot be amplified by the
        # poll + watcher threads hammering a struggling apiserver.
        self.backoff = backoff or Backoff(
            base_s=0.2, cap_s=5.0, attempts=4)
        self.breaker = breaker or CircuitBreaker(
            failure_threshold=5, cooldown_s=10.0)
        self._sleep = time.sleep  # injectable (tests avoid real sleeps)
        self._ctx = ssl_context
        handlers = []
        if ssl_context is not None:
            handlers.append(urllib.request.HTTPSHandler(context=ssl_context))
        self._opener = urllib.request.build_opener(*handlers)
        # Temp cert/key files (from inline kubeconfig data); unlinked by
        # close() — registered atexit by from_kubeconfig.
        self._tmpfiles: list[str] = []
        # Live watch streams; close() severs them so blocked reader
        # threads exit instead of outliving the backend.
        self._streams_lock = make_lock("kube.streams")
        self._streams: list[_HttpWatchStream] = []

    def close(self) -> None:
        """Tear down in-flight watch streams and remove materialized
        credential files (idempotent)."""
        with self._streams_lock:
            streams = list(self._streams)
            self._streams.clear()
        for s in streams:
            s.close()
        while self._tmpfiles:
            path = self._tmpfiles.pop()
            try:
                os.unlink(path)
            except OSError:
                pass

    # -- construction ---------------------------------------------------

    @classmethod
    def from_kubeconfig(cls, path: str | None = None,
                        context: str | None = None) -> "KubeRestBackend":
        """Build from a kubeconfig file; falls back to in-cluster config
        when no kubeconfig exists (reference client.go:40-45 order is
        kubeconfig-flag → in-cluster)."""
        path = path or os.environ.get("KUBECONFIG") or os.path.expanduser(
            "~/.kube/config")
        if not os.path.exists(path):
            if os.path.exists(os.path.join(_SA_DIR, "token")):
                return cls.in_cluster()
            raise ClusterError(
                f"no kubeconfig at {path} and not running in-cluster")
        with open(path, encoding="utf-8") as fh:
            cfg = yaml.safe_load(fh) or {}

        def _by_name(section: str, name: str) -> dict:
            for item in cfg.get(section, []) or []:
                if item.get("name") == name:
                    return item.get(section.rstrip("s"), {}) or {}
            raise ClusterError(f"kubeconfig: no {section} entry named {name!r}")

        ctx_name = context or cfg.get("current-context")
        if not ctx_name:
            raise ClusterError("kubeconfig has no current-context")
        ctx = _by_name("contexts", ctx_name)
        cluster = _by_name("clusters", ctx.get("cluster", ""))
        user = _by_name("users", ctx.get("user", ""))

        server = cluster.get("server")
        if not server:
            raise ClusterError("kubeconfig cluster entry has no server URL")

        backend = cls.__new__(cls)
        tmpfiles: list[str] = []

        def _materialize(data_key: str, file_key: str, src: dict) -> str | None:
            """Inline base64 data or a file path → a readable file path.
            Inline data (incl. client keys) lands in mode-0600 temp files
            that are unlinked on close()/exit."""
            if src.get(data_key):
                with tempfile.NamedTemporaryFile(
                        mode="wb", suffix=".pem", delete=False) as tmp:
                    tmp.write(base64.b64decode(src[data_key]))
                tmpfiles.append(tmp.name)
                return tmp.name
            return src.get(file_key)

        try:
            ctx_ssl: ssl.SSLContext | None = None
            if server.startswith("https"):
                ctx_ssl = ssl.create_default_context()
                ca = _materialize("certificate-authority-data",
                                  "certificate-authority", cluster)
                if ca:
                    ctx_ssl.load_verify_locations(cafile=ca)
                if cluster.get("insecure-skip-tls-verify"):
                    ctx_ssl.check_hostname = False
                    ctx_ssl.verify_mode = ssl.CERT_NONE
                cert = _materialize("client-certificate-data",
                                    "client-certificate", user)
                key = _materialize("client-key-data", "client-key", user)
                if cert and key:
                    ctx_ssl.load_cert_chain(certfile=cert, keyfile=key)
        except Exception:
            # Don't leave decoded key material behind when construction
            # fails before close() is registered.
            for p in tmpfiles:
                try:
                    os.unlink(p)
                except OSError:
                    pass
            raise

        token = user.get("token")
        backend.__init__(server, token=token, ssl_context=ctx_ssl)
        backend._tmpfiles = tmpfiles
        atexit.register(backend.close)
        return backend

    @classmethod
    def in_cluster(cls) -> "KubeRestBackend":
        """Service-account config from the pod filesystem + env."""
        host = os.environ.get("KUBERNETES_SERVICE_HOST")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        token_path = os.path.join(_SA_DIR, "token")
        ca_path = os.path.join(_SA_DIR, "ca.crt")
        if not host or not os.path.exists(token_path):
            raise ClusterError("not running inside a Kubernetes pod")
        with open(token_path, encoding="utf-8") as fh:
            token = fh.read().strip()
        ctx = ssl.create_default_context()
        if os.path.exists(ca_path):
            ctx.load_verify_locations(cafile=ca_path)
        return cls(f"https://{host}:{port}", token=token, ssl_context=ctx)

    # -- HTTP plumbing --------------------------------------------------

    def _headers(self) -> dict[str, str]:
        h = {"Accept": "application/json"}
        if self.token:
            h["Authorization"] = f"Bearer {self.token}"
        return h

    def _request(
        self,
        path: str,
        params: dict[str, Any] | None = None,
        *,
        method: str = "GET",
        body: dict | None = None,
        raw: bool = False,
        stream: bool = False,
        content_type: str | None = None,
    ) -> Any:
        """One apiserver call with retry + circuit breaking.

        Retriable failures (5xx, timeout, connection errors) retry through
        the jittered ``backoff`` budget — except POSTs (not idempotent: a
        timed-out create may have landed) and streams (the watcher's
        reconnect loop owns that retry).  404/409 are caller-level
        outcomes, not apiserver failures: they close the breaker and never
        retry.  When the breaker is open the call fails fast with
        ``ClusterError`` instead of queueing behind a dead apiserver.
        """
        retriable = not stream and method != "POST"
        attempts = self.backoff.attempts if retriable else 1
        last: Exception | None = None
        for attempt in range(attempts):
            try:
                self.breaker.before_call()
            except CircuitOpen as exc:
                raise ClusterError(f"{method} {path}: {exc}") from exc
            try:
                result = self._request_once(
                    path, params, method=method, body=body,
                    raw=raw, stream=stream, content_type=content_type)
            except (NotFound, Conflict):
                self.breaker.record_success()
                raise
            except ClusterError as exc:
                self.breaker.record_failure()
                last = exc
                if attempt + 1 < attempts:
                    self._sleep(self.backoff.delay(attempt))
                continue
            self.breaker.record_success()
            return result
        assert last is not None
        raise last

    def _request_once(
        self,
        path: str,
        params: dict[str, Any] | None = None,
        *,
        method: str = "GET",
        body: dict | None = None,
        raw: bool = False,
        stream: bool = False,
        content_type: str | None = None,
    ) -> Any:
        faults = get_injector()
        if faults.should_fire("kube_http_timeout"):
            raise ClusterError(
                f"{method} {path} failed: injected: timed out")
        if faults.should_fire("kube_http_reset"):
            raise ClusterError(
                f"{method} {path} failed: injected: connection reset by peer")
        if faults.should_fire("kube_http_5xx"):
            raise ClusterError(
                f"{method} {path} -> 503: injected: apiserver unavailable")
        url = self.base_url + path
        if params:
            url += "?" + urllib.parse.urlencode(params, doseq=True)
        data = None
        headers = self._headers()
        if body is not None:
            data = json.dumps(body).encode()
            headers["Content-Type"] = content_type or "application/json"
        req = urllib.request.Request(url, data=data, headers=headers,
                                     method=method)
        timeout = self.watch_timeout if stream else self.timeout
        try:
            resp = self._opener.open(req, timeout=timeout)
        except urllib.error.HTTPError as exc:
            detail = ""
            try:
                detail = exc.read().decode(errors="replace")[:300]
            except Exception:  # noqa: BLE001
                pass
            msg = f"{method} {path} -> {exc.code}: {detail or exc.reason}"
            if exc.code == 404:
                raise NotFound(msg) from exc
            if exc.code == 409:
                raise Conflict(msg) from exc
            raise ClusterError(msg) from exc
        except (urllib.error.URLError, OSError, TimeoutError) as exc:
            raise ClusterError(f"{method} {path} failed: {exc}") from exc
        if stream:
            return resp
        with resp:
            payload = resp.read()
        if raw:
            return payload.decode(errors="replace")
        return json.loads(payload) if payload else {}

    def _items(self, path: str, params: dict | None = None) -> list[dict]:
        return self._request(path, params).get("items", []) or []

    def _watch(self, path: str, params: dict[str, Any] | None = None) -> WatchStream:
        params = dict(params or {})
        params["watch"] = "1"
        resp = self._request(path, params, stream=True)
        stream = _HttpWatchStream(resp)
        with self._streams_lock:
            self._streams.append(stream)

        def reader() -> None:
            try:
                for line in resp:
                    if stream.closed:
                        break
                    line = line.strip()
                    if not line:
                        continue
                    evt = json.loads(line)
                    typ = evt.get("type", "")
                    if typ in ("ADDED", "MODIFIED", "DELETED"):
                        stream.put(typ, evt.get("object", {}))
                    # BOOKMARK / ERROR events are dropped; an ERROR is
                    # followed by server close → reconnect upstream.
            except Exception as exc:  # noqa: BLE001 — stream died
                logger.debug("watch %s ended: %s", path, exc)
            finally:
                stream.close()
                with self._streams_lock:
                    if stream in self._streams:
                        self._streams.remove(stream)

        threading.Thread(target=reader, daemon=True,
                         name=f"kube-watch{path}").start()
        return stream

    @staticmethod
    def _cr_path(group: str, version: str, plural: str,
                 namespace: str | None, name: str | None = None,
                 subresource: str | None = None) -> str:
        path = f"/apis/{group}/{version}"
        if namespace:
            path += f"/namespaces/{namespace}"
        path += f"/{plural}"
        if name:
            path += f"/{name}"
        if subresource:
            path += f"/{subresource}"
        return path

    # -- discovery / core reads ----------------------------------------

    def server_version(self) -> str:
        info = self._request("/version")
        return info.get("gitVersion", "unknown")

    def list_nodes(self) -> list[dict[str, Any]]:
        return self._items("/api/v1/nodes")

    def list_pods(self, namespace: str) -> list[dict[str, Any]]:
        return self._items(f"/api/v1/namespaces/{namespace}/pods")

    def list_services(self, namespace: str) -> list[dict[str, Any]]:
        return self._items(f"/api/v1/namespaces/{namespace}/services")

    def list_events(self, namespace: str, limit: int = 0) -> list[dict[str, Any]]:
        params = {"limit": limit} if limit > 0 else None
        return self._items(f"/api/v1/namespaces/{namespace}/events", params)

    def list_network_policies(self, namespace: str) -> list[dict[str, Any]]:
        return self._items(
            f"/apis/networking.k8s.io/v1/namespaces/{namespace}/networkpolicies")

    # -- workload scaling (autoscaler executor) -------------------------

    def get_statefulset_scale(self, namespace: str, name: str) -> dict[str, Any]:
        """The ``/scale`` subresource of one StatefulSet (spec.replicas is
        desired, status.replicas is observed)."""
        return self._request(
            f"/apis/apps/v1/namespaces/{namespace}/statefulsets/{name}/scale")

    def scale_statefulset(self, namespace: str, name: str, replicas: int,
                          dry_run: bool = False) -> dict[str, Any]:
        """PATCH the ``/scale`` subresource to ``replicas``.  Merge-patch
        on the scale object is idempotent, so it rides the normal retry
        budget (PATCH != POST).  ``dry_run=True`` sends ``dryRun=All`` —
        full apiserver validation + admission, no persistence — which is
        how the autoscaler proves a scale verb works before using it."""
        params = {"dryRun": "All"} if dry_run else None
        return self._request(
            f"/apis/apps/v1/namespaces/{namespace}/statefulsets/{name}/scale",
            params,
            method="PATCH",
            body={"spec": {"replicas": int(replicas)}},
            content_type="application/merge-patch+json")

    def list_statefulsets(self, namespace: str) -> list[dict[str, Any]]:
        return self._items(
            f"/apis/apps/v1/namespaces/{namespace}/statefulsets")

    # -- remediation verbs ----------------------------------------------
    # Everything the remediation executor may do to a live cluster.  All
    # writes support the server-side ``dryRun=All`` probe (full apiserver
    # validation + admission, no persistence), which is how plans are
    # validated before the real mutation.  PATCH/DELETE are idempotent,
    # so they ride the normal retry budget like ``scale_statefulset``.

    def rollout_restart(self, namespace: str, name: str,
                        dry_run: bool = False) -> dict[str, Any]:
        """The ``kubectl rollout restart`` idiom: merge-patch a
        ``restartedAt`` pod-template annotation so the controller rolls
        every pod.  Tries Deployment first, falls back to StatefulSet."""
        params = {"dryRun": "All"} if dry_run else None
        body = {"spec": {"template": {"metadata": {"annotations": {
            "kubectl.kubernetes.io/restartedAt":
                datetime.now(timezone.utc).isoformat(),
        }}}}}
        for kind in ("deployments", "statefulsets"):
            try:
                return self._request(
                    f"/apis/apps/v1/namespaces/{namespace}/{kind}/{name}",
                    params,
                    method="PATCH",
                    body=body,
                    content_type="application/merge-patch+json")
            except NotFound:
                continue
        raise NotFound(f"no deployment or statefulset {namespace}/{name}")

    def cordon_node(self, name: str, dry_run: bool = False) -> dict[str, Any]:
        params = {"dryRun": "All"} if dry_run else None
        return self._request(
            f"/api/v1/nodes/{name}",
            params,
            method="PATCH",
            body={"spec": {"unschedulable": True}},
            content_type="application/merge-patch+json")

    def delete_pod(self, namespace: str, name: str,
                   dry_run: bool = False) -> dict[str, Any]:
        params = {"dryRun": "All"} if dry_run else None
        return self._request(
            f"/api/v1/namespaces/{namespace}/pods/{name}",
            params,
            method="DELETE")

    def pod_logs(self, namespace: str, name: str, tail_lines: int = 100) -> str:
        return self._request(
            f"/api/v1/namespaces/{namespace}/pods/{name}/log",
            {"tailLines": tail_lines}, raw=True)

    # -- metrics.k8s.io -------------------------------------------------

    def node_usage(self) -> list[dict[str, Any]]:
        return self._items("/apis/metrics.k8s.io/v1beta1/nodes")

    def pod_usage(self, namespace: str) -> list[dict[str, Any]]:
        return self._items(
            f"/apis/metrics.k8s.io/v1beta1/namespaces/{namespace}/pods")

    # -- exec (WebSocket, v4.channel.k8s.io) ----------------------------

    def exec_in_pod(
        self, namespace: str, pod: str, command: list[str], timeout: float = 10.0
    ) -> tuple[str, str, int]:
        query = urllib.parse.urlencode(
            [("command", c) for c in command]
            + [("stdout", "true"), ("stderr", "true"),
               ("stdin", "false"), ("tty", "false")],
        )
        u = urllib.parse.urlparse(self.base_url)
        # Preserve any path prefix (proxied API servers, e.g. /k8s/clusters/x)
        # just like _request's base_url + path concatenation.
        prefix = u.path.rstrip("/")
        path = (f"{prefix}/api/v1/namespaces/{namespace}/pods/{pod}/exec"
                f"?{query}")
        host = u.hostname or "localhost"
        port = u.port or (443 if u.scheme == "https" else 80)

        try:
            sock = socket.create_connection((host, port), timeout=timeout)
        except OSError as exc:
            raise ClusterError(f"exec connect failed: {exc}") from exc
        try:
            if u.scheme == "https":
                ctx = self._ctx or ssl.create_default_context()
                sock = ctx.wrap_socket(sock, server_hostname=host)
            key = base64.b64encode(secrets.token_bytes(16)).decode()
            headers = [
                f"GET {path} HTTP/1.1",
                f"Host: {host}:{port}",
                "Upgrade: websocket",
                "Connection: Upgrade",
                f"Sec-WebSocket-Key: {key}",
                "Sec-WebSocket-Version: 13",
                "Sec-WebSocket-Protocol: v4.channel.k8s.io",
            ]
            if self.token:
                headers.append(f"Authorization: Bearer {self.token}")
            sock.sendall(("\r\n".join(headers) + "\r\n\r\n").encode())

            rfile = sock.makefile("rb")
            status = rfile.readline().decode(errors="replace")
            if "101" not in status.split(" ", 2)[1:2] and " 101 " not in status:
                # Drain headers for a useful error message.
                while rfile.readline().strip():
                    pass
                raise ClusterError(f"exec upgrade refused: {status.strip()}")
            accept_hdr = ""
            while True:
                line = rfile.readline().strip()
                if not line:
                    break  # end of response headers
                name, _, value = line.decode(errors="replace").partition(":")
                if name.strip().lower() == "sec-websocket-accept":
                    accept_hdr = value.strip()
            if accept_hdr != ws_accept_key(key):
                # RFC 6455 makes the header mandatory: absent or wrong both
                # mean we are NOT talking to the websocket peer we keyed.
                raise ClusterError(
                    "exec upgrade failed: Sec-WebSocket-Accept "
                    f"{'missing' if not accept_hdr else 'mismatch'} "
                    "(not a websocket peer or a tampering intermediary)")

            # Overall wall-clock deadline: the per-read socket timeout only
            # bounds silence — a command streaming slowly forever would
            # otherwise hold the call open indefinitely.
            deadline = time.monotonic() + timeout
            stdout, stderr, status_json = b"", b"", b""
            frag = b""            # partial fragmented message
            fragmenting = False
            while True:
                if time.monotonic() > deadline:
                    raise ClusterError(
                        f"exec timed out after {timeout:.0f}s (output still "
                        f"streaming)")
                frame = ws_read_frame(rfile)
                if frame is None:
                    break
                fin, opcode, payload = frame
                if opcode == 0x9:  # ping -> pong
                    sock.sendall(ws_encode_frame(0xA, payload, mask=True))
                    continue
                # Reassemble fragmented messages before demuxing: the k8s
                # channel id is the first byte of the *message*, which may
                # arrive in any fragment (even an empty first frame).
                if opcode == 0x0:
                    if not fragmenting:
                        continue  # stray continuation
                    frag += payload
                    if not fin:
                        continue
                    msg, frag, fragmenting = frag, b"", False
                elif not fin:
                    frag, fragmenting = payload, True
                    continue
                else:
                    msg = payload
                if not msg:
                    continue
                channel, data = msg[0], msg[1:]
                if channel == 1:
                    stdout += data
                elif channel == 2:
                    stderr += data
                elif channel == 3:
                    status_json += data
            exit_code = _parse_exec_status(status_json)
            return (stdout.decode(errors="replace"),
                    stderr.decode(errors="replace"), exit_code)
        except (OSError, TimeoutError) as exc:
            raise ClusterError(f"exec failed: {exc}") from exc
        finally:
            try:
                sock.sendall(ws_encode_frame(0x8, b"", mask=True))
            except OSError:
                pass
            sock.close()

    # -- watches --------------------------------------------------------

    def watch(self, kind: str, namespace: str) -> WatchStream:
        if kind not in _CORE_KINDS:
            raise ClusterError(f"unsupported watch kind {kind!r}")
        return self._watch(f"/api/v1/namespaces/{namespace}/{kind}")

    def watch_crds(self) -> WatchStream:
        return self._watch(
            "/apis/apiextensions.k8s.io/v1/customresourcedefinitions")

    def watch_custom_resources(
        self, group: str, version: str, plural: str, namespace: str | None
    ) -> WatchStream:
        return self._watch(self._cr_path(group, version, plural, namespace))

    # -- CRDs / custom resources ---------------------------------------

    def list_crds(self) -> list[dict[str, Any]]:
        return self._items(
            "/apis/apiextensions.k8s.io/v1/customresourcedefinitions")

    def list_custom_resources(
        self, group: str, version: str, plural: str, namespace: str | None
    ) -> list[dict[str, Any]]:
        return self._items(self._cr_path(group, version, plural, namespace))

    def get_custom_resource(
        self, group: str, version: str, plural: str, namespace: str | None, name: str
    ) -> dict[str, Any]:
        return self._request(
            self._cr_path(group, version, plural, namespace, name))

    def create_custom_resource(
        self, group: str, version: str, plural: str, namespace: str | None,
        body: dict[str, Any],
    ) -> dict[str, Any]:
        return self._request(
            self._cr_path(group, version, plural, namespace),
            method="POST", body=body)

    def update_custom_resource(
        self, group: str, version: str, plural: str, namespace: str | None,
        body: dict[str, Any],
    ) -> dict[str, Any]:
        name = (body.get("metadata") or {}).get("name")
        if not name:
            raise ClusterError("update_custom_resource: body has no metadata.name")
        return self._request(
            self._cr_path(group, version, plural, namespace, name),
            method="PUT", body=body)

    def update_custom_resource_status(
        self, group: str, version: str, plural: str, namespace: str | None,
        body: dict[str, Any],
    ) -> dict[str, Any]:
        name = (body.get("metadata") or {}).get("name")
        if not name:
            raise ClusterError(
                "update_custom_resource_status: body has no metadata.name")
        return self._request(
            self._cr_path(group, version, plural, namespace, name, "status"),
            method="PUT", body=body)


def _parse_exec_status(status_json: bytes) -> int:
    """v4.channel.k8s.io channel-3 payload → exit code.

    ``{"status":"Success"}`` → 0; Failure carries the code in
    details.causes[reason=ExitCode].message; missing/unparseable → 1.
    """
    if not status_json:
        return 0
    try:
        status = json.loads(status_json)
    except json.JSONDecodeError:
        return 1
    if status.get("status") == "Success":
        return 0
    for cause in (status.get("details") or {}).get("causes", []) or []:
        if cause.get("reason") == "ExitCode":
            try:
                return int(cause.get("message", 1))
            except ValueError:
                return 1
    return 1
