"""The Analysis Engine — LLM-backed cluster diagnosis on TPU.

This is the component the reference only sketched: its entire LLM
integration is config keys (``/root/reference/internal/config/config.go:
141-145,174-180``), the ``/api/v1/query`` endpoint is documented
(``README.md:89-95``) but never registered, and the analysis-type enum
(``pkg/models/models.go:87``: pod_communication / anomaly_detection /
root_cause) has no implementation behind it. Here all three types are
implemented, backed by the in-tree JAX/Pallas serving stack
(``k8s_llm_monitor_tpu.serving``) instead of a remote OpenAI call.

Pieces:
- ``LLMBackend`` seam with three implementations: ``LocalEngineBackend``
  (TPU inference via ``InferenceEngine``), ``OpenAICompatBackend`` (the
  reference's remote path, kept for parity), and ``TemplateBackend``
  (deterministic evidence summarizer — dev mode / tests without a model).
- ``EvidenceCollector``: assembles bounded cluster evidence (snapshot +
  events + logs, capped by ``analysis.max_context_events`` like ref
  config.go:94) into prompt sections.
- ``AnalysisEngine``: the three analyzers + free-form ``query``.
"""

from __future__ import annotations

import http.client
import json
import logging
import re
import time
import urllib.error
import urllib.request
import uuid
from typing import Any

from k8s_llm_monitor_tpu.monitor.client import Client
from k8s_llm_monitor_tpu.monitor.cluster import ClusterError
from k8s_llm_monitor_tpu.monitor.config import (
    AnalysisConfig,
    LifecycleConfig,
    LLMConfig,
)
from k8s_llm_monitor_tpu.diagnosis.grammar import (
    GrammarError,
    parse_verdict,
    render_verdict,
)
from k8s_llm_monitor_tpu.diagnosis.session import SessionManager
from k8s_llm_monitor_tpu.resilience.errors import OverloadedError
from k8s_llm_monitor_tpu.monitor.manager import Manager
from k8s_llm_monitor_tpu.monitor.models import (
    ANALYSIS_TYPES,
    AnalysisRequest,
    AnalysisResponse,
    to_jsonable,
    utcnow,
)
from k8s_llm_monitor_tpu.monitor.network import NetworkAnalyzer

logger = logging.getLogger("monitor.analysis")


# ---------------------------------------------------------------------------
# LLM backends
# ---------------------------------------------------------------------------


class LLMBackend:
    name = "base"

    def generate(
        self, prompt: str, max_tokens: int = 512, temperature: float = 0.1,
        slo_class: str = "standard", tenant: str = "",
    ) -> str:
        # ``slo_class`` and ``tenant`` are scheduling/accounting metadata
        # for backends with an admission layer (LocalEngineBackend);
        # remote/template backends accept and ignore them so callers can
        # tag unconditionally.  ``tenant=""`` means the default tenant.
        raise NotImplementedError

    def generate_stream(
        self, prompt: str, max_tokens: int = 512, temperature: float = 0.1,
        slo_class: str = "standard", tenant: str = "",
    ):
        """Yield text chunks.  Backends without true streaming yield the
        whole completion once (keeps the SSE route backend-agnostic)."""
        yield self.generate(prompt, max_tokens=max_tokens,
                            temperature=temperature, slo_class=slo_class,
                            tenant=tenant)

    def generate_constrained(self, prompt: str,
                             temperature: float = 0.0,
                             slo_class: str = "standard",
                             tenant: str = "") -> str:
        """Return Verdict JSON valid under ``diagnosis.grammar``'s schema.

        Default path for backends without token-level masking (remote
        endpoints can't apply per-step logit masks): generate free text and
        fold it into a canonical verdict via ``render_verdict``, so the
        contract — output always parses — holds even when the model
        rambles.  ``LocalEngineBackend`` overrides this with true on-device
        FSM-constrained decoding.
        """
        text = self.generate(prompt, max_tokens=512,
                             temperature=temperature,
                             slo_class=slo_class, tenant=tenant).strip()
        try:
            parse_verdict(text)
            return text
        except GrammarError:
            pass
        low = text.lower()
        if any(w in low for w in ("crash", "oom", "fail", "critical",
                                  "unreachable", "down")):
            severity = "critical"
        elif any(w in low for w in ("warn", "pressure", "restart",
                                    "degrad", "evict")):
            severity = "warning"
        else:
            severity = "info"
        return render_verdict(
            severity, "cluster", text,
            "see root_cause; re-run the diagnosis after remediation", 0.3)

    #: True only for backends that can decode under an arbitrary token FSM
    #: (``LocalEngineBackend`` with the byte tokenizer).  Callers check it
    #: before compiling a grammar nobody will use.
    supports_grammar = False

    def generate_with_grammar(self, prompt: str, fsm,
                              temperature: float = 0.0,
                              slo_class: str = "standard",
                              tenant: str = "") -> str:
        """Decode under a caller-supplied ``TokenFSM`` (the remediation
        plan grammar).  Backends without token-level masking return ""
        so callers fall back to their deterministic renderers — remote
        endpoints cannot apply per-step logit masks, and free text run
        through an arbitrary grammar would almost never parse."""
        return ""


class TemplateBackend(LLMBackend):
    """Deterministic diagnosis text from the prompt's evidence sections.

    Serves dev mode (no model weights) and keeps API tests fast; the output
    shape matches what the LLM path produces (diagnosis + recommendation).
    """

    name = "template"

    def generate(
        self, prompt: str, max_tokens: int = 512, temperature: float = 0.1,
        slo_class: str = "standard", tenant: str = "",
    ) -> str:
        issues = [
            line.strip("- ").strip()
            for line in prompt.splitlines()
            if line.lstrip().startswith("- ") and "##" not in line
        ]
        if issues:
            listed = "; ".join(issues[:5])
            return (
                f"Diagnosis: {len(issues)} finding(s) in the collected evidence: "
                f"{listed}. Recommendation: address the findings above in order; "
                "re-run the analysis after each fix to confirm resolution."
            )
        return (
            "Diagnosis: no anomalies detected in the collected evidence. "
            "The cluster appears healthy; no action required."
        )

    def generate_constrained(self, prompt: str,
                             temperature: float = 0.0,
                             slo_class: str = "standard",
                             tenant: str = "") -> str:
        """Deterministic grammar-valid verdict from the evidence sections —
        same extraction as ``generate``, rendered through the canonical
        serializer so it parses under the verdict grammar by construction."""
        issues = [
            line.strip("- ").strip()
            for line in prompt.splitlines()
            if line.lstrip().startswith("- ") and "##" not in line
        ]
        if not issues:
            return render_verdict(
                "info", "cluster",
                "no anomalies detected in the collected evidence",
                "no action required", 0.9)
        low = " ".join(issues).lower()
        if any(w in low for w in ("crashloop", "crash", "oom", "failed",
                                  "notready", "unreachable")):
            severity = "critical"
        else:
            severity = "warning"
        pod = re.search(r'"pod": "([^"]+)"', prompt)
        component = pod.group(1) if pod else "cluster"
        return render_verdict(
            severity, component,
            f"{len(issues)} finding(s): {'; '.join(issues[:3])}",
            "address the findings in order; re-run the analysis after "
            "each fix", 0.6)


class LocalEngineBackend(LLMBackend):
    """In-process TPU inference through the continuous-batching engine.

    Thread-safe and genuinely concurrent: a background ``EngineService``
    thread owns the engine's step loop, and each generate() call submits a
    request and waits on its handle — so N concurrent HTTP requests share
    prefill batches and decode steps instead of serializing.
    """

    name = "tpu-local"

    # Generations that outlive this are failed (queue + decode worst case).
    GENERATION_TIMEOUT_S = 600.0

    def __init__(self, engine=None, tokenizer=None, *,
                 dev_weights: bool = False, engine_factory=None,
                 lifecycle: LifecycleConfig | None = None,
                 governor=None) -> None:
        """Two construction modes:

        * ``engine=`` (tests, ad-hoc wiring): the service wraps the given
          engine directly — a dead step loop is terminal, exactly the PR 2
          behavior.
        * ``engine_factory=`` (server boot via ``from_config``): an
          ``EngineSupervisor`` owns the service, journals admits when
          ``lifecycle.journal_dir`` is set, and rebuilds + replays on a
          dead/wedged step loop.
        """
        from k8s_llm_monitor_tpu.serving.service import EngineService

        self.tokenizer = tokenizer
        self.supervisor = None
        self._service = None
        # resilience.tenancy.TenantGovernor (or None): per-tenant admission
        # quotas on single-replica roles.  Owned here (above the supervisor)
        # so reservations survive engine rebuilds; the HTTP layer reads it
        # for /api/v1/stats and the tenant_* exporter families.
        self.governor = governor
        if engine_factory is not None:
            from k8s_llm_monitor_tpu.resilience.journal import RequestJournal
            from k8s_llm_monitor_tpu.resilience.retry import Backoff
            from k8s_llm_monitor_tpu.serving.supervisor import EngineSupervisor

            lc = lifecycle or LifecycleConfig()
            journal = None
            if lc.journal_dir:
                journal = RequestJournal(
                    lc.journal_dir,
                    segment_max_bytes=lc.journal_segment_mb << 20,
                    fsync=lc.journal_fsync)
            self.supervisor = EngineSupervisor(
                engine_factory,
                journal=journal,
                max_restarts=lc.max_restarts,
                heartbeat_timeout_s=lc.heartbeat_timeout_s,
                backoff=Backoff(base_s=lc.restart_backoff_s,
                                cap_s=max(lc.restart_backoff_s * 8, 5.0),
                                jitter=0.0),
                governor=governor)
        else:
            assert engine is not None, "engine or engine_factory required"
            self._service = EngineService(engine, governor=governor)
            if getattr(engine, "_grammar", None) is None:
                self._install_verdict_grammar(engine, tokenizer)
        # Decode-rate EMAs (ms/token) for the exporter's
        # constrained_decode_overhead_ms gauge; plain float stores, benign
        # under concurrent generate() threads.
        self._ema_ms_constrained: float | None = None
        self._ema_ms_free: float | None = None
        # Serializes generate_with_grammar()'s set-grammar/decode/restore
        # window against itself.  The diagnosis pipeline worker is the
        # only constrained caller in-process, so a swap never races an
        # in-flight constrained decode.
        from k8s_llm_monitor_tpu.devtools.lockcheck import make_lock

        self._grammar_swap_lock = make_lock("analysis.grammar_swap")
        if dev_weights:
            # Random-init weights + byte tokenizer produce byte soup; make
            # that loud in every API response's `model` field instead of
            # presenting it as a real diagnosis backend.
            self.name = "tpu-local-DEV-RANDOM-WEIGHTS"
            logger.warning(
                "TPU backend running with RANDOM-INIT weights (no "
                "llm.tpu.checkpoint configured) — answers are not "
                "meaningful; set llm.tpu.checkpoint for real diagnosis")

    @property
    def service(self):
        """The live EngineService — the supervisor's current one when
        supervised (it changes across rebuilds), else the pinned one."""
        if self.supervisor is not None:
            return self.supervisor.service
        return self._service

    @property
    def engine(self):
        return self.service.engine

    def _submit(self, prompt_ids, sampling, slo_class: str = "standard",
                tenant: str = ""):
        if self.supervisor is not None:
            return self.supervisor.submit(prompt_ids, sampling,
                                          slo_class=slo_class,
                                          tenant=tenant)
        return self.service.submit(prompt_ids, sampling,
                                   slo_class=slo_class, tenant=tenant)

    def brownout_level(self) -> int:
        """Current brownout rung (0=normal, 1=degraded, 2=draining) from
        the live service's controller; 0 when no service is up."""
        svc = self.service
        if svc is None or getattr(svc, "brownout", None) is None:
            return 0
        return svc.brownout.level()

    @staticmethod
    def _install_verdict_grammar(engine, tokenizer) -> bool:
        """Register the Verdict token-FSM on a fresh engine.

        Byte tokenizer only: the grammar's char→token lift (token =
        byte + 3) is exact for ``ByteTokenizer``; HF/BPE tokenizers would
        need a subword-aware compile, so constrained submits are refused
        for them (``generate_constrained`` falls back to the render path)
        instead of silently emitting garbage.
        """
        from k8s_llm_monitor_tpu.diagnosis.grammar import verdict_fsm
        from k8s_llm_monitor_tpu.utils.tokenizer import ByteTokenizer

        if not isinstance(tokenizer, ByteTokenizer):
            return False
        if engine.cfg.vocab_size < ByteTokenizer.vocab_size:
            return False
        try:
            engine.set_grammar(verdict_fsm(eos_id=tokenizer.eos_id))
        except ValueError as exc:
            logger.warning("verdict grammar not installed: %s", exc)
            return False
        return True

    def _note_decode_ms(self, constrained: bool, n_tokens: int,
                        latency_s: float, ttft_s: float) -> None:
        if n_tokens <= 1:
            return
        ms = max(0.0, latency_s - ttft_s) * 1000.0 / (n_tokens - 1)
        attr = "_ema_ms_constrained" if constrained else "_ema_ms_free"
        prev = getattr(self, attr)
        setattr(self, attr, ms if prev is None else 0.8 * prev + 0.2 * ms)

    @property
    def constrained_decode_overhead_ms(self) -> float:
        """Per-token decode cost of FSM masking: EMA(constrained) −
        EMA(free), clamped at 0; 0.0 until both classes have samples."""
        if self._ema_ms_constrained is None or self._ema_ms_free is None:
            return 0.0
        return max(0.0, self._ema_ms_constrained - self._ema_ms_free)

    @classmethod
    def from_config(cls, tpu_cfg, lifecycle=None,
                    tenancy=None) -> "LocalEngineBackend":
        """Build from ``LLMConfig.tpu``: checkpoint weights or random-init
        dev weights for the named preset.  ``tenancy`` (TenancyConfig)
        arms the per-tenant admission governor and the KV fairness cap."""
        import jax

        # One normalization for the preflight AND the engine build below:
        # 'int8'/'w8a8' are real modes, anything else is bf16.
        qmode = getattr(tpu_cfg, "quantize", "")
        quantize = qmode in ("int8", "w8a8")

        # Fit preflight (cmd/preflight): shapes-only, so it warns about an
        # over-budget config BEFORE the multi-GiB weight build OOMs the
        # chip mid-load.  Warn-only — boot proceeds regardless.
        try:
            import contextlib
            import io

            from k8s_llm_monitor_tpu.cmd.preflight import check as _preflight

            # --quantize is always passed (preflight's own default is
            # w8a8, which would size int8 weights for a bf16 config —
            # exactly the over-budget case this warning exists for).
            # The workload shape mirrors what the engine can actually
            # hold per sequence (EngineConfig default max_blocks_per_seq
            # 64 x block 16 = 1024 tokens; longer requests are truncated
            # at submit), so FAIL here means "cannot serve even one
            # engine-shaped request".
            argv = ["--kv-blocks", str(tpu_cfg.kv_blocks),
                    "--quantize", qmode if quantize else "none",
                    "--prompt-len", "768", "--max-tokens", "256"]
            if tpu_cfg.checkpoint:
                argv += ["--checkpoint", tpu_cfg.checkpoint]
            else:
                argv += ["--model", tpu_cfg.model]
            if tpu_cfg.mesh_shape:
                argv += ["--mesh", tpu_cfg.mesh_shape]
            buf = io.StringIO()
            with contextlib.redirect_stdout(buf), \
                    contextlib.redirect_stderr(buf):
                rc, fails, warns = _preflight(argv)
            if warns:
                # Context even when a FAIL follows (e.g. "fit checks
                # skipped" qualifies what the verdict did NOT cover).
                logger.info("TPU config preflight warnings: %s",
                            "; ".join(warns))
            if rc != 0:
                logger.warning(
                    "TPU config preflight FAILED (boot continues): %s — "
                    "run `python -m k8s_llm_monitor_tpu.cmd.preflight` "
                    "for the full report", "; ".join(fails) or "see report")
        # SystemExit included: argparse exits on bad flag values, and
        # preflight must never block boot.  The debug line keeps a broken
        # preflight observable instead of silently disabling the check.
        except (Exception, SystemExit) as exc:  # noqa: BLE001
            logger.debug("TPU config preflight skipped: %s", exc,
                         exc_info=True)

        from k8s_llm_monitor_tpu.models import llama
        from k8s_llm_monitor_tpu.models.config import PRESETS
        from k8s_llm_monitor_tpu.serving.engine import EngineConfig, InferenceEngine
        from k8s_llm_monitor_tpu.utils.tokenizer import load_tokenizer

        dev_weights = not tpu_cfg.checkpoint
        if tpu_cfg.checkpoint:
            from k8s_llm_monitor_tpu.utils.checkpoint import load_hf_checkpoint

            # int8 streams each tensor through host-side quantization — the
            # only way 8B-class checkpoints fit a 16 GB chip (utils/quantize).
            cfg, params = load_hf_checkpoint(tpu_cfg.checkpoint,
                                             quantize=quantize)
            tokenizer = load_tokenizer(tpu_cfg.checkpoint)
        else:
            cfg = PRESETS[tpu_cfg.model]
            if quantize:
                from k8s_llm_monitor_tpu.utils.quantize import (
                    init_params_quantized,
                )

                params = init_params_quantized(jax.random.PRNGKey(0), cfg)
            else:
                params = llama.init_params(jax.random.PRNGKey(0), cfg)
            tokenizer = load_tokenizer(None)

        if qmode == "w8a8":
            # s8 x s8 prefill on the MXU int8 path (measured ~1.4x prefill rate
            # and the only mode meeting every short-leg SLO);
            # see utils/quantize.py and the bench's W8A8 legs.
            import dataclasses as _dc

            cfg = _dc.replace(cfg, act_quant=True)

        mesh = None
        if tpu_cfg.mesh_shape:
            from k8s_llm_monitor_tpu.parallel.mesh import MeshConfig, create_mesh

            data, seq, model = (int(x) for x in tpu_cfg.mesh_shape.split(","))
            mesh = create_mesh(MeshConfig(data=data, seq=seq, model=model))

        # Factory, not a single engine: the supervisor rebuilds through
        # this closure after a step-loop death, reusing the (expensive)
        # params/tokenizer while the KV allocator and slot table start
        # from baseline by construction.  Weights are jax.Arrays the dead
        # engine never mutates, so reuse is safe.
        max_kv_share = (float(tenancy.max_kv_share)
                        if tenancy is not None else 1.0)

        def engine_factory() -> InferenceEngine:
            engine = InferenceEngine(
                cfg,
                params,
                EngineConfig(max_slots=tpu_cfg.max_batch,
                             num_blocks=tpu_cfg.kv_blocks,
                             spec_k=tpu_cfg.spec_k,
                             spec_min_accept=tpu_cfg.spec_min_accept,
                             kv_max_tenant_share=max_kv_share),
                tokenizer=tokenizer,
                mesh=mesh,
            )
            # Inside the factory, not after it: supervisor rebuilds go
            # through this closure, and a rebuilt engine without the
            # grammar would reject every constrained submit.
            cls._install_verdict_grammar(engine, tokenizer)
            return engine

        governor = None
        if tenancy is not None and tenancy.enabled:
            from k8s_llm_monitor_tpu.resilience.tenancy import TenantGovernor

            governor = TenantGovernor(
                requests_per_s=tenancy.requests_per_s,
                request_burst=tenancy.request_burst,
                tokens_per_s=tenancy.tokens_per_s,
                token_burst=tenancy.token_burst,
                enforce=tenancy.enforce,
                max_tenants=tenancy.max_tenants)

        return cls(tokenizer=tokenizer, dev_weights=dev_weights,
                   engine_factory=engine_factory, lifecycle=lifecycle,
                   governor=governor)

    def generate(
        self, prompt: str, max_tokens: int = 512, temperature: float = 0.1,
        slo_class: str = "standard", tenant: str = "",
    ) -> str:
        from k8s_llm_monitor_tpu.serving.engine import SamplingParams

        handle = self._submit(
            self.tokenizer.encode(prompt),
            SamplingParams(max_tokens=max_tokens, temperature=temperature),
            slo_class=slo_class, tenant=tenant,
        )
        res = handle.result(timeout=self.GENERATION_TIMEOUT_S)
        if res.finish_reason == "error":
            raise RuntimeError(f"generation failed: {res.error}")
        self._note_decode_ms(False, len(res.token_ids),
                             res.latency_s, res.ttft_s)
        return self.tokenizer.decode(res.token_ids)

    def generate_constrained(self, prompt: str,
                             temperature: float = 0.0,
                             slo_class: str = "standard",
                             tenant: str = "") -> str:
        """True grammar-constrained decoding: the verdict FSM's per-step
        logit masks run inside the engine's on-device sampler, so the raw
        token stream IS the verdict JSON — no post-hoc repair.  Falls back
        to the base render path when no grammar is registered (HF
        tokenizer, undersized vocab)."""
        from k8s_llm_monitor_tpu.serving.engine import SamplingParams

        try:
            has_grammar = getattr(self.engine, "_grammar", None) is not None
        except Exception:  # noqa: BLE001 — supervisor mid-rebuild
            has_grammar = False
        if not has_grammar:
            return super().generate_constrained(prompt,
                                                temperature=temperature,
                                                slo_class=slo_class,
                                                tenant=tenant)
        handle = self._submit(
            self.tokenizer.encode(prompt),
            # max_tokens=1 is a floor: submit() raises it to the grammar's
            # max accepting path so the verdict can always close.
            SamplingParams(max_tokens=1, temperature=temperature,
                           constrained=True),
            slo_class=slo_class, tenant=tenant,
        )
        res = handle.result(timeout=self.GENERATION_TIMEOUT_S)
        if res.finish_reason == "error":
            raise RuntimeError(f"constrained generation failed: {res.error}")
        self._note_decode_ms(True, len(res.token_ids),
                             res.latency_s, res.ttft_s)
        return self.tokenizer.decode(res.token_ids).strip()

    @property
    def supports_grammar(self) -> bool:
        """Grammar swaps need an engine that already passed the verdict
        -grammar install gates (byte tokenizer, vocab ≥ 259)."""
        try:
            return getattr(self.engine, "_grammar", None) is not None
        except Exception:  # noqa: BLE001 — supervisor mid-rebuild
            return False

    def generate_with_grammar(self, prompt: str, fsm,
                              temperature: float = 0.0,
                              slo_class: str = "standard",
                              tenant: str = "") -> str:
        """Constrained decode under a caller-supplied FSM (the remediation
        plan grammar): save the installed verdict grammar, swap in the
        plan FSM, decode, restore.  Plan FSMs are padded to one fixed
        table shape (``plans.PLAN_STATE_CAP``), and the engine treats the
        table as a runtime argument — so the swap is recompile-free after
        the first plan decode warms its shape (traceguard ``grammar_swap``
        path proves it)."""
        from k8s_llm_monitor_tpu.serving.engine import SamplingParams

        with self._grammar_swap_lock:
            try:
                engine = self.engine
            except Exception:  # noqa: BLE001 — supervisor mid-rebuild
                return ""
            saved = getattr(engine, "_grammar", None)
            if saved is None:
                return ""  # verdict install already refused this engine
            try:
                engine.set_grammar(fsm)
            except ValueError as exc:
                logger.warning("plan grammar rejected by engine: %s", exc)
                return ""
            try:
                handle = self._submit(
                    self.tokenizer.encode(prompt),
                    # max_tokens=1 is a floor: submit() raises it to the
                    # plan grammar's max accepting path.
                    SamplingParams(max_tokens=1, temperature=temperature,
                                   constrained=True),
                    slo_class=slo_class, tenant=tenant,
                )
                res = handle.result(timeout=self.GENERATION_TIMEOUT_S)
            finally:
                engine.set_grammar(saved)
        if res.finish_reason == "error":
            raise RuntimeError(f"plan generation failed: {res.error}")
        self._note_decode_ms(True, len(res.token_ids),
                             res.latency_s, res.ttft_s)
        return self.tokenizer.decode(res.token_ids).strip()

    def generate_stream(
        self, prompt: str, max_tokens: int = 512, temperature: float = 0.1,
        slo_class: str = "standard", tenant: str = "",
    ):
        """Yield decoded text increments as tokens come off the device.

        Decodes cumulatively and emits suffixes so multi-byte/multi-token
        graphemes never split mid-character.
        """
        from k8s_llm_monitor_tpu.serving.engine import SamplingParams

        handle = self._submit(
            self.tokenizer.encode(prompt),
            SamplingParams(max_tokens=max_tokens, temperature=temperature),
            slo_class=slo_class, tenant=tenant,
        )
        toks: list[int] = []
        emitted = ""
        try:
            for tok in handle.stream(timeout=self.GENERATION_TIMEOUT_S):
                toks.append(tok)
                text = self.tokenizer.decode(toks)
                # Hold back a trailing replacement char: it usually means a
                # multi-byte grapheme is split mid-token and the next token
                # will rewrite it.
                stable = text[:-1] if text.endswith("�") else text
                if len(stable) > len(emitted) and stable.startswith(emitted):
                    yield stable[len(emitted):]
                    emitted = stable
        except GeneratorExit:
            # Consumer abandoned the stream (client disconnect): stop the
            # engine from burning decode steps on a dead request.
            handle.cancel()
            raise
        # Final flush: emit whatever the full decode has beyond (or instead
        # of) what was streamed, so held-back or rewritten tails are never
        # silently dropped.
        if toks:
            text = self.tokenizer.decode(toks)
            if text != emitted:
                common = 0
                limit = min(len(text), len(emitted))
                while common < limit and text[common] == emitted[common]:
                    common += 1
                if common < len(text):
                    yield text[common:]
        res = handle.result(timeout=1.0)
        if res.finish_reason == "error":
            raise RuntimeError(f"generation failed: {res.error}")


class OpenAICompatBackend(LLMBackend):
    """Remote OpenAI-compatible chat endpoint (the reference's configured
    path, config.go:141-145). Kept for deployments that want it; the
    north-star path is LocalEngineBackend.

    Transient failures (HTTP 429/5xx, connection resets, timeouts) are
    retried with exponential backoff so one 502 doesn't fail a diagnosis
    outright; non-transient HTTP errors surface the response body in the
    raised error for debuggability.
    """

    name = "openai"
    max_retries = 3
    backoff_s = 0.5
    _RETRY_STATUS = {429, 500, 502, 503, 504}

    def __init__(self, cfg: LLMConfig) -> None:
        self.cfg = cfg
        if not cfg.base_url:
            raise ValueError("llm.base_url required for the openai provider")

    def _post(self, body: bytes):
        req = urllib.request.Request(
            self.cfg.base_url.rstrip("/") + "/chat/completions",
            data=body,
            headers={
                "Content-Type": "application/json",
                "Authorization": f"Bearer {self.cfg.api_key}",
            },
        )
        return urllib.request.urlopen(req, timeout=self.cfg.timeout)

    def generate(
        self, prompt: str, max_tokens: int = 512, temperature: float = 0.1,
        slo_class: str = "standard", tenant: str = "",
    ) -> str:
        # slo_class/tenant ignored: the remote endpoint has its own
        # admission and accounting.
        body = json.dumps(
            {
                "model": self.cfg.model,
                "messages": [{"role": "user", "content": prompt}],
                "max_tokens": max_tokens,
                "temperature": temperature,
            }
        ).encode()
        last_err: Exception | None = None
        for attempt in range(self.max_retries + 1):
            if attempt:
                time.sleep(self.backoff_s * (2 ** (attempt - 1)))
            try:
                with self._post(body) as resp:
                    raw = resp.read()
                try:
                    # The envelope (choices/usage) is protocol JSON, not
                    # model text; the text itself goes through the
                    # generate_constrained -> parse_verdict funnel.
                    data = json.loads(raw)  # graftcheck: disable=unconstrained-model-parse -- HTTP envelope
                except ValueError as exc:
                    # 200 + non-JSON body (an LB/proxy error page): as
                    # transient as a 502, and must not surface as a
                    # caller-side validation error.
                    raise urllib.error.URLError(
                        f"non-JSON response from LLM endpoint: "
                        f"{raw[:200]!r} ({exc})") from exc
                return data["choices"][0]["message"]["content"]
            except urllib.error.HTTPError as exc:
                detail = ""
                try:
                    detail = exc.read().decode(errors="replace")[:500]
                except Exception:  # noqa: BLE001
                    pass
                last_err = RuntimeError(
                    f"LLM endpoint returned {exc.code}: {detail or exc.reason}")
                if exc.code not in self._RETRY_STATUS:
                    raise last_err from exc
                logger.warning("LLM request failed (%s), attempt %d/%d",
                               exc.code, attempt + 1, self.max_retries + 1)
            except (urllib.error.URLError, TimeoutError, OSError,
                    http.client.HTTPException) as exc:
                # HTTPException covers mid-body failures (IncompleteRead,
                # RemoteDisconnected) that are not OSError subclasses.
                last_err = RuntimeError(f"LLM endpoint unreachable: {exc}")
                logger.warning("LLM request failed (%s), attempt %d/%d",
                               exc, attempt + 1, self.max_retries + 1)
        raise last_err  # type: ignore[misc]


def build_backend(cfg: LLMConfig,
                  lifecycle: LifecycleConfig | None = None,
                  tenancy=None) -> LLMBackend:
    if cfg.provider == "tpu":
        try:
            return LocalEngineBackend.from_config(cfg.tpu, lifecycle=lifecycle,
                                                  tenancy=tenancy)
        except Exception as exc:  # noqa: BLE001 — degrade, never fail boot
            logger.warning(
                "TPU backend unavailable (%s); falling back to template", exc
            )
            return TemplateBackend()
    if cfg.provider == "openai":
        try:
            return OpenAICompatBackend(cfg)
        except ValueError as exc:
            logger.warning("openai backend misconfigured (%s); using template", exc)
            return TemplateBackend()
    return TemplateBackend()


# ---------------------------------------------------------------------------
# evidence assembly
# ---------------------------------------------------------------------------


class EvidenceCollector:
    """Bounded cluster evidence → prompt sections.

    The bound is ``analysis.max_context_events`` (ref config.go:94) applied
    to the event stream; metric sections are already summaries.
    """

    def __init__(
        self,
        client: Client | None,
        manager: Manager | None,
        cfg: AnalysisConfig | None = None,
    ) -> None:
        self.client = client
        self.manager = manager
        self.cfg = cfg or AnalysisConfig()

    def collect(
        self,
        namespace: str | None = None,
        pod: str | None = None,
        include_logs: bool = False,
    ) -> dict[str, Any]:
        """Structured evidence dict; ``format_prompt`` renders it."""
        ev: dict[str, Any] = {"collected_at": utcnow().isoformat()}
        if self.manager is not None:
            snap = self.manager.get_latest_snapshot()
            if snap.cluster_metrics is not None:
                ev["cluster"] = to_jsonable(snap.cluster_metrics)
            ev["unhealthy_nodes"] = [
                {"node": n.node_name, "conditions": n.conditions,
                 "cpu_pct": round(n.cpu_usage_rate, 1),
                 "mem_pct": round(n.memory_usage_rate, 1)}
                for n in snap.node_metrics.values()
                if not n.healthy or n.is_under_pressure()
            ]
            ev["problem_pods"] = [
                {"pod": key, "phase": p.phase, "ready": p.ready,
                 "restarts": p.restarts,
                 "over_limit": p.is_over_limit()}
                for key, p in snap.pod_metrics.items()
                if p.phase != "Running" or not p.ready or p.is_over_limit()
                or p.restarts > 3
            ]
            ev["network_issues"] = [
                {"pair": f"{m.source_pod} -> {m.target_pod}",
                 "connected": m.connected, "rtt_ms": round(m.rtt_ms, 2),
                 "quality": m.quality(), "error": m.error}
                for m in snap.network_metrics
                if not m.connected or m.quality() in ("fair", "poor")
            ]
            uavs = self.manager.get_uav_metrics()
            low = []
            for node, entry in uavs.items():
                state = entry.get("state") or {}
                batt = state.get("battery", {}) if isinstance(state, dict) else {}
                pct = batt.get("remaining_percent")
                if pct is not None and pct < 20.0:
                    low.append({"node": node, "battery_pct": pct})
            if low:
                ev["low_battery_uavs"] = low
        if self.client is not None:
            events = []
            try:
                for ns in self.client.namespaces():
                    for e in self.client.get_events(
                        ns, limit=self.cfg.max_context_events
                    ):
                        events.append(
                            {"ns": ns, "type": e.type, "reason": e.reason,
                             "message": e.message, "count": e.count}
                        )
            except ClusterError as exc:
                logger.warning("event collection failed: %s", exc)
            warnings = [e for e in events if e["type"] == "Warning"]
            ev["recent_warning_events"] = warnings[-self.cfg.max_context_events :]
            if pod and namespace and include_logs:
                try:
                    ev["pod_logs"] = self.client.get_pod_logs(
                        namespace, pod, tail_lines=40
                    )
                except ClusterError as exc:
                    ev["pod_logs"] = f"<unavailable: {exc}>"
        return ev

    @staticmethod
    def format_prompt(evidence: dict[str, Any]) -> str:
        """Render evidence into the markdown-ish prompt body."""
        lines: list[str] = []
        cluster = evidence.get("cluster")
        if cluster:
            lines.append("## Cluster health")
            lines.append(
                f"status={cluster.get('health_status')} nodes="
                f"{cluster.get('healthy_nodes')}/{cluster.get('total_nodes')} "
                f"pods_running={cluster.get('running_pods')}/{cluster.get('total_pods')} "
                f"cpu={cluster.get('cpu_usage_rate', 0):.1f}% "
                f"mem={cluster.get('memory_usage_rate', 0):.1f}%"
            )
            for issue in cluster.get("issues", []) or []:
                lines.append(f"- {issue}")
        for key, title in (
            ("unhealthy_nodes", "Unhealthy nodes"),
            ("problem_pods", "Problem pods"),
            ("network_issues", "Network issues"),
            ("low_battery_uavs", "Low-battery UAVs"),
            ("recent_warning_events", "Recent warning events"),
        ):
            items = evidence.get(key)
            if items:
                lines.append(f"## {title}")
                for item in items:
                    lines.append(f"- {json.dumps(item, default=str)}")
        logs = evidence.get("pod_logs")
        if logs:
            lines.append("## Pod logs (tail)")
            lines.append(str(logs))
        if len(lines) == 0:
            lines.append("## Cluster health")
            lines.append("No evidence available (cluster unreachable or empty).")
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# the analysis engine
# ---------------------------------------------------------------------------

_SYSTEM_PREAMBLE = (
    "You are a Kubernetes SRE assistant analyzing live cluster monitoring "
    "evidence. Answer with a concise diagnosis and concrete remediation "
    "steps grounded ONLY in the evidence sections below.\n"
)


class AnalysisEngine:
    def __init__(
        self,
        backend: LLMBackend,
        client: Client | None = None,
        manager: Manager | None = None,
        cfg: AnalysisConfig | None = None,
        llm_cfg: LLMConfig | None = None,
        anomaly_detector=None,
    ) -> None:
        self.backend = backend
        self.client = client
        self.manager = manager
        self.cfg = cfg or AnalysisConfig()
        self.llm_cfg = llm_cfg or LLMConfig()
        self.evidence = EvidenceCollector(client, manager, self.cfg)
        # analysis.anomaly.EmbeddingAnomalyDetector (optional): adds
        # content-aware outlier detection over event text to the
        # thresholds-only anomaly signals.
        self.anomaly_detector = anomaly_detector
        # Multi-turn follow-up sessions (diagnosis/session.py); build_server
        # replaces this with one sized from config.diagnosis.
        self.sessions = SessionManager()

    # -- free-form NL question (the missing /api/v1/query) ---------------------

    def query(self, question: str, slo_class: str = "interactive",
              tenant: str = "") -> AnalysisResponse:
        request_id = uuid.uuid4().hex[:12]
        try:
            ev = self.evidence.collect()
            prompt = (
                _SYSTEM_PREAMBLE
                + self.evidence.format_prompt(ev)
                + f"\n## Question\n{question}\n## Answer\n"
            )
            answer = self.backend.generate(
                prompt,
                max_tokens=self.llm_cfg.max_tokens,
                temperature=self.llm_cfg.temperature,
                slo_class=slo_class,
                tenant=tenant,
            )
            return AnalysisResponse(
                request_id=request_id,
                status="success",
                result={
                    "answer": answer,
                    "model": self.backend.name,
                    "evidence": ev,
                },
            )
        except OverloadedError:
            # Admission-control pushback is not an internal failure: let it
            # propagate to the HTTP layer, which maps it to 429/503 with a
            # Retry-After hint and queue evidence.
            raise
        except Exception as exc:  # noqa: BLE001 — API boundary
            logger.exception("query failed")
            return AnalysisResponse(
                request_id=request_id,
                status="error",
                error=str(exc),
                error_kind="internal",
            )

    def query_stream(self, question: str, slo_class: str = "interactive",
                     tenant: str = ""):
        """Streaming variant of query(): returns (request_id, model_name,
        iterator of answer-text chunks).  Evidence collection happens up
        front (before the first chunk); generation streams from the backend
        as tokens come off the device (LocalEngineBackend) or as one chunk
        (backends without true streaming)."""
        request_id = uuid.uuid4().hex[:12]
        ev = self.evidence.collect()
        prompt = (
            _SYSTEM_PREAMBLE
            + self.evidence.format_prompt(ev)
            + f"\n## Question\n{question}\n## Answer\n"
        )
        chunks = self.backend.generate_stream(
            prompt,
            max_tokens=self.llm_cfg.max_tokens,
            temperature=self.llm_cfg.temperature,
            slo_class=slo_class,
            tenant=tenant,
        )
        return request_id, self.backend.name, chunks

    def query_session(self, question: str, session_id: str = "",
                      slo_class: str = "interactive",
                      tenant: str = "") -> AnalysisResponse:
        """Multi-turn variant of ``query``: the cluster context is frozen
        at session creation and replayed verbatim as the prompt prefix on
        every follow-up, so the engine's PrefixCache (and fleet prefix
        affinity) serve the shared context instead of re-prefilling it.
        An empty ``session_id`` mints a new session; the id comes back in
        the result for the next turn."""
        request_id = uuid.uuid4().hex[:12]
        try:
            session, created = self.sessions.get_or_create(
                session_id,
                lambda: self.evidence.format_prompt(
                    self.evidence.collect()) + "\n",
            )
            prompt = session.build_prompt(_SYSTEM_PREAMBLE, question)
            answer = self.backend.generate(
                prompt,
                max_tokens=self.llm_cfg.max_tokens,
                temperature=self.llm_cfg.temperature,
                slo_class=slo_class,
                tenant=tenant,
            )
            session.record(question, answer)
            return AnalysisResponse(
                request_id=request_id,
                status="success",
                result={
                    "answer": answer,
                    "model": self.backend.name,
                    "session_id": session.session_id,
                    "session_created": created,
                    "turn": len(session.turns),
                },
            )
        except OverloadedError:
            raise  # mapped to 429/503 + Retry-After at the HTTP layer
        except Exception as exc:  # noqa: BLE001 — API boundary
            logger.exception("session query failed")
            return AnalysisResponse(
                request_id=request_id,
                status="error",
                error=str(exc),
                error_kind="internal",
            )

    # -- grammar-constrained verdicts -------------------------------------------

    def diagnose(self, question: str, context: str | None = None,
                 slo_class: str = "standard",
                 tenant: str = "") -> dict[str, Any]:
        """One grammar-constrained root-cause verdict as a parsed dict.

        The contract callers (pipeline, ``_analyze_root_cause``) rely on:
        the return value ALWAYS matches ``diagnosis.grammar.VERDICT_SCHEMA``
        — keys severity/component/root_cause/recommendation/confidence.
        ``context`` is pre-rendered evidence text (the pipeline passes its
        assembled burst context); when omitted, live cluster evidence is
        collected.
        """
        if context is None:
            context = self.evidence.format_prompt(self.evidence.collect())
        prompt = (
            _SYSTEM_PREAMBLE
            + context
            + f"\n## Question\n{question}\n"
            "## Verdict\nRespond with exactly one JSON object with keys "
            "severity, component, root_cause, recommendation, confidence:\n"
        )
        text = self.backend.generate_constrained(
            prompt, temperature=self.llm_cfg.temperature,
            slo_class=slo_class, tenant=tenant)
        try:
            return parse_verdict(text)
        except GrammarError as exc:
            # Defense in depth: the FSM makes this unreachable for the
            # constrained engine path, but a misbehaving custom backend
            # must not break the always-parses contract.
            logger.warning("backend emitted grammar-invalid verdict: %s", exc)
            return parse_verdict(render_verdict(
                "warning", "cluster", text,
                "re-run the diagnosis", 0.2))

    # -- typed analyses (ref pkg/models/models.go:85-99) ------------------------

    def analyze(self, request: AnalysisRequest,
                tenant: str = "") -> AnalysisResponse:
        request_id = uuid.uuid4().hex[:12]
        if request.type not in ANALYSIS_TYPES:
            return AnalysisResponse(
                request_id=request_id,
                status="error",
                error=f"unknown analysis type {request.type!r}; "
                f"expected one of {list(ANALYSIS_TYPES)}",
                error_kind="validation",
            )
        try:
            handler = {
                "pod_communication": self._analyze_pod_communication,
                "anomaly_detection": self._analyze_anomalies,
                "root_cause": self._analyze_root_cause,
            }[request.type]
            result = handler(request.parameters or {}, tenant)
            return AnalysisResponse(
                request_id=request_id, status="success", result=result
            )
        except ValueError as exc:  # bad parameters from the caller
            return AnalysisResponse(
                request_id=request_id,
                status="error",
                error=str(exc),
                error_kind="validation",
            )
        except OverloadedError:
            raise  # mapped to 429/503 + Retry-After at the HTTP layer
        except Exception as exc:  # noqa: BLE001 — API boundary
            logger.exception("analysis %s failed", request.type)
            return AnalysisResponse(
                request_id=request_id,
                status="error",
                error=str(exc),
                error_kind="internal",
            )

    def _analyze_pod_communication(self, params: dict[str, Any],
                                   tenant: str = "") -> dict[str, Any]:
        pod_a = params.get("pod_a", "")
        pod_b = params.get("pod_b", "")
        if not pod_a or not pod_b:
            raise ValueError("pod_a and pod_b are required")
        if self.client is None:
            raise ClusterError("cluster client unavailable")
        analysis = NetworkAnalyzer(self.client).analyze_pod_communication(pod_a, pod_b)
        findings = "\n".join(f"- {i}" for i in analysis.issues) or "- no issues found"
        prompt = (
            _SYSTEM_PREAMBLE
            + f"## Pod communication check {pod_a} -> {pod_b}\n"
            + f"status={analysis.status} confidence={analysis.confidence}\n"
            + f"## Findings\n{findings}\n"
            + "## Question\nExplain the most likely root cause of any "
            "communication problem between these pods and how to fix it.\n"
            "## Answer\n"
        )
        diagnosis = self.backend.generate(
            prompt, max_tokens=self.llm_cfg.max_tokens,
            temperature=self.llm_cfg.temperature,
            tenant=tenant,
        )
        return {
            "analysis": to_jsonable(analysis),
            "llm_diagnosis": diagnosis,
            "model": self.backend.name,
        }

    def _analyze_anomalies(self, params: dict[str, Any],
                           tenant: str = "") -> dict[str, Any]:
        ev = self.evidence.collect()
        anomalies: list[str] = []
        anomalies += [
            f"node {n['node']} unhealthy/pressured (cpu {n['cpu_pct']}%, "
            f"mem {n['mem_pct']}%, conditions {n['conditions']})"
            for n in ev.get("unhealthy_nodes", [])
        ]
        anomalies += [
            f"pod {p['pod']} {p['phase']} ready={p['ready']} "
            f"restarts={p['restarts']} over_limit={p['over_limit']}"
            for p in ev.get("problem_pods", [])
        ]
        anomalies += [
            f"network {m['pair']}: connected={m['connected']} "
            f"quality={m['quality']}"
            for m in ev.get("network_issues", [])
        ]
        anomalies += [
            f"UAV on {u['node']} battery {u['battery_pct']}%"
            for u in ev.get("low_battery_uavs", [])
        ]
        embedding_outliers: list[dict[str, Any]] = []
        if self.anomaly_detector is not None:
            events = ev.get("recent_warning_events", [])
            texts = [f"{e.get('reason', '')}: {e.get('message', '')}"
                     for e in events]
            try:
                for idx, score in self.anomaly_detector.flag_outliers(texts):
                    embedding_outliers.append(
                        {"event": texts[idx], "score": round(score, 4)})
                    anomalies.append(
                        f"semantic outlier event (score {score:.2f}): "
                        f"{texts[idx]}")
            except Exception as exc:  # noqa: BLE001 — detector is best-effort
                logger.warning("embedding anomaly scoring failed: %s", exc)
        prompt = (
            _SYSTEM_PREAMBLE
            + self.evidence.format_prompt(ev)
            + "\n## Question\nSummarize the anomalies, rank them by severity, "
            "and recommend the first remediation step for each.\n## Answer\n"
        )
        summary = self.backend.generate(
            prompt, max_tokens=self.llm_cfg.max_tokens,
            temperature=self.llm_cfg.temperature,
            tenant=tenant,
        )
        return {
            "anomalies": anomalies,
            "anomaly_count": len(anomalies),
            "embedding_outliers": embedding_outliers,
            "llm_summary": summary,
            "model": self.backend.name,
        }

    def _analyze_root_cause(self, params: dict[str, Any],
                            tenant: str = "") -> dict[str, Any]:
        namespace = params.get("namespace", "default")
        pod = params.get("pod", "")
        symptom = params.get("symptom", "") or params.get("question", "")
        ev = self.evidence.collect(
            namespace=namespace, pod=pod or None, include_logs=bool(pod)
        )
        target = f"pod {namespace}/{pod}" if pod else "the cluster"
        prompt = (
            _SYSTEM_PREAMBLE
            + self.evidence.format_prompt(ev)
            + f"\n## Question\nPerform a root-cause analysis for {target}."
            + (f" Reported symptom: {symptom}." if symptom else "")
            + " Identify the most probable cause chain and the fix.\n## Answer\n"
        )
        answer = self.backend.generate(
            prompt, max_tokens=self.llm_cfg.max_tokens,
            temperature=self.llm_cfg.temperature,
            tenant=tenant,
        )
        verdict = self.diagnose(
            f"Root-cause analysis for {target}."
            + (f" Reported symptom: {symptom}." if symptom else ""),
            context=self.evidence.format_prompt(ev),
            tenant=tenant,
        )
        return {
            "target": target,
            "root_cause_analysis": answer,
            "verdict": verdict,
            "evidence": ev,
            "model": self.backend.name,
        }
